"""Slow wrapper for the multi-replica fleet chaos soak (ISSUE 7
acceptance). Excluded from tier-1 by the `slow` marker (pytest.ini
addopts runs `-m "not slow"` by default); run it with `make soak-fleet`
or `pytest tests/test_soak_fleet.py -m slow`."""
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


@pytest.mark.slow
def test_soak_fleet_120_requests_kill_and_stall():
    from tools import soak_fleet
    assert soak_fleet.main(["--requests", "120", "--seed", "0"]) == 0


@pytest.mark.slow
@pytest.mark.parametrize("seed", [1, 2])
def test_soak_fleet_other_seeds(seed):
    from tools import soak_fleet
    assert soak_fleet.main(["--requests", "60", "--seed", str(seed)]) == 0
