"""Sparse tensors (COO/CSR).

Parity: reference sparse stack — `phi::SparseCooTensor`/`SparseCsrTensor`
(`paddle/phi/core/sparse_coo_tensor.h`), kernels in `paddle/phi/kernels/
sparse/` (~60 interfaces), python API `python/paddle/sparse/`.

TPU-native: backed by jax.experimental.sparse BCOO/BCSR — XLA lowers
sparse matmul to gather+MXU matmul; elementwise unary ops run on the
values buffer only (same trick the reference's sparse kernels use).
Autograd: value buffers participate through apply_op like any dense op.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from ..core.tensor import Tensor
from ..ops.dispatch import apply_op

__all__ = ["sparse_coo_tensor", "sparse_csr_tensor", "SparseCooTensor",
           "SparseCsrTensor", "is_same_shape", "matmul", "masked_matmul",
           "add", "multiply", "relu", "sin", "tanh", "sqrt", "abs",
           "to_dense", "to_sparse_coo", "nn"]


class SparseCooTensor:
    """COO sparse tensor wrapper (indices (ndim, nnz), values (nnz, ...)).

    Parity: paddle.sparse.sparse_coo_tensor / phi SparseCooTensor."""

    def __init__(self, bcoo):
        self._bcoo = bcoo

    # -- paddle tensor-ish surface ---------------------------------------
    @property
    def shape(self):
        return list(self._bcoo.shape)

    @property
    def dtype(self):
        return self._bcoo.dtype

    @property
    def nnz(self):
        return int(self._bcoo.nse)

    def indices(self):
        return Tensor(self._bcoo.indices.T)  # paddle layout (ndim, nnz)

    def values(self):
        return Tensor(self._bcoo.data)

    def to_dense(self):
        return Tensor(self._bcoo.todense())

    def to_sparse_csr(self):
        bcsr = jsparse.BCSR.from_bcoo(self._bcoo)
        return SparseCsrTensor(bcsr)

    def coalesce(self):
        return SparseCooTensor(self._bcoo.sum_duplicates())

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz}, "
                f"dtype={self.dtype})")


class SparseCsrTensor:
    """CSR sparse tensor wrapper. Parity: paddle.sparse.sparse_csr_tensor."""

    def __init__(self, bcsr):
        self._bcsr = bcsr

    @property
    def shape(self):
        return list(self._bcsr.shape)

    @property
    def dtype(self):
        return self._bcsr.dtype

    @property
    def nnz(self):
        return int(self._bcsr.nse)

    def crows(self):
        return Tensor(self._bcsr.indptr)

    def cols(self):
        return Tensor(self._bcsr.indices)

    def values(self):
        return Tensor(self._bcsr.data)

    def to_dense(self):
        return Tensor(self._bcsr.todense())

    def to_sparse_coo(self, sparse_dim=None):
        return SparseCooTensor(self._bcsr.to_bcoo())

    def __repr__(self):
        return (f"SparseCsrTensor(shape={self.shape}, nnz={self.nnz}, "
                f"dtype={self.dtype})")


def _data(x):
    if isinstance(x, Tensor):
        return x._data
    return jnp.asarray(x)


def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      place=None, stop_gradient=True):
    """indices: (ndim, nnz); values: (nnz,). Parity: paddle.sparse.
    sparse_coo_tensor."""
    idx = _data(indices).T.astype(jnp.int32)       # BCOO wants (nnz, ndim)
    val = _data(values)
    if dtype is not None:
        from ..core.dtype import convert_dtype
        val = val.astype(convert_dtype(dtype))
    if shape is None:
        shape = tuple(int(i) + 1 for i in idx.max(axis=0))
    bcoo = jsparse.BCOO((val, idx), shape=tuple(shape))
    return SparseCooTensor(bcoo)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None,
                      place=None, stop_gradient=True):
    """Parity: paddle.sparse.sparse_csr_tensor."""
    val = _data(values)
    if dtype is not None:
        from ..core.dtype import convert_dtype
        val = val.astype(convert_dtype(dtype))
    bcsr = jsparse.BCSR(
        (val, _data(cols).astype(jnp.int32),
         _data(crows).astype(jnp.int32)), shape=tuple(shape))
    return SparseCsrTensor(bcsr)


def is_same_shape(x, y):
    return list(x.shape) == list(y.shape)


def to_dense(x):
    return x.to_dense()


def to_sparse_coo(x, sparse_dim=None):
    if isinstance(x, SparseCsrTensor):
        return x.to_sparse_coo(sparse_dim)
    d = _data(x)
    return SparseCooTensor(jsparse.BCOO.fromdense(d))


# -- ops --------------------------------------------------------------------

def _unary_on_values(name, fn):
    """Elementwise op applied to the values buffer (zero-preserving ops
    only — the reference's sparse unary kernels share this contract)."""
    def op(x, name_arg=None):
        if isinstance(x, SparseCooTensor):
            new_vals = apply_op(name, fn, Tensor(x._bcoo.data))
            return SparseCooTensor(
                jsparse.BCOO((new_vals._data, x._bcoo.indices),
                             shape=x._bcoo.shape))
        if isinstance(x, SparseCsrTensor):
            new_vals = apply_op(name, fn, Tensor(x._bcsr.data))
            return SparseCsrTensor(
                jsparse.BCSR((new_vals._data, x._bcsr.indices,
                              x._bcsr.indptr), shape=x._bcsr.shape))
        return apply_op(name, fn, x)
    op.__name__ = name
    return op


relu = _unary_on_values("sparse_relu", lambda v: jnp.maximum(v, 0))
sin = _unary_on_values("sparse_sin", jnp.sin)
tanh = _unary_on_values("sparse_tanh", jnp.tanh)
sqrt = _unary_on_values("sparse_sqrt", jnp.sqrt)
abs = _unary_on_values("sparse_abs", jnp.abs)


def matmul(x, y, name=None):
    """sparse @ dense -> dense. Parity: paddle.sparse.matmul."""
    if isinstance(x, SparseCsrTensor):
        x = x.to_sparse_coo()
    if isinstance(x, SparseCooTensor):
        yb = _data(y)
        out = jsparse.bcoo_dot_general(
            x._bcoo, yb,
            dimension_numbers=(([x._bcoo.ndim - 1], [0]), ([], [])))
        return Tensor(out)
    return apply_op("matmul", jnp.matmul, x, y)


def masked_matmul(x, y, mask, name=None):
    """(dense @ dense) * sparse_mask -> sparse (SDDMM).
    Parity: paddle.sparse.masked_matmul."""
    xd, yd = _data(x), _data(y)
    if isinstance(mask, SparseCsrTensor):
        coo = mask.to_sparse_coo()
        out_coo = _sddmm(xd, yd, coo)
        return out_coo.to_sparse_csr()
    return _sddmm(xd, yd, mask)


def _sddmm(xd, yd, mask: SparseCooTensor):
    idx = mask._bcoo.indices  # (nnz, 2)
    rows, cols = idx[:, 0], idx[:, 1]
    vals = jnp.einsum("nk,nk->n", xd[rows, :], yd[:, cols].T)
    return SparseCooTensor(jsparse.BCOO((vals, idx), shape=mask._bcoo.shape))


def add(x, y, name=None):
    if isinstance(x, SparseCooTensor) and isinstance(y, SparseCooTensor):
        idx = jnp.concatenate([x._bcoo.indices, y._bcoo.indices], axis=0)
        val = jnp.concatenate([x._bcoo.data, y._bcoo.data], axis=0)
        out = jsparse.BCOO((val, idx), shape=x._bcoo.shape).sum_duplicates()
        return SparseCooTensor(out)
    raise TypeError("sparse.add expects two SparseCooTensors")


def multiply(x, y, name=None):
    if isinstance(x, SparseCooTensor) and isinstance(y, SparseCooTensor):
        # elementwise product via dense path (reference kernels do a merge;
        # nnz here is test-scale)
        out = x._bcoo.todense() * y._bcoo.todense()
        return SparseCooTensor(jsparse.BCOO.fromdense(out))
    raise TypeError("sparse.multiply expects two SparseCooTensors")


# -- sparse.nn --------------------------------------------------------------

class _SparseNN:
    """paddle.sparse.nn namespace (ReLU layer parity)."""

    class ReLU:
        def __call__(self, x):
            return relu(x)

    class Softmax:
        """Row-wise softmax over CSR values. Parity:
        paddle.sparse.nn.Softmax (csr softmax kernel)."""

        def __init__(self, axis=-1):
            self.axis = axis

        def __call__(self, x: SparseCsrTensor):
            indptr = x._bcsr.indptr
            vals = x._bcsr.data
            n_rows = x.shape[0]
            row_id = jnp.searchsorted(indptr, jnp.arange(vals.shape[0]),
                                      side="right") - 1
            row_max = jax.ops.segment_max(vals, row_id, n_rows)
            ex = jnp.exp(vals - row_max[row_id])
            row_sum = jax.ops.segment_sum(ex, row_id, n_rows)
            out = ex / row_sum[row_id]
            return SparseCsrTensor(jsparse.BCSR(
                (out, x._bcsr.indices, x._bcsr.indptr), shape=x._bcsr.shape))


nn = _SparseNN()
