"""Sparse tensors (COO/CSR) + the sparse op set.

Parity: reference sparse stack — `phi::SparseCooTensor`/`SparseCsrTensor`
(`paddle/phi/core/sparse_coo_tensor.h`), kernels in `paddle/phi/kernels/
sparse/` (~60 interfaces), python API `python/paddle/sparse/` (creation,
unary.py, binary.py, multiary.py, nn/).

TPU-native: backed by jax.experimental.sparse BCOO/BCSR — XLA lowers
sparse matmul to gather+MXU matmul; elementwise unary ops run on the
values buffer only (same trick the reference's sparse kernels use).
Autograd: value buffers participate through apply_op like any dense op.
Ops whose output sparsity pattern is data-dependent (conv, pooling,
reshape/slice re-sparsification) are eager-only, the same restriction the
reference's sparse kernels have under static shape inference.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ..core.tensor import Tensor
from ..ops.dispatch import apply_op

__all__ = [
    "sparse_coo_tensor", "sparse_csr_tensor", "SparseCooTensor",
    "SparseCsrTensor", "is_same_shape", "matmul", "masked_matmul", "mv",
    "addmm", "add", "subtract", "multiply", "divide", "mask_as",
    "relu", "relu6", "leaky_relu", "sin", "tan", "asin", "atan", "sinh",
    "asinh", "atanh", "tanh", "square", "sqrt", "log1p", "abs", "pow",
    "neg", "expm1", "rad2deg", "deg2rad", "isnan", "cast", "coalesce",
    "transpose", "reshape", "sum", "slice", "to_dense", "to_sparse_coo",
    "pca_lowrank",
    "nn",
]


class SparseCooTensor:
    """COO sparse tensor wrapper (indices (ndim, nnz), values (nnz, ...)).

    Parity: paddle.sparse.sparse_coo_tensor / phi SparseCooTensor."""

    def __init__(self, bcoo):
        self._bcoo = bcoo

    # -- paddle tensor-ish surface ---------------------------------------
    @property
    def shape(self):
        return list(self._bcoo.shape)

    @property
    def dtype(self):
        return self._bcoo.dtype

    @property
    def nnz(self):
        return int(self._bcoo.nse)

    def indices(self):
        return Tensor(self._bcoo.indices.T)  # paddle layout (ndim, nnz)

    def values(self):
        # the tape-connected values Tensor when this sparse tensor was
        # produced by a differentiable op (autograd flows through values
        # buffers, like the reference's sparse grad kernels)
        vt = getattr(self, "_vals_t", None)
        return vt if vt is not None else Tensor(self._bcoo.data)

    def to_dense(self):
        return Tensor(self._bcoo.todense())

    def to_sparse_csr(self):
        bcsr = jsparse.BCSR.from_bcoo(self._bcoo)
        return SparseCsrTensor(bcsr)

    def coalesce(self):
        return SparseCooTensor(self._bcoo.sum_duplicates())

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz}, "
                f"dtype={self.dtype})")


class SparseCsrTensor:
    """CSR sparse tensor wrapper. Parity: paddle.sparse.sparse_csr_tensor."""

    def __init__(self, bcsr):
        self._bcsr = bcsr

    @property
    def shape(self):
        return list(self._bcsr.shape)

    @property
    def dtype(self):
        return self._bcsr.dtype

    @property
    def nnz(self):
        return int(self._bcsr.nse)

    def crows(self):
        return Tensor(self._bcsr.indptr)

    def cols(self):
        return Tensor(self._bcsr.indices)

    def values(self):
        vt = getattr(self, "_vals_t", None)
        return vt if vt is not None else Tensor(self._bcsr.data)

    def to_dense(self):
        return Tensor(self._bcsr.todense())

    def to_sparse_coo(self, sparse_dim=None):
        return SparseCooTensor(self._bcsr.to_bcoo())

    def __repr__(self):
        return (f"SparseCsrTensor(shape={self.shape}, nnz={self.nnz}, "
                f"dtype={self.dtype})")


def _data(x):
    if isinstance(x, Tensor):
        return x._data
    return jnp.asarray(x)


def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      place=None, stop_gradient=True):
    """indices: (ndim, nnz); values: (nnz,). Parity: paddle.sparse.
    sparse_coo_tensor."""
    idx = _data(indices).T.astype(jnp.int32)       # BCOO wants (nnz, ndim)
    val = _data(values)
    if dtype is not None:
        from ..core.dtype import convert_dtype
        val = val.astype(convert_dtype(dtype))
    if shape is None:
        shape = tuple(int(i) + 1 for i in idx.max(axis=0))
    bcoo = jsparse.BCOO((val, idx), shape=tuple(shape))
    out = SparseCooTensor(bcoo)
    if isinstance(values, Tensor) and val is values._data:
        out._vals_t = values                       # keep the tape link
    return out


def sparse_csr_tensor(crows, cols, values, shape, dtype=None,
                      place=None, stop_gradient=True):
    """Parity: paddle.sparse.sparse_csr_tensor."""
    val = _data(values)
    if dtype is not None:
        from ..core.dtype import convert_dtype
        val = val.astype(convert_dtype(dtype))
    cr = _data(crows).astype(jnp.int32)
    cl = _data(cols).astype(jnp.int32)
    if len(shape) == 3 and cr.ndim == 1:
        # batched CSR with reference-style flat buffers: crows is
        # batch-concatenated (B*(rows+1),) — reshape to BCSR's batch form
        B = int(shape[0])
        cr = cr.reshape(B, int(shape[1]) + 1)
        cl = cl.reshape(B, -1)
        val = val.reshape(B, -1, *val.shape[1:]) if val.ndim == 1 \
            else val.reshape(B, -1, *val.shape[2:])
    bcsr = jsparse.BCSR((val, cl, cr), shape=tuple(shape))
    return SparseCsrTensor(bcsr)


def is_same_shape(x, y):
    return list(x.shape) == list(y.shape)


def to_dense(x):
    return x.to_dense()


def to_sparse_coo(x, sparse_dim=None):
    if isinstance(x, SparseCsrTensor):
        return x.to_sparse_coo(sparse_dim)
    d = _data(x)
    return SparseCooTensor(jsparse.BCOO.fromdense(d))


def _rebuild_coo(x: SparseCooTensor, new_vals, shape=None):
    """new_vals: a Tensor (tape-connected) or raw array."""
    vt = new_vals if isinstance(new_vals, Tensor) else None
    arr = new_vals._data if vt is not None else new_vals
    out = SparseCooTensor(jsparse.BCOO((arr, x._bcoo.indices),
                                       shape=shape or x._bcoo.shape))
    out._vals_t = vt
    return out


def _rebuild_csr(x: SparseCsrTensor, new_vals, shape=None):
    vt = new_vals if isinstance(new_vals, Tensor) else None
    arr = new_vals._data if vt is not None else new_vals
    out = SparseCsrTensor(jsparse.BCSR(
        (arr, x._bcsr.indices, x._bcsr.indptr),
        shape=shape or x._bcsr.shape))
    out._vals_t = vt
    return out


# -- unary ops (values-buffer only; parity: sparse/unary.py) ----------------

def _unary_on_values(name, fn):
    """Elementwise op applied to the values buffer (zero-preserving ops
    only — the reference's sparse unary kernels share this contract)."""
    def op(x, name_arg=None):
        if isinstance(x, SparseCooTensor):
            new_vals = apply_op(name, fn, x.values())
            return _rebuild_coo(x, new_vals)
        if isinstance(x, SparseCsrTensor):
            new_vals = apply_op(name, fn, x.values())
            return _rebuild_csr(x, new_vals)
        return apply_op(name, fn, x)
    op.__name__ = name
    return op


relu = _unary_on_values("sparse_relu", lambda v: jnp.maximum(v, 0))
relu6 = _unary_on_values("sparse_relu6", lambda v: jnp.clip(v, 0, 6))
sin = _unary_on_values("sparse_sin", jnp.sin)
tan = _unary_on_values("sparse_tan", jnp.tan)
asin = _unary_on_values("sparse_asin", jnp.arcsin)
atan = _unary_on_values("sparse_atan", jnp.arctan)
sinh = _unary_on_values("sparse_sinh", jnp.sinh)
asinh = _unary_on_values("sparse_asinh", jnp.arcsinh)
atanh = _unary_on_values("sparse_atanh", jnp.arctanh)
tanh = _unary_on_values("sparse_tanh", jnp.tanh)
square = _unary_on_values("sparse_square", jnp.square)
sqrt = _unary_on_values("sparse_sqrt", jnp.sqrt)
log1p = _unary_on_values("sparse_log1p", jnp.log1p)
abs = _unary_on_values("sparse_abs", jnp.abs)
neg = _unary_on_values("sparse_neg", jnp.negative)
expm1 = _unary_on_values("sparse_expm1", jnp.expm1)
rad2deg = _unary_on_values("sparse_rad2deg",
                           lambda v: v * np.float32(180.0 / math.pi))
deg2rad = _unary_on_values("sparse_deg2rad",
                           lambda v: v * np.float32(math.pi / 180.0))
isnan = _unary_on_values("sparse_isnan", jnp.isnan)


def leaky_relu(x, negative_slope=0.01, name=None):
    return _unary_on_values(
        "sparse_leaky_relu",
        lambda v: jnp.where(v >= 0, v, v * negative_slope))(x)


def pow(x, factor, name=None):
    return _unary_on_values("sparse_pow", lambda v: jnp.power(v, factor))(x)


def cast(x, index_dtype=None, value_dtype=None, name=None):
    from ..core.dtype import convert_dtype
    vd = convert_dtype(value_dtype) if value_dtype is not None else None
    idd = convert_dtype(index_dtype) if index_dtype is not None else None
    if isinstance(x, SparseCooTensor):
        vals_t = apply_op("sparse_cast",
                          lambda v: v.astype(vd) if vd else v, x.values())
        idx = x._bcoo.indices.astype(idd) if idd else x._bcoo.indices
        out = SparseCooTensor(jsparse.BCOO((vals_t._data, idx),
                                           shape=x._bcoo.shape))
        out._vals_t = vals_t
        return out
    vals_t = apply_op("sparse_cast",
                      lambda v: v.astype(vd) if vd else v, x.values())
    cols = x._bcsr.indices.astype(idd) if idd else x._bcsr.indices
    crows = x._bcsr.indptr.astype(idd) if idd else x._bcsr.indptr
    out = SparseCsrTensor(jsparse.BCSR((vals_t._data, cols, crows),
                                       shape=x._bcsr.shape))
    out._vals_t = vals_t
    return out


def coalesce(x, name=None):
    return x.coalesce()


def transpose(x, perm, name=None):
    """Permute sparse dims by reordering COO index columns (no data copy
    of a dense tensor — parity: sparse transpose_kernel)."""
    coo = x.to_sparse_coo() if isinstance(x, SparseCsrTensor) else x
    idx = coo._bcoo.indices[:, jnp.asarray(perm, jnp.int32)]
    shape = tuple(coo._bcoo.shape[p] for p in perm)
    out = SparseCooTensor(jsparse.BCOO((coo._bcoo.data, idx), shape=shape))
    out._vals_t = getattr(coo, "_vals_t", None)   # values unchanged
    return out.to_sparse_csr() if isinstance(x, SparseCsrTensor) else out


def reshape(x, shape, name=None):
    """Re-linearize COO coordinates into the new shape (index arithmetic
    only). -1 wildcard supported."""
    coo = x.to_sparse_coo() if isinstance(x, SparseCsrTensor) else x
    old_shape = coo._bcoo.shape
    size = int(np.prod(old_shape))
    shape = list(shape)
    if -1 in shape:
        known = int(np.prod([s for s in shape if s != -1]))
        shape[shape.index(-1)] = size // known
    lin = jnp.zeros(coo._bcoo.indices.shape[0], jnp.int64)
    for d, s in enumerate(old_shape):
        lin = lin * s + coo._bcoo.indices[:, d].astype(jnp.int64)
    new_idx = []
    for s in reversed(shape):
        new_idx.append(lin % s)
        lin = lin // s
    idx = jnp.stack(list(reversed(new_idx)), axis=1).astype(jnp.int32)
    out = SparseCooTensor(jsparse.BCOO((coo._bcoo.data, idx),
                                       shape=tuple(shape)))
    out._vals_t = getattr(coo, "_vals_t", None)   # values unchanged
    return out.to_sparse_csr() if isinstance(x, SparseCsrTensor) else out


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    """Sparse reduction. axis=None returns a dense 0-d Tensor; an int axis
    returns a sparse tensor (computed by re-bucketing coordinates)."""
    coo = x.to_sparse_coo() if isinstance(x, SparseCsrTensor) else x
    if axis is None:
        out = apply_op("sparse_sum", jnp.sum, Tensor(coo._bcoo.data))
        return out
    nd = len(coo._bcoo.shape)
    axis = axis % nd
    keep = [d for d in range(nd) if d != axis]
    idx_np = np.asarray(coo._bcoo.indices)[:, keep]
    shape = tuple(coo._bcoo.shape[d] for d in keep)
    uidx, inv = np.unique(idx_np, axis=0, return_inverse=True)
    inv = jnp.asarray(inv.reshape(-1))
    n_out = uidx.shape[0]
    vals = apply_op("sparse_sum",
                    lambda v: jax.ops.segment_sum(v, inv, n_out),
                    coo.values())
    if keepdim:
        uidx = np.insert(uidx, axis, 0, axis=1)
        shape = tuple(coo._bcoo.shape[d] if d != axis else 1
                      for d in range(nd))
    out = SparseCooTensor(jsparse.BCOO(
        (vals._data, jnp.asarray(uidx.astype(np.int32))), shape=shape))
    out._vals_t = vals
    # CSR needs rank >= 2; a rank-1 reduction result stays COO
    if isinstance(x, SparseCsrTensor) and len(shape) >= 2:
        return out.to_sparse_csr()
    return out


def slice(x, axes, starts, ends, name=None):
    """Slice by filtering coordinates (eager-only: output nnz is
    data-dependent, as in the reference's sparse slice kernel)."""
    coo = x.to_sparse_coo() if isinstance(x, SparseCsrTensor) else x
    idx = np.asarray(coo._bcoo.indices)
    shape = list(coo._bcoo.shape)
    mask = np.ones(idx.shape[0], bool)
    for ax, st, en in zip(axes, starts, ends):
        ax = ax % len(shape)
        st = st + shape[ax] if st < 0 else st
        en = min(en + shape[ax] if en < 0 else en, shape[ax])
        mask &= (idx[:, ax] >= st) & (idx[:, ax] < en)
        shape[ax] = en - st
    sel = np.where(mask)[0]
    new_idx = idx[sel].copy()
    for ax, st, _ in zip(axes, starts, ends):
        ax = ax % len(coo._bcoo.shape)
        st = st + coo._bcoo.shape[ax] if st < 0 else st
        new_idx[:, ax] -= st
    sel_j = jnp.asarray(sel)
    vals_t = apply_op("sparse_slice", lambda v: v[sel_j], coo.values())
    out = SparseCooTensor(jsparse.BCOO(
        (vals_t._data, jnp.asarray(new_idx)), shape=tuple(shape)))
    out._vals_t = vals_t
    return out.to_sparse_csr() if isinstance(x, SparseCsrTensor) else out


# -- binary / multiary (parity: sparse/binary.py, multiary.py) --------------

def matmul(x, y, name=None):
    """sparse @ dense -> dense. Parity: paddle.sparse.matmul."""
    if isinstance(x, SparseCsrTensor):
        x = x.to_sparse_coo()
    if isinstance(x, SparseCooTensor):
        yb = _data(y)
        out = jsparse.bcoo_dot_general(
            x._bcoo, yb,
            dimension_numbers=(([x._bcoo.ndim - 1], [0]), ([], [])))
        return Tensor(out)
    return apply_op("matmul", jnp.matmul, x, y)


def mv(x, vec, name=None):
    """sparse (M, N) @ dense vector (N,) -> dense (M,).
    Parity: paddle.sparse.mv."""
    return matmul(x, vec, name=name)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    """beta * input + alpha * (x @ y). Parity: paddle.sparse.addmm
    (multiary.py)."""
    prod = matmul(x, y)
    inp = input.to_dense() if isinstance(
        input, (SparseCooTensor, SparseCsrTensor)) else input
    from ..ops import math as _m
    return _m.add(_m.scale(inp, beta), _m.scale(prod, alpha))


def masked_matmul(x, y, mask, name=None):
    """(dense @ dense) * sparse_mask -> sparse (SDDMM).
    Parity: paddle.sparse.masked_matmul."""
    xd, yd = _data(x), _data(y)
    if isinstance(mask, SparseCsrTensor):
        coo = mask.to_sparse_coo()
        out_coo = _sddmm(xd, yd, coo)
        return out_coo.to_sparse_csr()
    return _sddmm(xd, yd, mask)


def _sddmm(xd, yd, mask: SparseCooTensor):
    idx = mask._bcoo.indices  # (nnz, 2)
    rows, cols = idx[:, 0], idx[:, 1]
    vals = jnp.einsum("nk,nk->n", xd[rows, :], yd[:, cols].T)
    return SparseCooTensor(jsparse.BCOO((vals, idx), shape=mask._bcoo.shape))


def mask_as(x, mask, name=None):
    """Gather the dense tensor's entries at the mask's sparsity pattern.
    Parity: paddle.sparse.mask_as (sparse_mask kernel)."""
    coo = mask.to_sparse_coo() if isinstance(mask, SparseCsrTensor) else mask
    idx = coo._bcoo.indices
    vals_t = apply_op(
        "sparse_mask_as",
        lambda d: d[tuple(idx[:, i] for i in range(idx.shape[1]))],
        x if isinstance(x, Tensor) else Tensor(jnp.asarray(_data(x))))
    out = SparseCooTensor(jsparse.BCOO((vals_t._data, idx),
                                       shape=coo._bcoo.shape))
    out._vals_t = vals_t
    return out.to_sparse_csr() if isinstance(mask, SparseCsrTensor) else out


def _coerce_coo_pair(x, y, opname):
    was_csr = isinstance(x, SparseCsrTensor)
    xc = x.to_sparse_coo() if was_csr else x
    yc = y.to_sparse_coo() if isinstance(y, SparseCsrTensor) else y
    if not (isinstance(xc, SparseCooTensor) and
            isinstance(yc, SparseCooTensor)):
        raise TypeError(f"sparse.{opname} expects two sparse tensors")
    return xc, yc, was_csr


def _union_indices(xc: SparseCooTensor, yc: SparseCooTensor):
    """Structural union of the two sparsity patterns (sorted, deduped).
    Non-differentiable by construction: only index buffers are touched."""
    union = jsparse.BCOO((jnp.concatenate([
        jnp.ones(xc._bcoo.nse, jnp.float32),
        jnp.ones(yc._bcoo.nse, jnp.float32)]),
        jnp.concatenate([xc._bcoo.indices, yc._bcoo.indices], axis=0)),
        shape=xc._bcoo.shape).sum_duplicates()
    return union.indices


def _binary_at_pattern(opname, fn, x, y, out_indices=None):
    """Elementwise binary op at a fixed output pattern, with the VALUES
    computed through apply_op over x.values()/y.values() so autograd flows
    into both values buffers (ADVICE r2: the earlier raw-array path
    silently dropped these gradients). The dense reconstruct + gather is
    all jnp inside the closure, hence differentiable; nnz is test-scale
    (same stance as the reference's merge kernels note above). Note the
    CSR path coalesces through a COO conversion, which drops an incoming
    `_vals_t` tape link — gradients are guaranteed for COO operands."""
    xc, yc, was_csr = _coerce_coo_pair(x, y, opname)
    idx = _union_indices(xc, yc) if out_indices is None else out_indices
    pos = tuple(idx[:, d] for d in range(idx.shape[1]))
    xi, yi, shp = xc._bcoo.indices, yc._bcoo.indices, xc._bcoo.shape

    def _f(vx, vy):
        dx = jsparse.BCOO((vx, xi), shape=shp).todense()
        dy = jsparse.BCOO((vy, yi), shape=shp).todense()
        return fn(dx, dy)[pos]

    vals_t = apply_op(opname, _f, xc.values(), yc.values())
    res = SparseCooTensor(jsparse.BCOO((vals_t._data, idx), shape=shp))
    res._vals_t = vals_t
    return res.to_sparse_csr() if was_csr else res


def add(x, y, name=None):
    return _binary_at_pattern("sparse_add", lambda a, b: a + b, x, y)


def subtract(x, y, name=None):
    return _binary_at_pattern("sparse_subtract", lambda a, b: a - b, x, y)


def multiply(x, y, name=None):
    xc, yc, _ = _coerce_coo_pair(x, y, "multiply")
    # keep the historical output pattern: exact nonzeros of the product
    # (intersection minus cancellations), computed structurally first
    pattern = jsparse.BCOO.fromdense(xc._bcoo.todense() * yc._bcoo.todense())
    return _binary_at_pattern("sparse_multiply", lambda a, b: a * b, x, y,
                              out_indices=pattern.indices)


def divide(x, y, name=None):
    """Elementwise divide at the UNION of the two sparsity patterns;
    entries where the divisor is (structurally) zero keep their inf/nan
    result, matching the reference (`python/paddle/sparse/binary.py`
    divide example: -1/0 -> -inf). Scalar divisor divides the values
    buffer (sparse_divide_scalar kernel)."""
    if jnp.isscalar(y) or isinstance(y, (int, float)):
        return _unary_on_values("sparse_divide_scalar",
                                lambda v: v / y)(x)
    return _binary_at_pattern("sparse_divide", lambda a, b: a / b, x, y)


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    """Randomized PCA of a sparse matrix (parity: paddle.sparse exports
    pca_lowrank too; computed through the dense path at test scale)."""
    from ..ops.linalg import pca_lowrank as _dense
    dense = x.to_dense() if isinstance(x, (SparseCooTensor,
                                           SparseCsrTensor)) else x
    return _dense(dense, q=q, center=center, niter=niter)


from . import nn  # noqa: E402,F401  (layers/functional subpackage)


# module-path parity (reference sparse/creation.py)
from . import creation  # noqa: F401,E402
