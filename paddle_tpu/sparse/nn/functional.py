"""sparse.nn.functional — sparse conv / pooling / activations / attention.

Parity: reference `python/paddle/sparse/nn/functional/` (conv.py
conv3d/subm_conv3d/conv2d/subm_conv2d, pooling.py max_pool3d,
activation.py, transformer.py attention) over the phi sparse kernels
(`paddle/phi/kernels/sparse/gpu/conv_kernel.cu`, `pool_kernel.cu`,
`fused_attention_kernel.cu`).

TPU-native designs:
  * submanifold conv = gather-GEMM: active sites keep their coordinates;
    for each kernel offset a host-built neighbor table gathers partner
    values and one (nnz, Cin) x (Cin, Cout) MXU matmul accumulates — the
    same rulebook formulation the reference builds on device, done once
    on host (eager-only, like every data-dependent-sparsity op here).
  * full conv / pooling densify into a window reduction (XLA
    conv_general_dilated / reduce_window) and re-sparsify — correct at
    any test scale; the submanifold path is the performance-critical one
    in point-cloud workloads.
  * sparse attention = SDDMM + segment softmax + SpMM, vmapped over
    (batch, head) with the CSR pattern riding along.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ...core.tensor import Tensor
from ...ops.dispatch import apply_op
from .. import (SparseCooTensor, SparseCsrTensor, _data, leaky_relu, relu,
                relu6)

__all__ = ["conv2d", "conv3d", "subm_conv2d", "subm_conv3d", "max_pool3d",
           "relu", "relu6", "leaky_relu", "softmax", "attention"]


def softmax(x, axis=-1, name=None):
    """Row-wise softmax over the last sparse dim (CSR rows / COO rows).
    Parity: sparse softmax kernel (csr)."""
    if axis not in (-1, len(x.shape) - 1):
        raise NotImplementedError("sparse softmax supports the last axis")
    csr = x.to_sparse_csr() if isinstance(x, SparseCooTensor) else x
    indptr = csr._bcsr.indptr
    vals = csr._bcsr.data
    n_rows = csr.shape[0]
    row_id = jnp.searchsorted(indptr, jnp.arange(vals.shape[0]),
                              side="right") - 1
    row_max = jax.ops.segment_max(vals, row_id, n_rows)
    ex = jnp.exp(vals - row_max[row_id])
    row_sum = jax.ops.segment_sum(ex, row_id, n_rows)
    out_vals = ex / row_sum[row_id]
    out = SparseCsrTensor(jsparse.BCSR(
        (out_vals, csr._bcsr.indices, csr._bcsr.indptr), shape=csr.shape))
    return out.to_sparse_coo() if isinstance(x, SparseCooTensor) else out


def _resparsify(out_dense):
    """Dense Tensor -> COO, keeping the tape link by gathering the dense
    output at the discovered nonzero coordinates (eager-only)."""
    bcoo = jsparse.BCOO.fromdense(out_dense._data)
    idx = bcoo.indices

    def _g(d):
        return d[tuple(idx[:, i] for i in range(idx.shape[1]))]

    vals = apply_op("sparse_values_gather", _g, out_dense)
    res = SparseCooTensor(jsparse.BCOO((vals._data, idx),
                                       shape=out_dense._data.shape))
    res._vals_t = vals
    return res


def _normalize(v, nd, name):
    if isinstance(v, int):
        return (v,) * nd
    v = tuple(int(s) for s in v)
    if len(v) != nd:
        raise ValueError(f"{name} must have {nd} entries, got {v}")
    return v


_RULEBOOK_CACHE = {}


def _subm_neighbor_tables(idx_np, kernel_sizes, dilation, dims):
    """Host-side rulebook: for every kernel offset, neighbor_row[i] = row
    of the input active site that the offset reaches from output site i,
    or -1. Output sites == input sites (submanifold contract). Built once
    per (geometry, kernel) — cached, since active sites are static across
    training steps — and fully vectorized via sorted linear coordinates."""
    key = (idx_np.tobytes(), tuple(kernel_sizes), tuple(dilation),
           tuple(dims))
    hit = _RULEBOOK_CACHE.get(key)
    if hit is not None:
        return hit
    nnz = idx_np.shape[0]
    dims = np.asarray(dims)
    lin = np.ravel_multi_index(idx_np.T, dims)
    order = np.argsort(lin)
    lin_sorted = lin[order]
    offsets = np.stack(np.meshgrid(
        *[np.arange(k) - k // 2 for k in kernel_sizes],
        indexing="ij"), axis=-1).reshape(-1, len(kernel_sizes))
    gathers = []
    for off in offsets:
        shifted = idx_np.copy()
        shifted[:, 1:] = idx_np[:, 1:] + off * np.asarray(dilation)
        inb = np.all((shifted >= 0) & (shifted < dims), axis=1)
        lin_s = np.where(
            inb, np.ravel_multi_index(shifted.T % dims[:, None], dims), 0)
        pos = np.searchsorted(lin_sorted, lin_s)
        pos_c = np.minimum(pos, nnz - 1)
        found = inb & (lin_sorted[pos_c] == lin_s)
        gathers.append(np.where(found, order[pos_c], -1))
    out = np.stack(gathers)                            # (K, nnz)
    if len(_RULEBOOK_CACHE) > 64:
        _RULEBOOK_CACHE.clear()
    _RULEBOOK_CACHE[key] = out
    return out


def _subm_conv(x: SparseCooTensor, weight, bias, dilation, name):
    """Gather-GEMM submanifold conv (stride 1, 'same' active set)."""
    idx_np = np.asarray(x._bcoo.indices)               # (nnz, 1+spatial)
    wd = _data(weight)
    ks = wd.shape[:-2]
    nd = len(ks)
    if idx_np.shape[1] != nd + 1:
        raise ValueError(
            f"subm_conv{nd}d input must have indices (batch, {nd} spatial)")
    gathers = jnp.asarray(
        _subm_neighbor_tables(idx_np, ks,
                              _normalize(dilation, nd, "dilation"),
                              tuple(x.shape[:-1])))

    def _f(vals, w, *maybe_b):
        wf = w.reshape(-1, w.shape[-2], w.shape[-1])   # (K, Cin, Cout)
        out = jnp.zeros((vals.shape[0], w.shape[-1]), vals.dtype)

        def body(k, acc):
            g = gathers[k]
            nb = jnp.where(g[:, None] >= 0, vals[jnp.maximum(g, 0)], 0.0)
            return acc + nb @ wf[k]
        out = jax.lax.fori_loop(0, wf.shape[0], body, out)
        if maybe_b:
            out = out + maybe_b[0]
        return out

    args = [x.values(), weight]
    if bias is not None:
        args.append(bias)
    new_vals = apply_op(name, _f, *args)
    from .. import _rebuild_coo
    shape = tuple(list(x.shape[:-1]) + [int(wd.shape[-1])])
    return _rebuild_coo(x, new_vals, shape=shape)


def _dense_conv(x: SparseCooTensor, weight, bias, stride, padding, dilation,
                groups, name):
    """Full sparse conv: densify -> XLA conv -> re-sparsify (eager)."""
    wd = _data(weight)
    nd = len(wd.shape) - 2
    stride = _normalize(stride, nd, "stride")
    padding = _normalize(padding, nd, "padding")
    dilation = _normalize(dilation, nd, "dilation")

    def _f(dense, w, *maybe_b):
        dn = jax.lax.conv_dimension_numbers(
            dense.shape, w.shape,
            ("NDHWC", "DHWIO", "NDHWC") if nd == 3 else
            ("NHWC", "HWIO", "NHWC"))
        out = jax.lax.conv_general_dilated(
            dense, w, window_strides=stride,
            padding=[(p, p) for p in padding], rhs_dilation=dilation,
            dimension_numbers=dn, feature_group_count=groups)
        if maybe_b:
            out = out + maybe_b[0]
        return out

    idx = x._bcoo.indices
    pos = tuple(idx[:, i] for i in range(idx.shape[1]))
    dense_shape = tuple(x.shape)

    def _densify(v):
        # .add (not .set): un-coalesced COO duplicates must sum, matching
        # todense() semantics
        return jnp.zeros(dense_shape, v.dtype).at[pos].add(v)

    dense_t = apply_op("sparse_to_dense", _densify, x.values())
    args = [dense_t, weight]
    if bias is not None:
        args.append(bias)
    out = apply_op(name, _f, *args)
    return _resparsify(out)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NDHWC", key=None, name=None):
    """x: COO (N, D, H, W, C); weight: (kD, kH, kW, Cin/groups, Cout).
    Parity: paddle.sparse.nn.functional.conv3d."""
    if data_format != "NDHWC":
        raise NotImplementedError("sparse conv3d supports NDHWC only")
    return _dense_conv(x, weight, bias, stride, padding, dilation, groups,
                       "sparse_conv3d")


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NHWC", key=None, name=None):
    if data_format != "NHWC":
        raise NotImplementedError("sparse conv2d supports NHWC only")
    return _dense_conv(x, weight, bias, stride, padding, dilation, groups,
                       "sparse_conv2d")


def subm_conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NDHWC", key=None, name=None):
    """Submanifold conv: output active set == input active set.
    Parity: paddle.sparse.nn.functional.subm_conv3d (rulebook + gemm)."""
    if data_format != "NDHWC":
        raise NotImplementedError("subm_conv3d supports NDHWC only")
    if _normalize(stride, 3, "stride") != (1, 1, 1) or groups != 1:
        raise NotImplementedError("subm conv requires stride=1, groups=1")
    return _subm_conv(x, weight, bias, dilation, "sparse_subm_conv3d")


def subm_conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NHWC", key=None, name=None):
    if data_format != "NHWC":
        raise NotImplementedError("subm_conv2d supports NHWC only")
    if _normalize(stride, 2, "stride") != (1, 1) or groups != 1:
        raise NotImplementedError("subm conv requires stride=1, groups=1")
    return _subm_conv(x, weight, bias, dilation, "sparse_subm_conv2d")


def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               data_format="NDHWC", name=None):
    """Max over each window's ACTIVE sites (inactive background is -inf,
    not 0 — matches the reference sparse pool kernel). Eager-only."""
    if data_format != "NDHWC":
        raise NotImplementedError("sparse max_pool3d supports NDHWC only")
    ks = _normalize(kernel_size, 3, "kernel_size")
    st = _normalize(stride if stride is not None else kernel_size, 3,
                    "stride")
    pd = _normalize(padding, 3, "padding")
    neg = jnp.asarray(-jnp.inf, x.dtype)
    idx = x._bcoo.indices
    pos = tuple(idx[:, d] for d in range(idx.shape[1]))
    dense_shape = tuple(x.shape)

    def _f(v):
        d = jnp.full(dense_shape, neg, v.dtype).at[pos].set(v)
        out = jax.lax.reduce_window(
            d, neg, jax.lax.max,
            window_dimensions=(1,) + ks + (1,),
            window_strides=(1,) + st + (1,),
            padding=((0, 0),) + tuple((p, p) for p in pd) + ((0, 0),))
        return jnp.where(jnp.isfinite(out), out, 0.0)

    out = apply_op("sparse_max_pool3d", _f, x.values())
    return _resparsify(out)


def attention(query, key, value, sparse_mask, key_padding_mask=None,
              attn_mask=None, name=None):
    """Sparse-pattern attention: scores only at the CSR mask's nonzeros.

    Parity: paddle.sparse.nn.functional.attention
    (`phi/kernels/sparse/gpu/fused_attention_kernel.cu`): q,k,v
    (B, H, S, D) dense, sparse_mask a (B*H, S, S) CSR pattern batch.
    TPU-native: SDDMM + segment softmax + SpMM vmapped over B*H — one
    fused XLA program, nnz-proportional work.
    """
    qd, kd, vd = _data(query), _data(key), _data(value)
    B, H, S, D = qd.shape
    csr = sparse_mask
    crows = _data(csr.crows()).reshape(B * H, S + 1)
    cols = _data(csr.cols()).reshape(B * H, -1)
    scale = 1.0 / float(np.sqrt(D))

    def _f(q, k, v, *masks):
        kpm = masks[0] if key_padding_mask is not None else None
        am = (masks[1] if key_padding_mask is not None else masks[0]) \
            if attn_mask is not None else None

        def one(qh, kh, vh, crow, col, extra):
            nnz = col.shape[0]
            row = jnp.searchsorted(crow, jnp.arange(nnz), side="right") - 1
            s = jnp.einsum("nd,nd->n", qh[row], kh[col]) * scale + extra
            mx = jax.ops.segment_max(s, row, S)
            ex = jnp.exp(s - mx[row])
            den = jax.ops.segment_sum(ex, row, S)
            p = ex / jnp.maximum(den[row], 1e-30)
            return jax.ops.segment_sum(p[:, None] * vh[col], row, S)

        qf = q.reshape(B * H, S, D)
        kf = k.reshape(B * H, S, D)
        vf = v.reshape(B * H, S, D)
        nnz = cols.shape[1]
        extra = jnp.zeros((B * H, nnz), qf.dtype)
        if kpm is not None:
            # (B, S) additive mask on keys
            kpm_bh = jnp.repeat(kpm, H, axis=0)
            extra = extra + jnp.take_along_axis(kpm_bh, cols, axis=1)
        if am is not None:
            am_bh = jnp.repeat(am.reshape(B, S, S), H, axis=0) \
                if am.ndim == 3 else jnp.broadcast_to(am, (B * H, S, S))
            row = jax.vmap(lambda cr: jnp.searchsorted(
                cr, jnp.arange(nnz), side="right") - 1)(crows)
            gat = jax.vmap(lambda a, r, c: a[r, c])(am_bh, row, cols)
            extra = extra + gat
        out = jax.vmap(one)(qf, kf, vf, crows, cols, extra)
        return out.reshape(B, H, S, D)

    args = [query, key, value]
    if key_padding_mask is not None:
        args.append(key_padding_mask)
    if attn_mask is not None:
        args.append(attn_mask)
    return apply_op("sparse_attention", _f, *args)


def subm_conv2d_igemm(x, weight, bias=None, stride=1, padding=0, dilation=1,
                      groups=1, data_format="NHWC", key=None, name=None):
    """Parity: sparse.nn.functional.subm_conv2d_igemm — the implicit-GEMM
    schedule variant; on TPU the same gather+MXU lowering serves both."""
    return subm_conv2d(x, weight, bias, stride, padding, dilation, groups,
                       data_format, key)


def subm_conv3d_igemm(x, weight, bias=None, stride=1, padding=0, dilation=1,
                      groups=1, data_format="NDHWC", key=None, name=None):
    """Parity: sparse.nn.functional.subm_conv3d_igemm."""
    return subm_conv3d(x, weight, bias, stride, padding, dilation, groups,
                       data_format, key)


__all__ += ["subm_conv2d_igemm", "subm_conv3d_igemm"]
