"""sparse.nn — layer classes over sparse.nn.functional.

Parity: reference `python/paddle/sparse/nn/layer/` (activation.py,
conv.py Conv3D/SubmConv3D/Conv2D/SubmConv2D, norm.py BatchNorm/
SyncBatchNorm, pooling.py MaxPool3D)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ...core.tensor import Tensor
from ...nn.layer.layers import Layer
from ...ops.dispatch import apply_op
from .. import SparseCooTensor
from . import functional
from . import functional as F

__all__ = ["ReLU", "ReLU6", "LeakyReLU", "Softmax", "Conv2D", "Conv3D",
           "SubmConv2D", "SubmConv3D", "BatchNorm", "SyncBatchNorm",
           "MaxPool3D", "functional"]


class ReLU(Layer):
    def forward(self, x):
        return F.relu(x)


class ReLU6(Layer):
    def forward(self, x):
        return F.relu6(x)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01, name=None):
        super().__init__()
        self._slope = negative_slope

    def forward(self, x):
        return F.leaky_relu(x, self._slope)


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return F.softmax(x, self._axis)


class _ConvBase(Layer):
    def __init__(self, nd, subm, in_channels, out_channels, kernel_size,
                 stride=1, padding=0, dilation=1, groups=1,
                 padding_mode="zeros", weight_attr=None, bias_attr=None,
                 data_format=None):
        super().__init__()
        ks = (kernel_size,) * nd if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        self._nd, self._subm = nd, subm
        self._stride, self._padding = stride, padding
        self._dilation, self._groups = dilation, groups
        fan_in = int(np.prod(ks)) * in_channels // groups
        bound = 1.0 / np.sqrt(fan_in)
        self.weight = self.create_parameter(
            ks + (in_channels // groups, out_channels), attr=weight_attr)
        if weight_attr is None or getattr(weight_attr, "initializer",
                                          None) is None:
            from ...framework.random import rng_key
            import jax
            self.weight._data = jax.random.uniform(
                rng_key(), tuple(self.weight.shape), self.weight.dtype,
                minval=-bound, maxval=bound)
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter((out_channels,),
                                              attr=bias_attr, is_bias=True)
        if self.bias is not None:
            self.add_parameter("bias", self.bias)
        self.add_parameter("weight", self.weight)

    def forward(self, x):
        fn = {(2, False): F.conv2d, (3, False): F.conv3d,
              (2, True): F.subm_conv2d, (3, True): F.subm_conv3d}[
            (self._nd, self._subm)]
        return fn(x, self.weight, self.bias, stride=self._stride,
                  padding=self._padding, dilation=self._dilation,
                  groups=self._groups)


class Conv3D(_ConvBase):
    """Parity: paddle.sparse.nn.Conv3D (sparse conv3d kernel)."""

    def __init__(self, in_channels, out_channels, kernel_size, **kw):
        super().__init__(3, False, in_channels, out_channels, kernel_size,
                         **kw)


class Conv2D(_ConvBase):
    def __init__(self, in_channels, out_channels, kernel_size, **kw):
        super().__init__(2, False, in_channels, out_channels, kernel_size,
                         **kw)


class SubmConv3D(_ConvBase):
    """Parity: paddle.sparse.nn.SubmConv3D (submanifold rulebook conv)."""

    def __init__(self, in_channels, out_channels, kernel_size, **kw):
        super().__init__(3, True, in_channels, out_channels, kernel_size,
                         **kw)


class SubmConv2D(_ConvBase):
    def __init__(self, in_channels, out_channels, kernel_size, **kw):
        super().__init__(2, True, in_channels, out_channels, kernel_size,
                         **kw)


class BatchNorm(Layer):
    """Batch norm over ACTIVE values per channel (inactive sites do not
    contribute to the statistics — reference sparse batch_norm kernel
    semantics, `phi/kernels/sparse/batch_norm_kernel.h`)."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NDHWC",
                 use_global_stats=None, name=None):
        super().__init__()
        self._momentum, self._eps = momentum, epsilon
        self._use_global_stats = use_global_stats
        from ...nn.initializer import Constant
        self.weight = self.create_parameter((num_features,),
                                            default_initializer=Constant(1.0))
        self.bias = self.create_parameter((num_features,), is_bias=True)
        self.add_parameter("weight", self.weight)
        self.add_parameter("bias", self.bias)
        self._mean = Tensor(jnp.zeros((num_features,)), stop_gradient=True)
        self._variance = Tensor(jnp.ones((num_features,)),
                                stop_gradient=True)
        self.register_buffer("_mean", self._mean)
        self.register_buffer("_variance", self._variance)

    def forward(self, x: SparseCooTensor):
        use_global = self._use_global_stats
        if use_global is None:
            use_global = not self.training
        run_mean = self._mean._data
        run_var = self._variance._data

        def _f(v, w, b):
            # stats computed INSIDE the taped closure so backward carries
            # the d(mean)/dv and d(var)/dv terms (dense F.batch_norm does
            # the same; reference sparse batch_norm grad kernel parity)
            if use_global:
                mean, var = run_mean, run_var
            else:
                mean = jnp.mean(v, axis=0)
                var = jnp.var(v, axis=0)
            out = (v - mean) / jnp.sqrt(var + self._eps) * w + b
            return out, mean, var

        out, mean_t, var_t = apply_op("sparse_batch_norm", _f, x.values(),
                                      self.weight, self.bias)
        if not use_global:
            m = self._momentum
            self._mean._data = m * run_mean + (1 - m) * mean_t._data
            self._variance._data = m * run_var + (1 - m) * var_t._data
        from .. import _rebuild_coo
        return _rebuild_coo(x, out)


class SyncBatchNorm(BatchNorm):
    """Cross-replica batch norm. Under SPMD the values buffer is already
    globally visible to the compiler (stats become collective reductions
    when sharded); eager single-process behavior equals BatchNorm —
    matching the reference's world_size==1 fast path
    (`python/paddle/sparse/nn/layer/norm.py` SyncBatchNorm)."""


class MaxPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 return_mask=False, data_format="NDHWC", name=None):
        super().__init__()
        self._ks, self._st, self._pd = kernel_size, stride, padding

    def forward(self, x):
        return F.max_pool3d(x, self._ks, self._st, self._pd)
