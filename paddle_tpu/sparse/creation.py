"""paddle.sparse.creation — module-path parity (reference
sparse/creation.py); implementations live in the package root."""
from . import sparse_coo_tensor, sparse_csr_tensor  # noqa: F401

__all__ = ["sparse_coo_tensor", "sparse_csr_tensor"]
