"""paddle.version — build metadata.

Parity: reference generated `python/paddle/version/__init__.py`
(full_version/major/minor/patch/rc + cuda()/cudnn()/nccl()/xpu() probes).
This build targets TPU through XLA: the CUDA-family probes report False/
None and tpu()/xla() report the live backend.
"""
from __future__ import annotations

full_version = "0.1.0"
major = "0"
minor = "1"
patch = "0"
rc = "0"
istaged = True
commit = "tpu-native"
with_pip_cuda_libraries = "OFF"

__all__ = ["full_version", "major", "minor", "patch", "rc", "cuda",
           "cudnn", "nccl", "xpu", "xpu_xccl", "xpu_xhpc", "cinn",
           "tpu", "xla", "show"]


def cuda():
    """False: this build has no CUDA dependency (TPU-native)."""
    return False


def cudnn():
    return False


def nccl():
    return 0


def xpu():
    return False


def xpu_xccl():
    return 0


def xpu_xhpc():
    return ""


def cinn():
    """The fusion-compiler role is played by XLA in this build."""
    return False


def tpu():
    """The libtpu/PJRT backend version when a TPU is attached."""
    try:
        import jax
        d = jax.devices()[0]
        return getattr(d, "device_kind", d.platform)
    except Exception:
        return None


def xla():
    import jax
    return jax.__version__


def show():
    print(f"full_version: {full_version}")
    print(f"commit: {commit}")
    print(f"xla (jax): {xla()}")
    print(f"cuda: {cuda()}  cudnn: {cudnn()}  (TPU-native build)")
