"""audio.functional — windows, mel filterbanks, dct.

Parity: reference `python/paddle/audio/functional/functional.py`
(hz_to_mel/mel_to_hz/mel_frequencies/fft_frequencies/compute_fbank_matrix/
power_to_db/create_dct) and `window.py` (get_window).
"""
from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor

__all__ = ["hz_to_mel", "mel_to_hz", "mel_frequencies", "fft_frequencies",
           "compute_fbank_matrix", "power_to_db", "create_dct", "get_window"]


def hz_to_mel(freq, htk=False):
    """Hz -> mel. Slaney (default) or HTK formula."""
    scalar = not hasattr(freq, "__len__") and not isinstance(freq, Tensor)
    f = np.asarray(freq._data if isinstance(freq, Tensor) else freq,
                   dtype=np.float64)
    if htk:
        mel = 2595.0 * np.log10(1.0 + f / 700.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        mel = (f - f_min) / f_sp
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        mel = np.where(f >= min_log_hz,
                       min_log_mel + np.log(np.maximum(f, 1e-10)
                                            / min_log_hz) / logstep, mel)
    return float(mel) if scalar else Tensor(jnp.asarray(mel, jnp.float32))


def mel_to_hz(mel, htk=False):
    scalar = not hasattr(mel, "__len__") and not isinstance(mel, Tensor)
    m = np.asarray(mel._data if isinstance(mel, Tensor) else mel,
                   dtype=np.float64)
    if htk:
        f = 700.0 * (10.0 ** (m / 2595.0) - 1.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        f = f_min + f_sp * m
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        f = np.where(m >= min_log_mel,
                     min_log_hz * np.exp(logstep * (m - min_log_mel)), f)
    return float(f) if scalar else Tensor(jnp.asarray(f, jnp.float32))


def mel_frequencies(n_mels=64, f_min=0.0, f_max=11025.0, htk=False,
                    dtype="float32"):
    lo = hz_to_mel(float(f_min), htk)
    hi = hz_to_mel(float(f_max), htk)
    mels = np.linspace(lo, hi, n_mels)
    return Tensor(jnp.asarray(
        np.asarray(mel_to_hz(mels, htk)._data), jnp.float32))


def fft_frequencies(sr, n_fft, dtype="float32"):
    return Tensor(jnp.linspace(0.0, sr / 2.0, 1 + n_fft // 2,
                               dtype=jnp.float32))


def compute_fbank_matrix(sr, n_fft, n_mels=64, f_min=0.0, f_max=None,
                         htk=False, norm="slaney", dtype="float32"):
    """(n_mels, 1 + n_fft//2) triangular mel filterbank."""
    f_max = f_max if f_max is not None else sr / 2.0
    fft_f = np.asarray(fft_frequencies(sr, n_fft)._data)
    mel_f = np.asarray(mel_frequencies(n_mels + 2, f_min, f_max, htk)._data)
    fdiff = np.diff(mel_f)
    ramps = mel_f[:, None] - fft_f[None, :]
    lower = -ramps[:-2] / np.maximum(fdiff[:-1, None], 1e-10)
    upper = ramps[2:] / np.maximum(fdiff[1:, None], 1e-10)
    fb = np.maximum(0.0, np.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (mel_f[2:n_mels + 2] - mel_f[:n_mels])
        fb *= enorm[:, None]
    return Tensor(jnp.asarray(fb, jnp.float32))


def power_to_db(spect, ref_value=1.0, amin=1e-10, top_db=80.0, name=None):
    """10*log10(S/ref) with floor. Parity: functional.py power_to_db."""
    from ..ops.dispatch import apply_op

    def _f(s):
        log_spec = 10.0 * jnp.log10(jnp.maximum(amin, s))
        log_spec = log_spec - 10.0 * math.log10(max(amin, ref_value))
        if top_db is not None:
            log_spec = jnp.maximum(log_spec, jnp.max(log_spec) - top_db)
        return log_spec
    return apply_op("power_to_db", _f, spect)


def create_dct(n_mfcc, n_mels, norm="ortho", dtype="float32"):
    """(n_mels, n_mfcc) DCT-II matrix. Parity: functional.py create_dct."""
    n = np.arange(n_mels, dtype=np.float64)
    k = np.arange(n_mfcc, dtype=np.float64)[None, :]
    dct = np.cos(math.pi / n_mels * (n[:, None] + 0.5) * k)
    if norm == "ortho":
        dct[:, 0] *= 1.0 / math.sqrt(2.0)
        dct *= math.sqrt(2.0 / n_mels)
    else:
        dct *= 2.0
    return Tensor(jnp.asarray(dct, jnp.float32))


def get_window(window, win_length, fftbins=True, dtype="float32"):
    """hann/hamming/blackman/... periodic (fftbins) or symmetric windows."""
    if isinstance(window, (tuple, list)):
        name, *args = window
    else:
        name, args = window, []
    n = win_length + (0 if fftbins else -1)
    t = np.arange(win_length, dtype=np.float64)
    denom = max(n, 1)
    if name in ("hann", "hanning"):
        w = 0.5 - 0.5 * np.cos(2 * math.pi * t / denom)
    elif name == "hamming":
        w = 0.54 - 0.46 * np.cos(2 * math.pi * t / denom)
    elif name == "blackman":
        w = (0.42 - 0.5 * np.cos(2 * math.pi * t / denom)
             + 0.08 * np.cos(4 * math.pi * t / denom))
    elif name in ("rect", "rectangular", "boxcar", "ones"):
        w = np.ones(win_length)
    elif name == "bartlett":
        w = 1.0 - np.abs(2 * t / denom - 1.0)
    elif name == "gaussian":
        std = args[0] if args else 7.0
        w = np.exp(-0.5 * ((t - (win_length - 1) / 2.0) / std) ** 2)
    else:
        raise ValueError(f"unsupported window {window!r}")
    return Tensor(jnp.asarray(w, jnp.float32))
