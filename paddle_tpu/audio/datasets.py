"""paddle.audio.datasets — ESC50 / TESS audio classification datasets.

Parity: reference `python/paddle/audio/datasets/` (ESC50, TESS over
AudioClassificationDataset: wav files -> (feature, label)). Zero-egress
build: reads the standard local extraction; synthetic fallback otherwise
(same stance as vision.datasets.MNIST).
"""
from __future__ import annotations

import os

import numpy as np

from ..io.dataset import Dataset

__all__ = ["ESC50", "TESS"]

_DATA_HOME = os.path.expanduser(os.environ.get("PADDLE_TPU_DATA_HOME",
                                               "~/.cache/paddle_tpu/datasets"))


class AudioClassificationDataset(Dataset):
    """(waveform, label) pairs with an optional feature transform."""

    def __init__(self, files, labels, sample_rate, feat_type="raw",
                 archive=None, **kwargs):
        self.files = files
        self.labels = labels
        self.sample_rate = sample_rate
        self.feat_type = feat_type
        self.feat_config = kwargs

    def _feature(self, wav):
        import jax.numpy as jnp
        from ..core.tensor import Tensor
        if self.feat_type == "raw":
            return Tensor(jnp.asarray(wav, jnp.float32))
        from .features import MelSpectrogram
        if self.feat_type == "mel_spectrogram":
            m = MelSpectrogram(sr=self.sample_rate, **self.feat_config)
            return m(Tensor(jnp.asarray(wav, jnp.float32)[None]))
        raise ValueError(f"unknown feat_type {self.feat_type}")

    def __getitem__(self, idx):
        f = self.files[idx]
        if isinstance(f, np.ndarray):
            wav = f
        else:
            from .backends import load
            t, _ = load(f, channels_first=False)
            wav = np.asarray(t._data)[:, 0]
        return self._feature(wav), np.asarray(self.labels[idx], np.int64)

    def __len__(self):
        return len(self.files)


def _synthetic(n, sr, n_classes, seconds=1):
    rng = np.random.RandomState(0)
    waves = [rng.randn(sr * seconds).astype(np.float32) * 0.1
             for _ in range(n)]
    labels = rng.randint(0, n_classes, n)
    return waves, labels


class ESC50(AudioClassificationDataset):
    """Parity: audio.datasets.ESC50 (2000 clips, 50 classes, 5 folds)."""

    sample_rate = 44100

    def __init__(self, mode="train", split=1, feat_type="raw",
                 archive=None, **kwargs):
        base = os.path.join(_DATA_HOME, "esc50", "ESC-50-master")
        meta = os.path.join(base, "meta", "esc50.csv")
        if os.path.exists(meta):
            import csv
            files, labels = [], []
            with open(meta) as f:
                for row in csv.DictReader(f):
                    in_fold = int(row["fold"]) == int(split)
                    if (mode == "train") != in_fold:
                        files.append(os.path.join(base, "audio",
                                                  row["filename"]))
                        labels.append(int(row["target"]))
        else:
            n = 160 if mode == "train" else 40
            files, labels = _synthetic(n, 4410, 50)
        super().__init__(files, labels, self.sample_rate, feat_type,
                         **kwargs)


class TESS(AudioClassificationDataset):
    """Parity: audio.datasets.TESS (2800 clips, 7 emotions)."""

    sample_rate = 24414
    emotions = ["angry", "disgust", "fear", "happy", "neutral",
                "ps", "sad"]

    def __init__(self, mode="train", n_folds=5, split=1, feat_type="raw",
                 archive=None, **kwargs):
        base = os.path.join(_DATA_HOME, "tess",
                            "TESS_Toronto_emotional_speech_set_data")
        if os.path.isdir(base):
            files, labels = [], []
            wavs = []
            for dirpath, _, fs in sorted(os.walk(base)):
                wavs += [os.path.join(dirpath, f) for f in sorted(fs)
                         if f.lower().endswith(".wav")]
            for i, w in enumerate(wavs):
                emo = os.path.basename(w).split("_")[-1][:-4].lower()
                label = self.emotions.index(emo) if emo in self.emotions \
                    else 0
                in_fold = (i % n_folds) + 1 == int(split)
                if (mode == "train") != in_fold:
                    files.append(w)
                    labels.append(label)
        else:
            n = 112 if mode == "train" else 28
            files, labels = _synthetic(n, 2441, 7)
        super().__init__(files, labels, self.sample_rate, feat_type,
                        **kwargs)
