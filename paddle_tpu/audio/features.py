"""audio.features — Spectrogram / MelSpectrogram / LogMelSpectrogram / MFCC.

Parity: reference `python/paddle/audio/features/layers.py`. STFT is
implemented as strided framing + window + rfft (XLA FFT HLO); all layers
are differentiable through the tape.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..nn.layer.layers import Layer
from ..ops.dispatch import apply_op
from . import functional as AF

__all__ = ["Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC"]


def _stft_power(x, n_fft, hop_length, win, center, power,
                pad_mode="reflect"):
    """x: (..., T) -> (..., n_freq, n_frames) |STFT|^power."""
    if center:
        pad = [(0, 0)] * (x.ndim - 1) + [(n_fft // 2, n_fft // 2)]
        x = jnp.pad(x, pad, mode=pad_mode)
    T = x.shape[-1]
    n_frames = 1 + (T - n_fft) // hop_length
    idx = (jnp.arange(n_frames)[:, None] * hop_length
           + jnp.arange(n_fft)[None, :])                 # (frames, n_fft)
    frames = x[..., idx]                                 # (..., frames, n_fft)
    frames = frames * win[None, :]
    spec = jnp.fft.rfft(frames, axis=-1)                 # (..., frames, freq)
    mag = jnp.abs(spec) ** power
    return jnp.swapaxes(mag, -1, -2)                     # (..., freq, frames)


class Spectrogram(Layer):
    """Parity: features/layers.py Spectrogram."""

    def __init__(self, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 dtype="float32"):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        w = AF.get_window(window, self.win_length)._data
        if self.win_length < n_fft:  # center-pad window to n_fft
            lpad = (n_fft - self.win_length) // 2
            w = jnp.pad(w, (lpad, n_fft - self.win_length - lpad))
        self.register_buffer("window", Tensor(w), persistable=False)

    def forward(self, x):
        return apply_op(
            "spectrogram",
            lambda a, w: _stft_power(a, self.n_fft, self.hop_length, w,
                                     self.center, self.power,
                                     self.pad_mode),
            x, self.window)


class MelSpectrogram(Layer):
    """Parity: features/layers.py MelSpectrogram."""

    def __init__(self, sr=22050, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 n_mels=64, f_min=50.0, f_max=None, htk=False, norm="slaney",
                 dtype="float32"):
        super().__init__()
        self._spectrogram = Spectrogram(n_fft, hop_length, win_length,
                                        window, power, center, pad_mode)
        fb = AF.compute_fbank_matrix(sr, n_fft, n_mels, f_min, f_max, htk,
                                     norm)
        self.register_buffer("fbank_matrix", fb, persistable=False)

    def forward(self, x):
        spec = self._spectrogram(x)
        return apply_op("mel_spectrogram",
                        lambda s, fb: jnp.einsum("...ft,mf->...mt", s, fb),
                        spec, self.fbank_matrix)


class LogMelSpectrogram(Layer):
    """Parity: features/layers.py LogMelSpectrogram."""

    def __init__(self, sr=22050, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 n_mels=64, f_min=50.0, f_max=None, htk=False, norm="slaney",
                 ref_value=1.0, amin=1e-10, top_db=None, dtype="float32"):
        super().__init__()
        self._mel = MelSpectrogram(sr, n_fft, hop_length, win_length, window,
                                   power, center, pad_mode, n_mels, f_min,
                                   f_max, htk, norm)
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x):
        return AF.power_to_db(self._mel(x), self.ref_value, self.amin,
                              self.top_db)


class MFCC(Layer):
    """Parity: features/layers.py MFCC (log-mel + DCT)."""

    def __init__(self, sr=22050, n_mfcc=40, n_fft=512, hop_length=None,
                 win_length=None, window="hann", power=2.0, center=True,
                 pad_mode="reflect", n_mels=64, f_min=50.0, f_max=None,
                 htk=False, norm="slaney", ref_value=1.0, amin=1e-10,
                 top_db=None, dtype="float32"):
        super().__init__()
        self._log_mel = LogMelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center,
            pad_mode, n_mels, f_min, f_max, htk, norm, ref_value, amin,
            top_db)
        dct = AF.create_dct(n_mfcc, n_mels)
        self.register_buffer("dct_matrix", dct, persistable=False)

    def forward(self, x):
        lm = self._log_mel(x)
        return apply_op("mfcc",
                        lambda s, d: jnp.einsum("...mt,mk->...kt", s, d),
                        lm, self.dct_matrix)
