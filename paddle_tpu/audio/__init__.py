"""paddle.audio — spectrogram features.

Parity: reference `python/paddle/audio/` — functional (window/mel/dct
helpers, `audio/functional/functional.py`) and features (Spectrogram /
MelSpectrogram / LogMelSpectrogram / MFCC layers, `audio/features/
layers.py`).

TPU-native: STFT framing is a strided window + rfft — one batched matmul
and an XLA FFT, no conv tricks needed.
"""
from . import functional  # noqa: F401
from . import features  # noqa: F401
from . import backends  # noqa: F401
from . import datasets  # noqa: F401
from .backends import load, save, info  # noqa: F401
from .features import (LogMelSpectrogram, MelSpectrogram, MFCC,  # noqa: F401
                       Spectrogram)

__all__ = ["functional", "features", "backends", "datasets", "load",
           "save", "info", "Spectrogram", "MelSpectrogram",
           "LogMelSpectrogram", "MFCC"]
