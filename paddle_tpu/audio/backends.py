"""paddle.audio.backends — wav IO (parity: audio/backends/wave_backend.py:
load/save/info over the stdlib wave module, get/set/list_audio_backends).
"""
from __future__ import annotations

import wave as _wave

import numpy as np

__all__ = ["info", "load", "save", "get_current_audio_backend",
           "list_available_backends", "set_backend", "AudioInfo"]


class AudioInfo:
    """Parity: backends.backend.AudioInfo."""

    def __init__(self, sample_rate, num_samples, num_channels,
                 bits_per_sample, encoding):
        self.sample_rate = sample_rate
        self.num_samples = num_samples
        self.num_channels = num_channels
        self.bits_per_sample = bits_per_sample
        self.encoding = encoding


def info(filepath):
    with _wave.open(filepath, "rb") as f:
        return AudioInfo(f.getframerate(), f.getnframes(), f.getnchannels(),
                         f.getsampwidth() * 8, "PCM_S")


def load(filepath, frame_offset=0, num_frames=-1, normalize=True,
         channels_first=True):
    """wav -> (Tensor (C, L) or (L, C), sample_rate). normalize=True
    scales int PCM to [-1, 1] float32 (reference contract)."""
    from ..core.tensor import Tensor
    import jax.numpy as jnp
    with _wave.open(filepath, "rb") as f:
        sr = f.getframerate()
        n = f.getnframes()
        ch = f.getnchannels()
        width = f.getsampwidth()
        f.setpos(min(frame_offset, n))
        take = n - frame_offset if num_frames < 0 else num_frames
        raw = f.readframes(take)
    dtype = {1: np.uint8, 2: np.int16, 4: np.int32}[width]
    data = np.frombuffer(raw, dtype).reshape(-1, ch)
    if normalize:
        if width == 1:
            data = (data.astype(np.float32) - 128.0) / 128.0
        else:
            data = data.astype(np.float32) / float(2 ** (width * 8 - 1))
    arr = data.T if channels_first else data
    return Tensor(jnp.asarray(arr)), sr


def save(filepath, src, sample_rate, channels_first=True,
         encoding="PCM_16", bits_per_sample=16):
    """Tensor/array -> 16-bit PCM wav."""
    a = np.asarray(getattr(src, "_data", src))
    if channels_first:
        a = a.T
    if a.ndim == 1:
        a = a[:, None]
    if a.dtype.kind == "f":
        a = np.clip(a, -1.0, 1.0)
        a = (a * 32767.0).astype(np.int16)
    with _wave.open(filepath, "wb") as f:
        f.setnchannels(a.shape[1])
        f.setsampwidth(2)
        f.setframerate(int(sample_rate))
        f.writeframes(a.astype("<i2").tobytes())


def get_current_audio_backend():
    return "wave_backend"


def list_available_backends():
    return ["wave_backend"]


def set_backend(backend_name):
    if backend_name != "wave_backend":
        raise NotImplementedError(
            f"backend {backend_name!r} unavailable; only the stdlib wave "
            "backend ships in the TPU build (no soundfile/sox)")


get_current_backend = get_current_audio_backend
__all__ += ["get_current_backend"]
