"""DataLoader with multiprocess workers.

Parity: reference `python/paddle/io/dataloader/dataloader_iter.py:155,370`
(single-process + multiprocess iterators, worker loop in worker.py, batch
collation, prefetching). The reference ships batches through shared-memory
LoDTensor transport; here workers return numpy arrays over a
multiprocessing queue and the main process uploads to device (TPU infeed is
host->HBM DMA; numpy + jnp.asarray is the supported path).
"""
from __future__ import annotations

import itertools
import os
import queue as queue_mod
import threading
from typing import Optional

import numpy as np

from ..core.tensor import Tensor
from .dataset import Dataset, IterableDataset
from .sampler import BatchSampler

__all__ = ["DataLoader", "default_collate_fn", "get_worker_info"]

_worker_info = threading.local()


def get_worker_info():
    return getattr(_worker_info, "info", None)


class WorkerInfo:
    def __init__(self, id, num_workers, dataset, seed):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset
        self.seed = seed


def default_collate_fn(batch):
    """Stack samples into batched numpy arrays (structure-preserving)."""
    sample = batch[0]
    if isinstance(sample, Tensor):
        return Tensor(np.stack([np.asarray(s._data) for s in batch]))
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, float)):
        return np.asarray(batch)
    if isinstance(sample, (str, bytes)):
        return batch
    if isinstance(sample, dict):
        return {k: default_collate_fn([s[k] for s in batch]) for k in sample}
    if isinstance(sample, (list, tuple)):
        transposed = list(zip(*batch))
        out = [default_collate_fn(list(col)) for col in transposed]
        return out if isinstance(sample, list) else tuple(out)
    return batch


def _to_tensor_tree(obj):
    import jax.numpy as jnp
    if isinstance(obj, np.ndarray):
        return Tensor(jnp.asarray(obj))
    if isinstance(obj, dict):
        return {k: _to_tensor_tree(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = [_to_tensor_tree(v) for v in obj]
        return t if isinstance(obj, list) else tuple(t)
    return obj


class DataLoader:
    """Parity: paddle.io.DataLoader (return_list=True semantics)."""

    def __init__(self, dataset, feed_list=None, places=None, return_list=True,
                 batch_sampler=None, batch_size=1, shuffle=False,
                 drop_last=False, collate_fn=None, num_workers=0,
                 use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=120, worker_init_fn=None,
                 persistent_workers=False):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = int(num_workers)
        self.timeout = timeout
        self.worker_init_fn = worker_init_fn
        self.prefetch_factor = prefetch_factor
        self._iterable = isinstance(dataset, IterableDataset)
        if self._iterable:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                              batch_size=batch_size,
                                              drop_last=drop_last)

    def __len__(self):
        if self._iterable:
            raise TypeError("IterableDataset has no len()")
        return len(self.batch_sampler)

    def __call__(self):
        return self.__iter__()

    def __iter__(self):
        if self._iterable:
            return self._iter_iterable()
        if self.num_workers == 0:
            return self._iter_single()
        return self._iter_multiprocess()

    def _iter_iterable(self):
        it = iter(self.dataset)
        while True:
            batch = list(itertools.islice(it, self.batch_size))
            if not batch:
                return
            if len(batch) < self.batch_size and self.drop_last:
                return
            yield _to_tensor_tree(self.collate_fn(batch))

    def _iter_single(self):
        for indices in self.batch_sampler:
            batch = [self.dataset[i] for i in indices]
            yield _to_tensor_tree(self.collate_fn(batch))

    def _iter_multiprocess(self):
        """Thread-pool prefetch pipeline.

        Design note: the reference forks OS processes because CPython holds
        the GIL during numpy-heavy preprocessing; numpy releases the GIL for
        its kernels, and TPU hosts have many cores, so a thread pool +
        bounded queue gives the same overlap without pickling/shared-memory
        transport. (A C++ shared-memory ring like the reference's
        `use_shared_memory` path is a planned native extension.)
        """
        work_q: queue_mod.Queue = queue_mod.Queue()
        done_q: queue_mod.Queue = queue_mod.Queue(
            maxsize=self.num_workers * self.prefetch_factor)
        indices_list = list(self.batch_sampler)
        for i, idxs in enumerate(indices_list):
            work_q.put((i, idxs))
        stop = object()
        results = {}
        lock = threading.Lock()

        def worker(worker_id):
            _worker_info.info = WorkerInfo(worker_id, self.num_workers,
                                           self.dataset, worker_id)
            if self.worker_init_fn is not None:
                self.worker_init_fn(worker_id)
            while True:
                try:
                    item = work_q.get_nowait()
                except queue_mod.Empty:
                    return
                i, idxs = item
                batch = [self.dataset[j] for j in idxs]
                done_q.put((i, self.collate_fn(batch)))

        threads = [threading.Thread(target=worker, args=(w,), daemon=True)
                   for w in range(self.num_workers)]
        for t in threads:
            t.start()

        next_idx = 0
        received = 0
        total = len(indices_list)
        buffer = {}
        while received < total:
            i, batch = done_q.get(timeout=self.timeout)
            buffer[i] = batch
            received += 1
            while next_idx in buffer:
                yield _to_tensor_tree(buffer.pop(next_idx))
                next_idx += 1
        while next_idx in buffer:
            yield _to_tensor_tree(buffer.pop(next_idx))
            next_idx += 1
