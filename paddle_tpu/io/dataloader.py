"""DataLoader with multiprocess workers.

Parity: reference `python/paddle/io/dataloader/dataloader_iter.py:155,370`
(single-process + multiprocess iterators, worker loop in worker.py, batch
collation, prefetching). Like the reference's `use_shared_memory=True`
path, process workers ship batches through a native shared-memory ring
(`paddle_tpu/_native`: POSIX shm + robust process-shared mutex) and the
main process uploads to device (TPU infeed is host->HBM DMA; numpy +
jnp.asarray is the supported path). Without the native extension, a
thread-pool prefetch pipeline provides the overlap instead.
"""
from __future__ import annotations

import itertools
import os
import pickle
import queue as queue_mod
import threading
from typing import Optional

import numpy as np

from ..core.tensor import Tensor
from .dataset import Dataset, IterableDataset
from .sampler import BatchSampler

__all__ = ["DataLoader", "default_collate_fn", "get_worker_info"]

_worker_info = threading.local()
_pool_seq = itertools.count()  # unique shm ring names per pool


def get_worker_info():
    info = getattr(_worker_info, "info", None)
    if info is None:
        # process workers register in the standalone (import-light) module
        import sys
        ptw = sys.modules.get("paddle_tpu_worker")
        if ptw is not None:
            info = ptw.get_worker_info()
    return info


class WorkerInfo:
    def __init__(self, id, num_workers, dataset, seed):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset
        self.seed = seed


def default_collate_fn(batch):
    """Stack samples into batched numpy arrays (structure-preserving)."""
    sample = batch[0]
    if isinstance(sample, Tensor):
        return Tensor(np.stack([np.asarray(s._data) for s in batch]))
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    # (str, bytes) before np.generic: np.str_/np.bytes_ subclass both, and
    # string batches must stay lists (no string dtype on device)
    if isinstance(sample, (str, bytes)):
        return batch
    if isinstance(sample, (int, float, np.generic)):
        return np.asarray(batch)
    if isinstance(sample, dict):
        return {k: default_collate_fn([s[k] for s in batch]) for k in sample}
    if isinstance(sample, (list, tuple)):
        transposed = list(zip(*batch))
        out = [default_collate_fn(list(col)) for col in transposed]
        return out if isinstance(sample, list) else tuple(out)
    return batch


def _to_tensor_tree(obj):
    import jax.numpy as jnp
    if isinstance(obj, np.ndarray):
        return Tensor(jnp.asarray(obj))
    if isinstance(obj, dict):
        return {k: _to_tensor_tree(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = [_to_tensor_tree(v) for v in obj]
        return t if isinstance(obj, list) else tuple(t)
    return obj


class DataLoader:
    """Parity: paddle.io.DataLoader (return_list=True semantics)."""

    def __init__(self, dataset, feed_list=None, places=None, return_list=True,
                 batch_sampler=None, batch_size=1, shuffle=False,
                 drop_last=False, collate_fn=None, num_workers=0,
                 use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=120, worker_init_fn=None,
                 persistent_workers=False, mp_start_method=None):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = int(num_workers)
        self.timeout = timeout
        self.worker_init_fn = worker_init_fn
        self.prefetch_factor = prefetch_factor
        self.use_shared_memory = use_shared_memory
        self.persistent_workers = persistent_workers
        # "spawn" is the safe default: jax is multithreaded, so fork() from
        # a jax-initialised parent can deadlock the child. "fork" opt-in.
        self.mp_start_method = mp_start_method or os.environ.get(
            "PADDLE_TPU_DATALOADER_START_METHOD", "spawn")
        self._iterable = isinstance(dataset, IterableDataset)
        self._shm_state = None  # persistent worker pool (map-style only)
        if self._iterable:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                              batch_size=batch_size,
                                              drop_last=drop_last)

    def __len__(self):
        if self._iterable:
            raise TypeError("IterableDataset has no len()")
        return len(self.batch_sampler)

    def __call__(self):
        return self.__iter__()

    def __iter__(self):
        if self.num_workers > 0 and self.use_shared_memory:
            from .. import _native
            if _native.available():
                if self._iterable:
                    return self._iter_shm_iterable()
                return self._iter_shm_workers()
        if self._iterable:
            if self.num_workers > 0:
                return self._iter_iterable_threads()
            return self._iter_iterable()
        if self.num_workers == 0:
            return self._iter_single()
        return self._iter_multiprocess()

    def _iter_iterable(self):
        it = iter(self.dataset)
        while True:
            batch = list(itertools.islice(it, self.batch_size))
            if not batch:
                return
            if len(batch) < self.batch_size and self.drop_last:
                return
            yield _to_tensor_tree(self.collate_fn(batch))

    def _iter_single(self):
        for indices in self.batch_sampler:
            batch = [self.dataset[i] for i in indices]
            yield _to_tensor_tree(self.collate_fn(batch))

    # -- native shared-memory worker pool (map-style) ----------------------

    def _collate_for_worker(self):
        # the standalone worker module resolves "default" to its
        # numpy-only collate so light datasets avoid importing paddle_tpu
        return ("default" if self.collate_fn is default_collate_fn
                else self.collate_fn)

    def _spawn_shm_pool(self, iterable_spec):
        """Spawn a worker pool: one shm ring (results) and, for map-style
        datasets (iterable_spec None), one index queue per worker (tasks).
        Outstanding tasks are capped at num_workers*prefetch_factor, which
        bounds both the ring occupancy and the parent's reorder buffer —
        the reference bounds outstanding batches the same way
        (`dataloader_iter.py:370` _outstanding_capacity)."""
        import multiprocessing as mp

        import paddle_tpu_worker as worker_mod

        from .. import _native
        from ..utils.flags import flags

        so_path = _native._build()
        capacity = int(flags("shm_ring_bytes", 128 << 20))
        ring_name = (f"/pt_dl_{os.getpid()}_{id(self) & 0xFFFFFF}_"
                     f"{next(_pool_seq)}")
        ring = _native.ShmRing(ring_name, capacity=capacity, create=True)
        ctx = mp.get_context(self.mp_start_method)
        queues = (None if iterable_spec is not None
                  else [ctx.Queue() for _ in range(self.num_workers)])
        procs = []
        for w in range(self.num_workers):
            p = ctx.Process(
                target=worker_mod.worker_loop,
                args=(so_path, ring_name,
                      queues[w] if queues is not None else None,
                      self.dataset, self._collate_for_worker(), w,
                      self.num_workers, w, self.worker_init_fn,
                      iterable_spec),
                daemon=True)
            p.start()
            procs.append(p)
        return {"ring": ring, "queues": queues, "procs": procs,
                "epoch": 0, "busy": False, "stopped": False}

    @staticmethod
    def _stop_pool(st):
        if st is None or st["stopped"]:
            return
        st["stopped"] = True
        if st["queues"] is not None:
            for q in st["queues"]:
                try:
                    q.put(None)
                except Exception:
                    pass
        for p in st["procs"]:
            p.join(timeout=5)
        for p in st["procs"]:
            if p.is_alive():
                p.terminate()
                p.join(timeout=5)
        st["ring"].unlink()

    def _shm_pool_stop(self):
        st = self._shm_state
        self._shm_state = None
        self._stop_pool(st)

    def _pop_with_liveness(self, ring, procs, finished=()):
        """Pop from the ring in short slices, failing fast (with an
        actionable message) when a worker died instead of waiting out the
        full timeout."""
        import time
        deadline = time.monotonic() + self.timeout
        while True:
            payload = ring.pop(timeout_ms=1000)
            if payload is not None:
                return payload
            dead = [w for w, p in enumerate(procs)
                    if not p.is_alive() and w not in finished]
            if dead and ring.qsize() == 0:
                hint = ""
                if self.mp_start_method != "fork":
                    hint = (
                        f"; start method {self.mp_start_method!r} requires "
                        "your script's entry point to be guarded with "
                        "`if __name__ == '__main__':` (or pass "
                        "mp_start_method='fork')")
                raise RuntimeError(
                    f"DataLoader worker(s) {dead} exited unexpectedly"
                    f"{hint}")
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"DataLoader produced no batch for {self.timeout}s")

    def _iter_shm_workers(self):
        """OS-process workers + native shared-memory ring transport.

        Parity: reference multiprocess DataLoader with use_shared_memory
        (`dataloader_iter.py:370`, worker.py): workers produce collated
        batches; transport is a POSIX shm ring (no pipe copies); the
        parent reorders by batch index and uploads to device. Worker death
        is detected via pop timeout + liveness check, matching the
        reference's "DataLoader worker exited unexpectedly" behavior.
        """
        import paddle_tpu_worker as worker_mod

        # pool acquisition: reuse the persistent pool when it is idle;
        # a nested/concurrent iterator (or persistent_workers=False) gets
        # its own ephemeral pool so iterators never steal each other's
        # batches off a shared ring
        if self.persistent_workers and (
                self._shm_state is None or not self._shm_state["busy"]):
            if self._shm_state is None:
                self._shm_state = self._spawn_shm_pool(None)
            st = self._shm_state
            ephemeral = False
        else:
            st = self._spawn_shm_pool(None)
            ephemeral = True
        st["busy"] = True
        st["epoch"] += 1
        epoch = st["epoch"]
        ring, queues, procs = st["ring"], st["queues"], st["procs"]

        # stream tasks from the sampler (an epoch over a huge dataset must
        # not materialise every index list up front); only the outstanding
        # window lives in memory
        total = len(self.batch_sampler)
        task_iter = enumerate(self.batch_sampler)
        window = max(1, self.prefetch_factor)

        def _feed(worker_id):
            task = next(task_iter, None)
            if task is not None:
                queues[worker_id].put((epoch, task[0], list(task[1])))

        try:
            for w in range(self.num_workers):
                for _ in range(window):
                    _feed(w)
            received = 0
            next_idx = 0
            buffer = {}
            while received < total:
                payload = self._pop_with_liveness(ring, procs)
                kind, (ep, wid, bidx), body = pickle.loads(payload)
                if kind == worker_mod.MSG_ERROR:
                    raise RuntimeError(
                        f"DataLoader worker {wid} raised:\n{body}")
                if ep != epoch:
                    continue  # stale batch from an abandoned epoch
                received += 1
                _feed(wid)  # refill the worker that freed a slot
                buffer[bidx] = body
                while next_idx in buffer:
                    yield _to_tensor_tree(buffer.pop(next_idx))
                    next_idx += 1
            while next_idx in buffer:
                yield _to_tensor_tree(buffer.pop(next_idx))
                next_idx += 1
        except GeneratorExit:
            # iterator abandoned mid-epoch; a persistent pool survives —
            # stale in-flight batches are discarded by the epoch tag above
            st["busy"] = False
            if ephemeral:
                self._stop_pool(st)
            raise
        except BaseException:
            if st is self._shm_state:
                self._shm_state = None
            self._stop_pool(st)
            raise
        else:
            st["busy"] = False
            if ephemeral:
                self._stop_pool(st)

    def __del__(self):
        try:
            self._shm_pool_stop()
        except Exception:
            pass

    def _iter_shm_iterable(self):
        """IterableDataset over process workers: each worker iterates a
        dataset REPLICA; sharding across replicas is the dataset's job via
        get_worker_info() — the reference's (and torch's) IterableDataset
        contract. Batches are yielded in arrival order, so no reorder
        buffer exists and ring capacity is the only backpressure."""
        import paddle_tpu_worker as worker_mod

        st = self._spawn_shm_pool((self.batch_size, self.drop_last))
        try:
            finished = set()
            while len(finished) < self.num_workers:
                payload = self._pop_with_liveness(st["ring"], st["procs"],
                                                  finished=finished)
                kind, (ep, wid, bidx), body = pickle.loads(payload)
                if kind == worker_mod.MSG_ERROR:
                    raise RuntimeError(
                        f"DataLoader worker {wid} raised:\n{body}")
                if kind == worker_mod.MSG_DONE:
                    finished.add(wid)
                    continue
                yield _to_tensor_tree(body)
        finally:
            self._stop_pool(st)

    def _iter_iterable_threads(self):
        """Thread fallback for IterableDataset with num_workers>0 when the
        native ring is unavailable — same replica + get_worker_info
        semantics as the process path, so behavior does not depend on
        whether the native extension compiled."""
        done_q: queue_mod.Queue = queue_mod.Queue(
            maxsize=max(1, self.num_workers * self.prefetch_factor))
        stop = object()

        def worker(worker_id):
            try:
                import paddle_tpu_worker
                info = WorkerInfo(worker_id, self.num_workers, self.dataset,
                                  worker_id)
                _worker_info.info = info
                # also register in the standalone module so datasets that
                # shard via paddle_tpu_worker.get_worker_info() behave the
                # same with or without the native extension
                paddle_tpu_worker._worker_info.info = info
                if self.worker_init_fn is not None:
                    self.worker_init_fn(worker_id)
                it = iter(self.dataset)
                while True:
                    chunk = list(itertools.islice(it, self.batch_size))
                    if not chunk or (len(chunk) < self.batch_size
                                     and self.drop_last):
                        break
                    done_q.put(("batch", self.collate_fn(chunk)))
            except Exception as e:  # propagate to consumer
                done_q.put(("error", e))
            done_q.put((stop, None))

        threads = [threading.Thread(target=worker, args=(w,), daemon=True)
                   for w in range(self.num_workers)]
        for t in threads:
            t.start()
        done = 0
        while done < self.num_workers:
            kind, body = done_q.get(timeout=self.timeout)
            if kind is stop:
                done += 1
            elif kind == "error":
                raise body
            else:
                yield _to_tensor_tree(body)

    def _iter_multiprocess(self):
        """Thread-pool prefetch pipeline.

        Design note: the reference forks OS processes because CPython holds
        the GIL during numpy-heavy preprocessing; numpy releases the GIL for
        its kernels, and TPU hosts have many cores, so a thread pool +
        bounded queue gives the same overlap without pickling/shared-memory
        transport. (A C++ shared-memory ring like the reference's
        `use_shared_memory` path is a planned native extension.)
        """
        work_q: queue_mod.Queue = queue_mod.Queue()
        done_q: queue_mod.Queue = queue_mod.Queue(
            maxsize=self.num_workers * self.prefetch_factor)
        indices_list = list(self.batch_sampler)
        for i, idxs in enumerate(indices_list):
            work_q.put((i, idxs))
        stop = object()
        results = {}
        lock = threading.Lock()

        def worker(worker_id):
            _worker_info.info = WorkerInfo(worker_id, self.num_workers,
                                           self.dataset, worker_id)
            if self.worker_init_fn is not None:
                self.worker_init_fn(worker_id)
            while True:
                try:
                    item = work_q.get_nowait()
                except queue_mod.Empty:
                    return
                i, idxs = item
                batch = [self.dataset[j] for j in idxs]
                done_q.put((i, self.collate_fn(batch)))

        threads = [threading.Thread(target=worker, args=(w,), daemon=True)
                   for w in range(self.num_workers)]
        for t in threads:
            t.start()

        next_idx = 0
        received = 0
        total = len(indices_list)
        buffer = {}
        while received < total:
            i, batch = done_q.get(timeout=self.timeout)
            buffer[i] = batch
            received += 1
            while next_idx in buffer:
                yield _to_tensor_tree(buffer.pop(next_idx))
                next_idx += 1
        while next_idx in buffer:
            yield _to_tensor_tree(buffer.pop(next_idx))
            next_idx += 1
