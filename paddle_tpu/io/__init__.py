"""paddle_tpu.io — Dataset/DataLoader.

Parity: reference `python/paddle/io/` (Dataset, IterableDataset,
TensorDataset, Subset, random_split, samplers, BatchSampler, DataLoader
with multiprocess workers).
"""
from .dataset import (  # noqa: F401
    Dataset, IterableDataset, TensorDataset, ComposeDataset, ChainDataset,
    Subset, random_split, ConcatDataset,
)
from .sampler import (  # noqa: F401
    Sampler, SequenceSampler, RandomSampler, WeightedRandomSampler,
    BatchSampler, DistributedBatchSampler, SubsetRandomSampler,
)
from .dataloader import DataLoader, default_collate_fn, get_worker_info  # noqa: F401

__all__ = [
    "Dataset", "IterableDataset", "TensorDataset", "ComposeDataset",
    "ChainDataset", "Subset", "random_split", "ConcatDataset", "Sampler",
    "SequenceSampler", "RandomSampler", "WeightedRandomSampler",
    "BatchSampler", "DistributedBatchSampler", "SubsetRandomSampler",
    "DataLoader", "default_collate_fn", "get_worker_info",
]
