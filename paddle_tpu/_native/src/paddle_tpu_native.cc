// paddle_tpu native runtime: C++ components for the host-side runtime.
//
// TPU-native equivalents of the reference's native runtime pieces:
//   * ShmRing  — a POSIX shared-memory MPSC ring buffer used as the
//     DataLoader worker->parent batch transport (parity with the reference's
//     shared-memory LoDTensor transport used by
//     python/paddle/io/dataloader/worker.py when use_shared_memory=True).
//   * TCPStore — a TCP key/value rendezvous store (parity with
//     paddle/phi/core/distributed/store/tcp_store.cc) used for process
//     bootstrap by paddle_tpu.distributed. On TPU the collectives themselves
//     are XLA's; only the bootstrap/rendezvous role survives, so the store
//     is a stateless request/reply server (clients poll for blocking waits).
//
// Exposed as the CPython extension module `_paddle_tpu_native` (built with
// the raw CPython C API; pybind11 is not available in this image).

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <pthread.h>
#include <stdint.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <time.h>
#include <unistd.h>

#include <atomic>
#include <cstdio>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// ShmRing: MPSC byte-message ring in POSIX shared memory.
// Layout: [RingHeader][data bytes ...]; messages are [u64 len][payload],
// written contiguously with wraparound (a message never straddles the end:
// if it would, the writer pads with a SKIP marker and restarts at offset 0).
// Synchronisation: one process-shared robust mutex + two condvars.
// ---------------------------------------------------------------------------

constexpr uint64_t kRingMagic = 0x70617474707572ULL;  // "pattpur"
constexpr uint64_t kSkipMarker = ~0ULL;

struct RingHeader {
  uint64_t magic;
  uint64_t capacity;   // bytes in data region
  uint64_t head;       // write offset into data region (wrapped)
  uint64_t tail;       // read offset into data region (wrapped)
  uint64_t used;       // bytes currently occupied
  uint64_t n_msgs;     // messages currently queued
  pthread_mutex_t mutex;
  pthread_cond_t not_empty;
  pthread_cond_t not_full;
};

struct ShmRing {
  PyObject_HEAD
  char name[256];
  int fd;
  RingHeader* hdr;
  uint8_t* data;
  uint64_t capacity;
  int creator;
  int closed;
};

// All ring deadlines use CLOCK_MONOTONIC (condvars are initialised with
// pthread_condattr_setclock) so NTP wall-clock steps cannot fire or
// stretch timeouts mid-training.
static void timespec_in_ms(struct timespec* ts, long ms) {
  clock_gettime(CLOCK_MONOTONIC, ts);
  ts->tv_sec += ms / 1000;
  ts->tv_nsec += (ms % 1000) * 1000000L;
  if (ts->tv_nsec >= 1000000000L) {
    ts->tv_sec += 1;
    ts->tv_nsec -= 1000000000L;
  }
}

// Lock that recovers a robust mutex whose owner died (a killed DataLoader
// worker must not wedge the parent). `recovered` is set when EOWNERDEAD
// fired: the dead owner may have left a half-written header, so the caller
// MUST validate ring invariants before trusting it.
static int robust_timedlock(pthread_mutex_t* m, struct timespec* ts,
                            int* recovered) {
  int rc = pthread_mutex_clocklock(m, CLOCK_MONOTONIC, ts);
  if (rc == EOWNERDEAD) {
    pthread_mutex_consistent(m);
    if (recovered) *recovered = 1;
    rc = 0;
  }
  return rc;
}

// cond_timedwait re-acquires the mutex on return; if the previous owner
// died it reports EOWNERDEAD, which must be recovered (not treated as a
// timeout) or a later unlock would mark the mutex ENOTRECOVERABLE and
// wedge the ring for every surviving process.
static int robust_cond_timedwait(pthread_cond_t* c, pthread_mutex_t* m,
                                 struct timespec* ts, int* recovered) {
  int rc = pthread_cond_timedwait(c, m, ts);
  if (rc == EOWNERDEAD) {
    pthread_mutex_consistent(m);
    if (recovered) *recovered = 1;
    rc = 0;
  }
  return rc;
}

// Header invariants. push/pop advance (head|tail, used, n_msgs) together
// under the lock, and a message never straddles the region end, so a
// consistent header always satisfies head == (tail + used) % capacity.
// A SIGKILLed owner can leave any prefix of its stores applied; a recovered
// lock must re-check before parsing, else a mis-framed ring yields an
// out-of-bounds payload.assign in pop.
static bool ring_header_valid(const RingHeader* h, uint64_t cap) {
  if (h->magic != kRingMagic || h->capacity != cap) return false;
  if (h->head >= cap || h->tail >= cap || h->used > cap) return false;
  if (h->head != (h->tail + h->used) % cap) return false;
  if (h->n_msgs > 0 && h->used < 8 * h->n_msgs) return false;
  if (h->n_msgs == 0 && h->used != 0 && h->used != cap - h->tail)
    return false;  // only tail-end skip padding may remain
  return true;
}

// Poison the ring (magic cleared) and wake every waiter so they observe
// the corruption instead of blocking forever.
static void ring_poison(RingHeader* h) {
  h->magic = 0;
  pthread_cond_broadcast(&h->not_empty);
  pthread_cond_broadcast(&h->not_full);
}

static PyObject* ShmRingError;

// tp_new zero-initialises the struct; mark the object closed (fd would
// read as 0 == stdin) until init fully succeeds, so dealloc after a
// failed/partial __init__ never closes an fd it does not own.
static PyObject* ShmRing_new(PyTypeObject* type, PyObject*, PyObject*) {
  ShmRing* self = (ShmRing*)type->tp_alloc(type, 0);
  if (self) {
    self->fd = -1;
    self->closed = 1;
  }
  return (PyObject*)self;
}

static int ShmRing_init(ShmRing* self, PyObject* args, PyObject* kwds) {
  const char* name;
  unsigned long long capacity = 0;
  int create = 0;
  static const char* kwlist[] = {"name", "capacity", "create", nullptr};
  if (!PyArg_ParseTupleAndKeywords(args, kwds, "s|Kp",
                                   const_cast<char**>(kwlist), &name,
                                   &capacity, &create))
    return -1;
  snprintf(self->name, sizeof(self->name), "%s", name);
  self->creator = create;
  self->closed = 1;  // flipped to 0 only on full success
  size_t total = 0;
  if (create) {
    if (capacity < 4096) {
      PyErr_SetString(ShmRingError, "capacity must be >= 4096 bytes");
      return -1;
    }
    shm_unlink(name);  // stale segment from a crashed run
    self->fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
    if (self->fd < 0) {
      PyErr_Format(ShmRingError, "shm_open(%s) failed: %s", name,
                   strerror(errno));
      return -1;
    }
    total = sizeof(RingHeader) + capacity;
    if (ftruncate(self->fd, (off_t)total) != 0) {
      PyErr_Format(ShmRingError, "ftruncate failed: %s", strerror(errno));
      close(self->fd);
      self->fd = -1;
      shm_unlink(name);
      return -1;
    }
  } else {
    self->fd = shm_open(name, O_RDWR, 0600);
    if (self->fd < 0) {
      PyErr_Format(ShmRingError, "shm_open(%s) failed: %s", name,
                   strerror(errno));
      return -1;
    }
    struct stat st;
    if (fstat(self->fd, &st) != 0 || (size_t)st.st_size < sizeof(RingHeader)) {
      PyErr_SetString(ShmRingError, "shm segment too small");
      close(self->fd);
      self->fd = -1;
      return -1;
    }
    total = (size_t)st.st_size;
    capacity = total - sizeof(RingHeader);
  }
  void* mem =
      mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, self->fd, 0);
  if (mem == MAP_FAILED) {
    PyErr_Format(ShmRingError, "mmap failed: %s", strerror(errno));
    close(self->fd);
    self->fd = -1;
    if (create) shm_unlink(name);
    return -1;
  }
  self->hdr = (RingHeader*)mem;
  self->data = (uint8_t*)mem + sizeof(RingHeader);
  self->capacity = capacity;
  if (create) {
    memset(self->hdr, 0, sizeof(RingHeader));
    self->hdr->capacity = capacity;
    pthread_mutexattr_t ma;
    pthread_mutexattr_init(&ma);
    pthread_mutexattr_setpshared(&ma, PTHREAD_PROCESS_SHARED);
    pthread_mutexattr_setrobust(&ma, PTHREAD_MUTEX_ROBUST);
    pthread_mutex_init(&self->hdr->mutex, &ma);
    pthread_mutexattr_destroy(&ma);
    pthread_condattr_t ca;
    pthread_condattr_init(&ca);
    pthread_condattr_setpshared(&ca, PTHREAD_PROCESS_SHARED);
    pthread_condattr_setclock(&ca, CLOCK_MONOTONIC);
    pthread_cond_init(&self->hdr->not_empty, &ca);
    pthread_cond_init(&self->hdr->not_full, &ca);
    pthread_condattr_destroy(&ca);
    std::atomic_thread_fence(std::memory_order_seq_cst);
    self->hdr->magic = kRingMagic;
  } else if (self->hdr->magic != kRingMagic) {
    PyErr_SetString(ShmRingError, "shm segment not initialised");
    munmap(mem, total);
    close(self->fd);
    self->fd = -1;
    return -1;
  }
  self->closed = 0;
  return 0;
}

static void ShmRing_close_impl(ShmRing* self, int unlink_seg) {
  if (self->closed) return;
  self->closed = 1;
  munmap((void*)self->hdr, sizeof(RingHeader) + self->capacity);
  close(self->fd);
  if (unlink_seg) shm_unlink(self->name);
}

static void ShmRing_dealloc(ShmRing* self) {
  ShmRing_close_impl(self, 0);
  Py_TYPE(self)->tp_free((PyObject*)self);
}

// Contiguous free bytes from head to either tail or end-of-region.
static bool ring_fit(RingHeader* h, uint64_t need) {
  uint64_t cap = h->capacity;
  if (h->used + need > cap) return false;
  uint64_t head = h->head;
  uint64_t room_to_end = cap - head;
  if (need <= room_to_end) return true;
  // must pad to end (SKIP) and restart at 0
  return h->used + room_to_end + need <= cap && need <= h->tail;
}

static PyObject* ShmRing_push(ShmRing* self, PyObject* args, PyObject* kwds) {
  Py_buffer buf;
  long timeout_ms = 30000;
  static const char* kwlist[] = {"data", "timeout_ms", nullptr};
  if (!PyArg_ParseTupleAndKeywords(args, kwds, "y*|l",
                                   const_cast<char**>(kwlist), &buf,
                                   &timeout_ms))
    return nullptr;
  uint64_t need = 8 + (uint64_t)buf.len;
  if (need + 8 > self->capacity) {  // +8: room for a SKIP header
    PyBuffer_Release(&buf);
    PyErr_Format(ShmRingError,
                 "message of %zd bytes exceeds ring capacity %llu "
                 "(raise FLAGS_shm_ring_bytes)",
                 buf.len, (unsigned long long)self->capacity);
    return nullptr;
  }
  RingHeader* h = self->hdr;
  int ok = 0;
  int corrupt = 0;
  Py_BEGIN_ALLOW_THREADS;
  struct timespec ts;
  timespec_in_ms(&ts, timeout_ms);
  int recovered = 0;
  if (robust_timedlock(&h->mutex, &ts, &recovered) == 0) {
    int rc = 0;
    while (!corrupt && !ring_fit(h, need) && rc == 0) {
      if ((recovered && !ring_header_valid(h, self->capacity)) ||
          h->magic != kRingMagic) {
        corrupt = 1;
        break;
      }
      recovered = 0;
      rc = robust_cond_timedwait(&h->not_full, &h->mutex, &ts, &recovered);
    }
    if (!corrupt && ((recovered && !ring_header_valid(h, self->capacity)) ||
                     h->magic != kRingMagic))
      corrupt = 1;
    if (corrupt) {
      ring_poison(h);
      pthread_mutex_unlock(&h->mutex);
    } else if (rc == 0) {
      uint64_t cap = h->capacity;
      uint64_t head = h->head;
      if (need > cap - head) {
        // pad the tail-end with a skip marker; consume that space
        if (cap - head >= 8) memcpy(self->data + head, &kSkipMarker, 8);
        h->used += cap - head;
        head = 0;
      }
      uint64_t len = (uint64_t)buf.len;
      memcpy(self->data + head, &len, 8);
      memcpy(self->data + head + 8, buf.buf, buf.len);
      h->head = (head + need) % cap;
      h->used += need;
      h->n_msgs += 1;
      ok = 1;
      pthread_cond_signal(&h->not_empty);
      pthread_mutex_unlock(&h->mutex);
    } else {
      pthread_mutex_unlock(&h->mutex);
    }
  }
  Py_END_ALLOW_THREADS;
  PyBuffer_Release(&buf);
  if (corrupt) {
    PyErr_SetString(ShmRingError,
                    "shm ring corrupted: a worker died mid-push and left an "
                    "inconsistent header (ring poisoned; recreate it)");
    return nullptr;
  }
  if (!ok) Py_RETURN_FALSE;
  Py_RETURN_TRUE;
}

static PyObject* ShmRing_pop(ShmRing* self, PyObject* args, PyObject* kwds) {
  long timeout_ms = 30000;
  static const char* kwlist[] = {"timeout_ms", nullptr};
  if (!PyArg_ParseTupleAndKeywords(args, kwds, "|l",
                                   const_cast<char**>(kwlist), &timeout_ms))
    return nullptr;
  RingHeader* h = self->hdr;
  std::string payload;  // copied out under the lock: space may be reused
                        // by a writer the moment `used` shrinks
  int ok = 0;
  int corrupt = 0;
  Py_BEGIN_ALLOW_THREADS;
  struct timespec ts;
  timespec_in_ms(&ts, timeout_ms);
  int recovered = 0;
  if (robust_timedlock(&h->mutex, &ts, &recovered) == 0) {
    int rc = 0;
    while (!corrupt && h->n_msgs == 0 && rc == 0) {
      if ((recovered && !ring_header_valid(h, self->capacity)) ||
          h->magic != kRingMagic) {
        corrupt = 1;
        break;
      }
      recovered = 0;
      rc = robust_cond_timedwait(&h->not_empty, &h->mutex, &ts, &recovered);
    }
    if (!corrupt && ((recovered && !ring_header_valid(h, self->capacity)) ||
                     h->magic != kRingMagic))
      corrupt = 1;
    if (!corrupt && rc == 0) {
      uint64_t cap = h->capacity;
      uint64_t tail = h->tail;
      if (cap - tail < 8) {
        h->used -= cap - tail;
        tail = 0;
      } else {
        uint64_t marker;
        memcpy(&marker, self->data + tail, 8);
        if (marker == kSkipMarker) {
          h->used -= cap - tail;
          tail = 0;
        }
      }
      uint64_t len;
      memcpy(&len, self->data + tail, 8);
      // never trust the on-shm length blindly: bound it by the framing
      // invariants or a mis-framed ring reads out of bounds. Compare in
      // subtracted form — '8 + len' overflows uint64 for garbage lengths
      // near 2^64 and would slip past an additive check.
      if (h->used < 8 || len > h->used - 8 ||
          cap - tail < 8 || len > cap - tail - 8) {
        corrupt = 1;
      } else {
        payload.assign((const char*)(self->data + tail + 8), len);
        h->tail = (tail + 8 + len) % cap;
        h->used -= 8 + len;
        h->n_msgs -= 1;
        ok = 1;
        pthread_cond_broadcast(&h->not_full);
      }
    }
    if (corrupt) ring_poison(h);
    pthread_mutex_unlock(&h->mutex);
  }
  Py_END_ALLOW_THREADS;
  if (corrupt) {
    PyErr_SetString(ShmRingError,
                    "shm ring corrupted: a worker died mid-operation and "
                    "left an inconsistent header (ring poisoned)");
    return nullptr;
  }
  if (!ok) Py_RETURN_NONE;
  return PyBytes_FromStringAndSize(payload.data(), (Py_ssize_t)payload.size());
}

static PyObject* ShmRing_qsize(ShmRing* self, PyObject*) {
  return PyLong_FromUnsignedLongLong(self->hdr->n_msgs);
}

static PyObject* ShmRing_close(ShmRing* self, PyObject*) {
  ShmRing_close_impl(self, 0);
  Py_RETURN_NONE;
}

static PyObject* ShmRing_unlink(ShmRing* self, PyObject*) {
  ShmRing_close_impl(self, 1);
  Py_RETURN_NONE;
}

static PyMethodDef ShmRing_methods[] = {
    {"push", (PyCFunction)ShmRing_push, METH_VARARGS | METH_KEYWORDS,
     "push(data: bytes, timeout_ms=30000) -> bool"},
    {"pop", (PyCFunction)ShmRing_pop, METH_VARARGS | METH_KEYWORDS,
     "pop(timeout_ms=30000) -> bytes | None"},
    {"qsize", (PyCFunction)ShmRing_qsize, METH_NOARGS, "queued message count"},
    {"close", (PyCFunction)ShmRing_close, METH_NOARGS, "unmap"},
    {"unlink", (PyCFunction)ShmRing_unlink, METH_NOARGS, "unmap + unlink"},
    {nullptr, nullptr, 0, nullptr}};

static PyTypeObject ShmRingType = []() {
  PyTypeObject t = {PyVarObject_HEAD_INIT(nullptr, 0)};
  t.tp_name = "_paddle_tpu_native.ShmRing";
  t.tp_basicsize = sizeof(ShmRing);
  t.tp_flags = Py_TPFLAGS_DEFAULT;
  t.tp_doc = "POSIX shared-memory MPSC ring buffer";
  t.tp_new = ShmRing_new;
  t.tp_init = (initproc)ShmRing_init;
  t.tp_dealloc = (destructor)ShmRing_dealloc;
  t.tp_methods = ShmRing_methods;
  return t;
}();

// ---------------------------------------------------------------------------
// TCPStore
// Protocol: request  = u8 op | u32 keylen | key | (op payload)
//           ops: 1=SET(u32 vallen|val) 2=GET 3=ADD(i64) 4=CHECK 5=DEL
//                6=NUMKEYS
//           reply: SET -> u8(1); GET -> u8 found [u32 vallen|val];
//                  ADD -> i64 newval; CHECK -> u8 found; DEL -> u8;
//                  NUMKEYS -> u32
// Blocking get/wait is client-side polling over CHECK/GET.
// ---------------------------------------------------------------------------

enum StoreOp : uint8_t {
  OP_SET = 1,
  OP_GET = 2,
  OP_ADD = 3,
  OP_CHECK = 4,
  OP_DEL = 5,
  OP_NUMKEYS = 6,
};

static bool send_all(int fd, const void* buf, size_t n) {
  const char* p = (const char*)buf;
  while (n) {
    ssize_t k = send(fd, p, n, MSG_NOSIGNAL);
    if (k <= 0) {
      if (k < 0 && (errno == EINTR)) continue;
      return false;
    }
    p += k;
    n -= (size_t)k;
  }
  return true;
}

static bool recv_all(int fd, void* buf, size_t n) {
  char* p = (char*)buf;
  while (n) {
    ssize_t k = recv(fd, p, n, 0);
    if (k <= 0) {
      if (k < 0 && errno == EINTR) continue;
      return false;
    }
    p += k;
    n -= (size_t)k;
  }
  return true;
}

struct StoreServer {
  int listen_fd = -1;
  std::thread accept_thread;
  std::atomic<bool> stop{false};
  std::mutex mu;
  std::unordered_map<std::string, std::string> kv;
  std::mutex conn_mu;
  std::vector<std::thread> conns;
  std::vector<int> conn_fds;

  void handle_conn(int fd) {
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    while (!stop.load()) {
      uint8_t op;
      uint32_t keylen;
      if (!recv_all(fd, &op, 1) || !recv_all(fd, &keylen, 4)) break;
      if (keylen > (1u << 20)) break;
      std::string key(keylen, '\0');
      if (keylen && !recv_all(fd, &key[0], keylen)) break;
      bool alive = true;
      switch (op) {
        case OP_SET: {
          uint32_t vallen;
          if (!recv_all(fd, &vallen, 4)) { alive = false; break; }
          std::string val(vallen, '\0');
          if (vallen && !recv_all(fd, &val[0], vallen)) { alive = false; break; }
          {
            std::lock_guard<std::mutex> g(mu);
            kv[key] = std::move(val);
          }
          uint8_t ok = 1;
          alive = send_all(fd, &ok, 1);
          break;
        }
        case OP_GET: {
          std::string val;
          uint8_t found = 0;
          {
            std::lock_guard<std::mutex> g(mu);
            auto it = kv.find(key);
            if (it != kv.end()) {
              found = 1;
              val = it->second;
            }
          }
          alive = send_all(fd, &found, 1);
          if (alive && found) {
            uint32_t vallen = (uint32_t)val.size();
            alive = send_all(fd, &vallen, 4) &&
                    (vallen == 0 || send_all(fd, val.data(), vallen));
          }
          break;
        }
        case OP_ADD: {
          int64_t delta;
          if (!recv_all(fd, &delta, 8)) { alive = false; break; }
          int64_t newval;
          {
            std::lock_guard<std::mutex> g(mu);
            int64_t cur = 0;
            auto it = kv.find(key);
            if (it != kv.end() && it->second.size() == 8)
              memcpy(&cur, it->second.data(), 8);
            newval = cur + delta;
            std::string v(8, '\0');
            memcpy(&v[0], &newval, 8);
            kv[key] = std::move(v);
          }
          alive = send_all(fd, &newval, 8);
          break;
        }
        case OP_CHECK: {
          uint8_t found;
          {
            std::lock_guard<std::mutex> g(mu);
            found = kv.count(key) ? 1 : 0;
          }
          alive = send_all(fd, &found, 1);
          break;
        }
        case OP_DEL: {
          uint8_t erased;
          {
            std::lock_guard<std::mutex> g(mu);
            erased = kv.erase(key) ? 1 : 0;
          }
          alive = send_all(fd, &erased, 1);
          break;
        }
        case OP_NUMKEYS: {
          uint32_t n;
          {
            std::lock_guard<std::mutex> g(mu);
            n = (uint32_t)kv.size();
          }
          alive = send_all(fd, &n, 4);
          break;
        }
        default:
          alive = false;
      }
      if (!alive) break;
    }
    close(fd);
  }

  bool start(const char* host, int port, std::string* err) {
    listen_fd = socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd < 0) {
      *err = strerror(errno);
      return false;
    }
    int one = 1;
    setsockopt(listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons((uint16_t)port);
    addr.sin_addr.s_addr =
        host && *host ? inet_addr(host) : htonl(INADDR_ANY);
    if (bind(listen_fd, (sockaddr*)&addr, sizeof(addr)) != 0 ||
        listen(listen_fd, 128) != 0) {
      *err = strerror(errno);
      close(listen_fd);
      listen_fd = -1;
      return false;
    }
    accept_thread = std::thread([this]() {
      while (!stop.load()) {
        pollfd pfd{listen_fd, POLLIN, 0};
        int pr = poll(&pfd, 1, 200);
        if (pr <= 0) continue;
        int fd = accept(listen_fd, nullptr, nullptr);
        if (fd < 0) continue;
        std::lock_guard<std::mutex> g(conn_mu);
        conn_fds.push_back(fd);
        conns.emplace_back([this, fd]() { handle_conn(fd); });
      }
    });
    return true;
  }

  void shutdown() {
    bool expected = false;
    if (!stop.compare_exchange_strong(expected, true)) return;
    if (accept_thread.joinable()) accept_thread.join();
    if (listen_fd >= 0) close(listen_fd);
    {
      // unblock handler threads stuck in recv()
      std::lock_guard<std::mutex> g(conn_mu);
      for (int fd : conn_fds) ::shutdown(fd, SHUT_RDWR);
    }
    for (auto& t : conns)
      if (t.joinable()) t.join();
    conns.clear();
    conn_fds.clear();
  }
};

struct TCPStore {
  PyObject_HEAD
  StoreServer* server;  // non-null on master
  int fd;               // client connection
  long timeout_ms;
  // serialises request/reply transactions: the store object is a
  // process-wide singleton used from several Python threads (heartbeats,
  // barriers) and the GIL is released around socket IO
  pthread_mutex_t io_mu;
};

struct IoGuard {
  pthread_mutex_t* m;
  explicit IoGuard(pthread_mutex_t* mu) : m(mu) { pthread_mutex_lock(m); }
  ~IoGuard() { pthread_mutex_unlock(m); }
};

static PyObject* TCPStoreError;

static PyObject* TCPStore_new(PyTypeObject* type, PyObject*, PyObject*) {
  TCPStore* self = (TCPStore*)type->tp_alloc(type, 0);
  if (self) {
    self->fd = -1;
    self->server = nullptr;
    pthread_mutex_init(&self->io_mu, nullptr);
  }
  return (PyObject*)self;
}

static int connect_with_retry(const char* host, int port, long timeout_ms) {
  struct timespec start;
  clock_gettime(CLOCK_MONOTONIC, &start);
  for (;;) {
    int fd = socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return -1;
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons((uint16_t)port);
    hostent* he = gethostbyname(host);
    if (he)
      memcpy(&addr.sin_addr, he->h_addr_list[0], he->h_length);
    else
      addr.sin_addr.s_addr = inet_addr(host);
    if (connect(fd, (sockaddr*)&addr, sizeof(addr)) == 0) {
      int one = 1;
      setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      return fd;
    }
    close(fd);
    struct timespec now;
    clock_gettime(CLOCK_MONOTONIC, &now);
    long elapsed = (now.tv_sec - start.tv_sec) * 1000 +
                   (now.tv_nsec - start.tv_nsec) / 1000000;
    if (elapsed > timeout_ms) return -1;
    usleep(50 * 1000);
  }
}

static int TCPStore_init(TCPStore* self, PyObject* args, PyObject* kwds) {
  const char* host;
  int port;
  int is_master = 0;
  long timeout_ms = 120000;
  static const char* kwlist[] = {"host", "port", "is_master", "timeout_ms",
                                 nullptr};
  if (!PyArg_ParseTupleAndKeywords(args, kwds, "si|pl",
                                   const_cast<char**>(kwlist), &host, &port,
                                   &is_master, &timeout_ms))
    return -1;
  self->timeout_ms = timeout_ms;
  if (is_master) {
    self->server = new StoreServer();
    std::string err;
    bool ok;
    Py_BEGIN_ALLOW_THREADS;
    ok = self->server->start(nullptr, port, &err);
    Py_END_ALLOW_THREADS;
    if (!ok) {
      PyErr_Format(TCPStoreError, "TCPStore bind(%s:%d) failed: %s", host,
                   port, err.c_str());
      delete self->server;
      self->server = nullptr;
      return -1;
    }
  }
  int fd;
  Py_BEGIN_ALLOW_THREADS;
  fd = connect_with_retry(is_master ? "127.0.0.1" : host, port, timeout_ms);
  Py_END_ALLOW_THREADS;
  if (fd < 0) {
    PyErr_Format(TCPStoreError, "TCPStore connect(%s:%d) timed out", host,
                 port);
    return -1;
  }
  self->fd = fd;
  return 0;
}

static void TCPStore_dealloc(TCPStore* self) {
  pthread_mutex_destroy(&self->io_mu);
  if (self->fd >= 0) close(self->fd);
  if (self->server) {
    Py_BEGIN_ALLOW_THREADS;
    self->server->shutdown();
    Py_END_ALLOW_THREADS;
    delete self->server;
  }
  Py_TYPE(self)->tp_free((PyObject*)self);
}

static bool store_send_req(TCPStore* self, uint8_t op, const char* key,
                           Py_ssize_t keylen, const void* payload,
                           size_t paylen) {
  uint32_t kl = (uint32_t)keylen;
  return send_all(self->fd, &op, 1) && send_all(self->fd, &kl, 4) &&
         (kl == 0 || send_all(self->fd, key, kl)) &&
         (paylen == 0 || send_all(self->fd, payload, paylen));
}

static PyObject* TCPStore_set(TCPStore* self, PyObject* args) {
  const char* key;
  Py_ssize_t keylen;
  Py_buffer val;
  if (!PyArg_ParseTuple(args, "s#y*", &key, &keylen, &val)) return nullptr;
  bool ok;
  Py_BEGIN_ALLOW_THREADS;
  IoGuard g(&self->io_mu);
  uint32_t vallen = (uint32_t)val.len;
  ok = store_send_req(self, OP_SET, key, keylen, nullptr, 0) &&
       send_all(self->fd, &vallen, 4) &&
       (vallen == 0 || send_all(self->fd, val.buf, vallen));
  uint8_t ack;
  ok = ok && recv_all(self->fd, &ack, 1);
  Py_END_ALLOW_THREADS;
  PyBuffer_Release(&val);
  if (!ok) {
    PyErr_SetString(TCPStoreError, "set: connection lost");
    return nullptr;
  }
  Py_RETURN_NONE;
}

// returns: 1 found, 0 not found, -1 connection error
static int store_get_once(TCPStore* self, const char* key, Py_ssize_t keylen,
                          std::string* out) {
  if (!store_send_req(self, OP_GET, key, keylen, nullptr, 0)) return -1;
  uint8_t found;
  if (!recv_all(self->fd, &found, 1)) return -1;
  if (!found) return 0;
  uint32_t vallen;
  if (!recv_all(self->fd, &vallen, 4)) return -1;
  out->resize(vallen);
  if (vallen && !recv_all(self->fd, &(*out)[0], vallen)) return -1;
  return 1;
}

static PyObject* TCPStore_get(TCPStore* self, PyObject* args, PyObject* kwds) {
  const char* key;
  Py_ssize_t keylen;
  int wait = 1;
  static const char* kwlist[] = {"key", "wait", nullptr};
  if (!PyArg_ParseTupleAndKeywords(args, kwds, "s#|p",
                                   const_cast<char**>(kwlist), &key, &keylen,
                                   &wait))
    return nullptr;
  std::string val;
  int rc = 0;
  Py_BEGIN_ALLOW_THREADS;
  struct timespec start;
  clock_gettime(CLOCK_MONOTONIC, &start);
  for (;;) {
    {
      IoGuard g(&self->io_mu);
      rc = store_get_once(self, key, keylen, &val);
    }
    if (rc != 0 || !wait) break;
    struct timespec now;
    clock_gettime(CLOCK_MONOTONIC, &now);
    long elapsed = (now.tv_sec - start.tv_sec) * 1000 +
                   (now.tv_nsec - start.tv_nsec) / 1000000;
    if (elapsed > self->timeout_ms) {
      rc = -2;
      break;
    }
    usleep(10 * 1000);
  }
  Py_END_ALLOW_THREADS;
  if (rc == -1) {
    PyErr_SetString(TCPStoreError, "get: connection lost");
    return nullptr;
  }
  if (rc == -2) {
    PyErr_Format(PyExc_TimeoutError, "get(%s) timed out after %ld ms", key,
                 self->timeout_ms);
    return nullptr;
  }
  if (rc == 0) Py_RETURN_NONE;
  return PyBytes_FromStringAndSize(val.data(), (Py_ssize_t)val.size());
}

static PyObject* TCPStore_add(TCPStore* self, PyObject* args) {
  const char* key;
  Py_ssize_t keylen;
  long long delta;
  if (!PyArg_ParseTuple(args, "s#L", &key, &keylen, &delta)) return nullptr;
  int64_t d = (int64_t)delta, newval = 0;
  bool ok;
  Py_BEGIN_ALLOW_THREADS;
  IoGuard g(&self->io_mu);
  ok = store_send_req(self, OP_ADD, key, keylen, &d, 8) &&
       recv_all(self->fd, &newval, 8);
  Py_END_ALLOW_THREADS;
  if (!ok) {
    PyErr_SetString(TCPStoreError, "add: connection lost");
    return nullptr;
  }
  return PyLong_FromLongLong(newval);
}

static PyObject* TCPStore_check(TCPStore* self, PyObject* args) {
  const char* key;
  Py_ssize_t keylen;
  if (!PyArg_ParseTuple(args, "s#", &key, &keylen)) return nullptr;
  uint8_t found = 0;
  bool ok;
  Py_BEGIN_ALLOW_THREADS;
  IoGuard g(&self->io_mu);
  ok = store_send_req(self, OP_CHECK, key, keylen, nullptr, 0) &&
       recv_all(self->fd, &found, 1);
  Py_END_ALLOW_THREADS;
  if (!ok) {
    PyErr_SetString(TCPStoreError, "check: connection lost");
    return nullptr;
  }
  return PyBool_FromLong(found);
}

static PyObject* TCPStore_delete_key(TCPStore* self, PyObject* args) {
  const char* key;
  Py_ssize_t keylen;
  if (!PyArg_ParseTuple(args, "s#", &key, &keylen)) return nullptr;
  uint8_t erased = 0;
  bool ok;
  Py_BEGIN_ALLOW_THREADS;
  IoGuard g(&self->io_mu);
  ok = store_send_req(self, OP_DEL, key, keylen, nullptr, 0) &&
       recv_all(self->fd, &erased, 1);
  Py_END_ALLOW_THREADS;
  if (!ok) {
    PyErr_SetString(TCPStoreError, "delete_key: connection lost");
    return nullptr;
  }
  return PyBool_FromLong(erased);
}

static PyObject* TCPStore_num_keys(TCPStore* self, PyObject*) {
  uint32_t n = 0;
  bool ok;
  Py_BEGIN_ALLOW_THREADS;
  IoGuard g(&self->io_mu);
  ok = store_send_req(self, OP_NUMKEYS, "", 0, nullptr, 0) &&
       recv_all(self->fd, &n, 4);
  Py_END_ALLOW_THREADS;
  if (!ok) {
    PyErr_SetString(TCPStoreError, "num_keys: connection lost");
    return nullptr;
  }
  return PyLong_FromUnsignedLong(n);
}

static PyMethodDef TCPStore_methods[] = {
    {"set", (PyCFunction)TCPStore_set, METH_VARARGS,
     "set(key: str, value: bytes)"},
    {"get", (PyCFunction)TCPStore_get, METH_VARARGS | METH_KEYWORDS,
     "get(key, wait=True) -> bytes | None (polls until timeout when wait)"},
    {"add", (PyCFunction)TCPStore_add, METH_VARARGS,
     "add(key, delta) -> new i64 value"},
    {"check", (PyCFunction)TCPStore_check, METH_VARARGS,
     "check(key) -> bool"},
    {"delete_key", (PyCFunction)TCPStore_delete_key, METH_VARARGS,
     "delete_key(key) -> bool"},
    {"num_keys", (PyCFunction)TCPStore_num_keys, METH_NOARGS,
     "num_keys() -> int"},
    {nullptr, nullptr, 0, nullptr}};

static PyTypeObject TCPStoreType = []() {
  PyTypeObject t = {PyVarObject_HEAD_INIT(nullptr, 0)};
  t.tp_name = "_paddle_tpu_native.TCPStore";
  t.tp_basicsize = sizeof(TCPStore);
  t.tp_flags = Py_TPFLAGS_DEFAULT;
  t.tp_doc = "TCP key/value rendezvous store (master serves; others connect)";
  t.tp_new = TCPStore_new;
  t.tp_init = (initproc)TCPStore_init;
  t.tp_dealloc = (destructor)TCPStore_dealloc;
  t.tp_methods = TCPStore_methods;
  return t;
}();

// ---------------------------------------------------------------------------

static PyModuleDef native_module = {PyModuleDef_HEAD_INIT,
                                    "_paddle_tpu_native",
                                    "paddle_tpu native runtime components",
                                    -1,
                                    nullptr,
                                    nullptr,
                                    nullptr,
                                    nullptr,
                                    nullptr};

}  // namespace

PyMODINIT_FUNC PyInit__paddle_tpu_native(void) {
  if (PyType_Ready(&ShmRingType) < 0) return nullptr;
  if (PyType_Ready(&TCPStoreType) < 0) return nullptr;
  PyObject* m = PyModule_Create(&native_module);
  if (!m) return nullptr;
  ShmRingError =
      PyErr_NewException("_paddle_tpu_native.ShmRingError", nullptr, nullptr);
  TCPStoreError =
      PyErr_NewException("_paddle_tpu_native.TCPStoreError", nullptr, nullptr);
  PyModule_AddObject(m, "ShmRingError", ShmRingError);
  PyModule_AddObject(m, "TCPStoreError", TCPStoreError);
  Py_INCREF(&ShmRingType);
  PyModule_AddObject(m, "ShmRing", (PyObject*)&ShmRingType);
  Py_INCREF(&TCPStoreType);
  PyModule_AddObject(m, "TCPStore", (PyObject*)&TCPStoreType);
  PyModule_AddStringConstant(m, "__version__", "0.1");
  return m;
}
