"""Native runtime loader.

Compiles `src/paddle_tpu_native.cc` (CPython C API; no pybind11 in this
image) into a cached shared object on first import and exposes:

  * ``ShmRing``  — POSIX shared-memory MPSC ring buffer (DataLoader worker
    batch transport; parity with the reference's shared-memory tensor
    transport in `python/paddle/io/dataloader/worker.py` /
    `paddle/fluid/memory/allocation/mmap_allocator.cc`).
  * ``TCPStore`` — TCP rendezvous KV store (parity with
    `paddle/phi/core/distributed/store/tcp_store.cc`).
  * ``available()`` — whether the native extension loaded.

If compilation fails (no toolchain), pure-Python fallbacks with the same
API are provided so the framework stays functional.
"""
from __future__ import annotations

import hashlib
import importlib.util
import os
import subprocess
import sys
import sysconfig
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "src", "paddle_tpu_native.cc")
_BUILD_DIR = os.path.join(_HERE, "_build")

_native = None
_native_err = None


def _source_tag() -> str:
    with open(_SRC, "rb") as f:
        h = hashlib.sha256(f.read()).hexdigest()[:16]
    return f"{h}-py{sys.version_info.major}{sys.version_info.minor}"


def _build() -> str:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    so_path = os.path.join(_BUILD_DIR, f"_paddle_tpu_native-{_source_tag()}.so")
    if os.path.exists(so_path):
        return so_path
    include = sysconfig.get_paths()["include"]
    # per-pid temp + atomic rename: N ranks on one host may build
    # concurrently and must not corrupt the shared cache entry
    tmp = f"{so_path}.tmp.{os.getpid()}"
    cmd = [
        "g++", "-O2", "-fPIC", "-shared", "-std=c++17",
        f"-I{include}", _SRC, "-o", tmp,
        "-lpthread", "-lrt",
    ]
    subprocess.run(cmd, check=True, capture_output=True, timeout=300)
    os.replace(tmp, so_path)
    return so_path


def _load():
    global _native, _native_err
    if _native is not None or _native_err is not None:
        return _native
    if os.environ.get("PADDLE_TPU_DISABLE_NATIVE"):
        _native_err = "disabled via PADDLE_TPU_DISABLE_NATIVE"
        return None
    try:
        so_path = _build()
        spec = importlib.util.spec_from_file_location(
            "_paddle_tpu_native", so_path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        _native = mod
    except Exception as e:  # no toolchain / sandbox: fall back to python
        _native_err = f"{type(e).__name__}: {e}"
        if isinstance(e, subprocess.CalledProcessError):
            _native_err += "\n" + e.stderr.decode(errors="replace")[-2000:]
    return _native


def available() -> bool:
    return _load() is not None


def load_error():
    _load()
    return _native_err


# ---------------------------------------------------------------------------
# Pure-Python fallbacks (same API)
# ---------------------------------------------------------------------------

class _PyTCPStore:
    """socket-based fallback with the native TCPStore's API."""

    def __init__(self, host, port, is_master=False, timeout_ms=120000):
        import socket
        import time
        self._timeout = timeout_ms / 1000.0
        self._lock = threading.Lock()       # server KV lock
        self._cli_lock = threading.Lock()   # client request/reply framing
        if is_master:
            self._kv = {}
            self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            self._srv.bind(("", port))
            self._srv.listen(128)
            threading.Thread(target=self._serve, daemon=True).start()
            host = "127.0.0.1"
        deadline = time.monotonic() + self._timeout
        while True:
            try:
                self._sock = socket.create_connection((host, port), timeout=5)
                self._sock.settimeout(self._timeout)
                break
            except OSError:
                if time.monotonic() > deadline:
                    raise TimeoutError(f"TCPStore connect({host}:{port})")
                time.sleep(0.05)

    # -- server side -------------------------------------------------------
    def _serve(self):
        while True:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True).start()

    def _handle(self, conn):
        import struct
        try:
            while True:
                hdr = self._recvn(conn, 5, eof_ok=True)
                if hdr is None:
                    return
                op, klen = struct.unpack("<BI", hdr)
                key = self._recvn(conn, klen).decode()
                # recv any payload BEFORE taking the lock: a stalled
                # client mid-SET must not block every other client
                if op == 1:  # SET
                    (vlen,) = struct.unpack("<I", self._recvn(conn, 4))
                    val = self._recvn(conn, vlen) if vlen else b""
                    with self._lock:
                        self._kv[key] = val
                    conn.sendall(b"\x01")
                elif op == 2:  # GET
                    with self._lock:
                        v = self._kv.get(key)
                    if v is None:
                        conn.sendall(b"\x00")
                    else:
                        conn.sendall(b"\x01" + struct.pack("<I", len(v)) + v)
                elif op == 3:  # ADD
                    (delta,) = struct.unpack("<q", self._recvn(conn, 8))
                    with self._lock:
                        raw = self._kv.get(key, b"\x00" * 8)
                        cur = struct.unpack("<q", raw)[0] if len(raw) == 8 \
                            else 0
                        new = cur + delta
                        self._kv[key] = struct.pack("<q", new)
                    conn.sendall(struct.pack("<q", new))
                elif op == 4:  # CHECK
                    with self._lock:
                        found = key in self._kv
                    conn.sendall(b"\x01" if found else b"\x00")
                elif op == 5:  # DEL
                    with self._lock:
                        erased = self._kv.pop(key, None) is not None
                    conn.sendall(b"\x01" if erased else b"\x00")
                elif op == 6:  # NUMKEYS
                    with self._lock:
                        n = len(self._kv)
                    conn.sendall(struct.pack("<I", n))
                else:
                    return
        except OSError:
            pass
        finally:
            conn.close()

    @staticmethod
    def _recvn(conn, n, eof_ok=False):
        """Read exactly n bytes. A clean EOF before any byte returns None
        when eof_ok (idle connection closed); any partial read raises —
        a truncated buffer must never be parsed as a complete message."""
        buf = b""
        while len(buf) < n:
            chunk = conn.recv(n - len(buf))
            if not chunk:
                if eof_ok and not buf:
                    return None
                raise ConnectionError(
                    f"connection lost mid-message ({len(buf)}/{n} bytes)")
            buf += chunk
        return buf

    # -- client side -------------------------------------------------------
    def _req(self, op, key, payload=b""):
        import struct
        k = key.encode()
        self._sock.sendall(struct.pack("<BI", op, len(k)) + k + payload)

    def set(self, key, value):
        import struct
        with self._cli_lock:
            self._req(1, key, struct.pack("<I", len(value)) + value)
            self._recvn(self._sock, 1)

    def get(self, key, wait=True):
        import struct
        import time
        deadline = time.monotonic() + self._timeout
        while True:
            with self._cli_lock:
                self._req(2, key)
                found = self._recvn(self._sock, 1)
                if found == b"\x01":
                    (vlen,) = struct.unpack(
                        "<I", self._recvn(self._sock, 4))
                    return self._recvn(self._sock, vlen) if vlen else b""
            if not wait:
                return None
            if time.monotonic() > deadline:
                raise TimeoutError(f"get({key}) timed out")
            time.sleep(0.01)

    def add(self, key, delta):
        import struct
        with self._cli_lock:
            self._req(3, key, struct.pack("<q", delta))
            return struct.unpack("<q", self._recvn(self._sock, 8))[0]

    def check(self, key):
        with self._cli_lock:
            self._req(4, key)
            return self._recvn(self._sock, 1) == b"\x01"

    def delete_key(self, key):
        with self._cli_lock:
            self._req(5, key)
            return self._recvn(self._sock, 1) == b"\x01"

    def num_keys(self):
        import struct
        with self._cli_lock:
            self._req(6, "")
            return struct.unpack("<I", self._recvn(self._sock, 4))[0]


def ShmRing(name, capacity=0, create=False):
    mod = _load()
    if mod is None:
        raise RuntimeError(
            f"native ShmRing unavailable ({_native_err}); "
            "use num_workers with the thread-pool path instead")
    return mod.ShmRing(name, capacity=capacity, create=create)


def TCPStore(host, port, is_master=False, timeout_ms=120000):
    mod = _load()
    if mod is None:
        return _PyTCPStore(host, port, is_master=is_master,
                           timeout_ms=timeout_ms)
    return mod.TCPStore(host, port, is_master=is_master, timeout_ms=timeout_ms)


__all__ = ["ShmRing", "TCPStore", "available", "load_error"]
