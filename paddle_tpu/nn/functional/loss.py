"""Loss functionals.

Parity: reference `python/paddle/nn/functional/loss.py` (cross_entropy with
soft/hard labels + ignore_index + weights, bce, mse, l1, smooth_l1, nll,
kl_div, margin/cosine/hinge family, ctc excluded this round).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor
from ...ops.dispatch import apply_op

__all__ = [
    "cross_entropy", "softmax_with_cross_entropy", "binary_cross_entropy",
    "binary_cross_entropy_with_logits", "mse_loss", "l1_loss",
    "smooth_l1_loss", "nll_loss", "kl_div", "margin_ranking_loss",
    "cosine_embedding_loss", "hinge_embedding_loss", "triplet_margin_loss",
    "triplet_margin_with_distance_loss", "multi_label_soft_margin_loss",
    "soft_margin_loss", "sigmoid_focal_loss", "square_error_cost",
    "log_loss", "poisson_nll_loss", "gaussian_nll_loss", "dice_loss",
    "npair_loss", "multi_margin_loss",
]


def _reduce(out, reduction):
    if reduction == "mean":
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean",
                  soft_label=False, axis=-1, use_softmax=True, label_smoothing=0.0,
                  name=None):
    def _f(logits, lab, w):
        ax = axis % logits.ndim
        logp = jax.nn.log_softmax(logits, axis=ax) if use_softmax \
            else jnp.log(jnp.maximum(logits, 1e-30))
        n_classes = logits.shape[ax]
        if soft_label:
            soft = lab
            if label_smoothing > 0.0:
                soft = soft * (1 - label_smoothing) + label_smoothing / n_classes
            loss = -jnp.sum(soft * logp, axis=ax)
            if w is not None:
                shape = [1] * logits.ndim
                shape[ax] = -1
                loss = loss * jnp.sum(soft * w.reshape(shape), axis=ax)
            if reduction == "mean":
                return jnp.mean(loss)
            if reduction == "sum":
                return jnp.sum(loss)
            return loss
        lab_idx = lab
        if lab_idx.ndim == logits.ndim:  # trailing 1 dim
            lab_idx = jnp.squeeze(lab_idx, axis=ax)
        lab_idx = lab_idx.astype(jnp.int32)
        valid = lab_idx != ignore_index
        safe = jnp.where(valid, lab_idx, 0)
        if label_smoothing > 0.0:
            onehot = jax.nn.one_hot(safe, n_classes, axis=ax, dtype=logp.dtype)
            smooth = onehot * (1 - label_smoothing) + label_smoothing / n_classes
            picked = jnp.sum(smooth * logp, axis=ax)
        else:
            picked = jnp.take_along_axis(logp, jnp.expand_dims(safe, ax), axis=ax)
            picked = jnp.squeeze(picked, axis=ax)
        loss = -picked
        wsel = w[safe] if w is not None else jnp.ones_like(loss)
        loss = jnp.where(valid, loss * wsel, 0.0)
        if reduction == "mean":
            denom = jnp.sum(jnp.where(valid, wsel, 0.0))
            return jnp.sum(loss) / jnp.maximum(denom, 1e-12)
        if reduction == "sum":
            return jnp.sum(loss)
        return loss

    return apply_op("cross_entropy", _f, input, label, weight)


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100,
                               numeric_stable_mode=True, return_softmax=False,
                               axis=-1, name=None):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none", axis=axis)
    # paddle returns loss with a trailing singleton dim in this legacy API
    from ...ops.manipulation import unsqueeze
    loss = unsqueeze(loss, axis)
    if return_softmax:
        from .activation import softmax
        return loss, softmax(logits, axis=axis)
    return loss


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    def _f(x, y, w):
        eps = 1e-12
        out = -(y * jnp.log(jnp.maximum(x, eps)) +
                (1 - y) * jnp.log(jnp.maximum(1 - x, eps)))
        if w is not None:
            out = out * w
        return _reduce(out, reduction)
    return apply_op("bce", _f, input, label, weight)


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean",
                                     pos_weight=None, name=None):
    def _f(x, y, w, pw):
        max_val = jnp.maximum(-x, 0.0)
        if pw is not None:
            log_w = (pw - 1) * y + 1
            out = (1 - y) * x + log_w * (jnp.log1p(jnp.exp(-jnp.abs(x))) + max_val)
        else:
            out = (1 - y) * x + jnp.log1p(jnp.exp(-jnp.abs(x))) + max_val
        if w is not None:
            out = out * w
        return _reduce(out, reduction)
    return apply_op("bce_logits", _f, logit, label, weight, pos_weight)


def mse_loss(input, label, reduction="mean", name=None):
    return apply_op("mse_loss",
                    lambda x, y: _reduce(jnp.square(x - y), reduction), input, label)


def l1_loss(input, label, reduction="mean", name=None):
    return apply_op("l1_loss",
                    lambda x, y: _reduce(jnp.abs(x - y), reduction), input, label)


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def _f(x, y):
        d = jnp.abs(x - y)
        out = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
        # paddle's smooth_l1 multiplies by delta
        out = out * delta
        return _reduce(out, reduction)
    return apply_op("smooth_l1", _f, input, label)


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):
    def _f(logp, lab, w):
        lab = lab.astype(jnp.int32)
        valid = lab != ignore_index
        safe = jnp.where(valid, lab, 0)
        picked = jnp.take_along_axis(logp, jnp.expand_dims(safe, 1), axis=1)
        picked = jnp.squeeze(picked, axis=1)
        loss = -picked
        wsel = w[safe] if w is not None else jnp.ones_like(loss)
        loss = jnp.where(valid, loss * wsel, 0.0)
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(jnp.sum(jnp.where(valid, wsel, 0.0)), 1e-12)
        if reduction == "sum":
            return jnp.sum(loss)
        return loss
    return apply_op("nll_loss", _f, input, label, weight)


def kl_div(input, label, reduction="mean", log_target=False, name=None):
    def _f(x, y):
        if log_target:
            out = jnp.exp(y) * (y - x)
        else:
            out = y * (jnp.log(jnp.maximum(y, 1e-12)) - x)
        if reduction == "batchmean":
            return jnp.sum(out) / x.shape[0]
        return _reduce(out, reduction)
    return apply_op("kl_div", _f, input, label)


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):
    def _f(x, y, lab):
        out = jnp.maximum(-lab * (x - y) + margin, 0.0)
        return _reduce(out, reduction)
    return apply_op("margin_ranking", _f, input, other, label)


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean", name=None):
    def _f(x1, x2, lab):
        cos = jnp.sum(x1 * x2, axis=-1) / (
            jnp.linalg.norm(x1, axis=-1) * jnp.linalg.norm(x2, axis=-1) + 1e-12)
        out = jnp.where(lab == 1, 1 - cos, jnp.maximum(cos - margin, 0.0))
        return _reduce(out, reduction)
    return apply_op("cosine_embedding", _f, input1, input2, label)


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    def _f(x, lab):
        out = jnp.where(lab == 1, x, jnp.maximum(margin - x, 0.0))
        return _reduce(out, reduction)
    return apply_op("hinge_embedding", _f, input, label)


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction="mean", name=None):
    def _f(a, pos, neg):
        dp = jnp.linalg.norm(a - pos + epsilon, ord=p, axis=-1)
        dn = jnp.linalg.norm(a - neg + epsilon, ord=p, axis=-1)
        if swap:
            dswap = jnp.linalg.norm(pos - neg + epsilon, ord=p, axis=-1)
            dn = jnp.minimum(dn, dswap)
        return _reduce(jnp.maximum(dp - dn + margin, 0.0), reduction)
    return apply_op("triplet_margin", _f, input, positive, negative)


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean", name=None):
    if distance_function is None:
        return triplet_margin_loss(input, positive, negative, margin=margin,
                                   swap=swap, reduction=reduction)
    dp = distance_function(input, positive)
    dn = distance_function(input, negative)
    if swap:
        dn2 = distance_function(positive, negative)
        from ...ops.math import minimum
        dn = minimum(dn, dn2)
    from ...ops.math import maximum as t_max
    out = apply_op("triplet_dist",
                   lambda a, b: _reduce(jnp.maximum(a - b + margin, 0.0), reduction),
                   dp, dn)
    return out


def multi_label_soft_margin_loss(input, label, weight=None, reduction="mean", name=None):
    def _f(x, y, w):
        out = -(y * jax.nn.log_sigmoid(x) + (1 - y) * jax.nn.log_sigmoid(-x))
        if w is not None:
            out = out * w
        out = jnp.mean(out, axis=-1)
        return _reduce(out, reduction)
    return apply_op("ml_soft_margin", _f, input, label, weight)


def soft_margin_loss(input, label, reduction="mean", name=None):
    def _f(x, y):
        return _reduce(jnp.log1p(jnp.exp(-y * x)), reduction)
    return apply_op("soft_margin", _f, input, label)


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,
                      reduction="mean", name=None):
    def _f(x, lab, w):
        n, c = x.shape
        lab = lab.astype(jnp.int32)
        correct = jnp.take_along_axis(x, lab[:, None], axis=1)
        diff = jnp.maximum(margin - correct + x, 0.0) ** p
        mask = 1.0 - jax.nn.one_hot(lab, c, dtype=x.dtype)
        if w is not None:
            diff = diff * w[lab][:, None]
        out = jnp.sum(diff * mask, axis=1) / c
        return _reduce(out, reduction)
    return apply_op("multi_margin", _f, input, label, weight)


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    def _f(x, y, norm):
        p = jax.nn.sigmoid(x)
        ce = (1 - y) * x + jnp.log1p(jnp.exp(-jnp.abs(x))) + jnp.maximum(-x, 0.0)
        p_t = p * y + (1 - p) * (1 - y)
        mod = (1 - p_t) ** gamma
        a_t = alpha * y + (1 - alpha) * (1 - y)
        out = a_t * mod * ce
        if norm is not None:
            out = out / norm
        return _reduce(out, reduction)
    return apply_op("focal", _f, logit, label, normalizer)


def square_error_cost(input, label):
    return apply_op("square_error_cost", lambda x, y: jnp.square(x - y), input, label)


def log_loss(input, label, epsilon=1e-4, name=None):
    def _f(x, y):
        return -y * jnp.log(x + epsilon) - (1 - y) * jnp.log(1 - x + epsilon)
    return apply_op("log_loss", _f, input, label)


def poisson_nll_loss(input, label, log_input=True, full=False, epsilon=1e-8,
                     reduction="mean", name=None):
    def _f(x, y):
        if log_input:
            out = jnp.exp(x) - y * x
        else:
            out = x - y * jnp.log(x + epsilon)
        if full:
            stirling = y * jnp.log(y + epsilon) - y + 0.5 * jnp.log(2 * jnp.pi * (y + epsilon))
            out = out + jnp.where(y > 1, stirling, 0.0)
        return _reduce(out, reduction)
    return apply_op("poisson_nll", _f, input, label)


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean", name=None):
    def _f(x, y, v):
        v = jnp.maximum(v, epsilon)
        out = 0.5 * (jnp.log(v) + jnp.square(x - y) / v)
        if full:
            out = out + 0.5 * jnp.log(2 * jnp.pi)
        return _reduce(out, reduction)
    return apply_op("gaussian_nll", _f, input, label, variance)


def dice_loss(input, label, epsilon=1e-5, name=None):
    def _f(x, y):
        lab = jax.nn.one_hot(jnp.squeeze(y, -1), x.shape[-1], dtype=x.dtype)
        red = tuple(range(1, x.ndim))
        inter = jnp.sum(x * lab, axis=red)
        union = jnp.sum(x, axis=red) + jnp.sum(lab, axis=red)
        return jnp.mean(1 - (2 * inter + epsilon) / (union + epsilon))
    return apply_op("dice", _f, input, label)


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    def _f(a, p, lab):
        batch = a.shape[0]
        sim = a @ p.T
        eq = (lab[:, None] == lab[None, :]).astype(a.dtype)
        eq = eq / jnp.sum(eq, axis=1, keepdims=True)
        xent = -jnp.sum(eq * jax.nn.log_softmax(sim, axis=1), axis=1)
        reg = l2_reg * (jnp.sum(jnp.square(a)) + jnp.sum(jnp.square(p))) / (2 * batch)
        return jnp.mean(xent) + reg
    return apply_op("npair", _f, anchor, positive, labels)
