"""paddle_tpu.nn.functional — parity with python/paddle/nn/functional/."""
from . import activation, common, conv, pooling, norm, loss, extra  # noqa: F401
from . import flash_attention as _fa_mod

from .activation import *  # noqa: F401,F403
from .common import *  # noqa: F401,F403
from .conv import *  # noqa: F401,F403
from .pooling import *  # noqa: F401,F403
from .norm import *  # noqa: F401,F403
from .loss import *  # noqa: F401,F403
from .flash_attention import *  # noqa: F401,F403
from .extra import *  # noqa: F401,F403

__all__ = (activation.__all__ + common.__all__ + conv.__all__ +
           pooling.__all__ + norm.__all__ + loss.__all__ +
           _fa_mod.__all__ + extra.__all__)
