"""nn.functional tail: spatial sampling (grid_sample/affine_grid),
sequence losses (ctc_loss/rnnt_loss), unpooling, and small utilities.

Parity: reference `python/paddle/nn/functional/vision.py`
(grid_sample:270, affine_grid:26, temporal_shift), `loss.py` ctc_loss /
rnnt_loss (warpctc/warprnnt bindings in the reference), `pooling.py`
max_unpool1d/2d/3d, `common.py` embedding_bag-style gathers.

TPU-native: grid_sample is four gathers + bilinear weights (one fused
XLA program, differentiable); CTC and RNN-T are log-domain dynamic
programs over `lax.scan` — the reference dynloads warpctc/warprnnt CUDA,
here the same recurrences compile through XLA.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...ops.dispatch import apply_op

__all__ = ["grid_sample", "affine_grid", "sequence_mask", "max_unpool1d",
           "max_unpool2d", "max_unpool3d", "pairwise_distance",
           "temporal_shift", "feature_alpha_dropout", "embedding_bag",
           "ctc_loss", "rnnt_loss", "hardtanh_", "leaky_relu_",
           "thresholded_relu_", "fractional_max_pool2d",
           "fractional_max_pool3d", "hsigmoid_loss",
           "adaptive_log_softmax_with_loss", "gather_tree",
           "sparse_attention", "flash_attn_qkvpacked",
           "flash_attn_varlen_qkvpacked", "margin_cross_entropy"]

NEG = -1e30


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """x (N, C, H, W); grid (N, Ho, Wo, 2) in [-1, 1] (x, y) order.
    Parity: nn/functional/vision.py grid_sample."""

    def _f(a, g):
        N, C, H, W = a.shape
        gx, gy = g[..., 0], g[..., 1]

        def unnorm(v, size):
            if align_corners:
                return (v + 1) * (size - 1) / 2
            return ((v + 1) * size - 1) / 2
        fx = unnorm(gx, W)
        fy = unnorm(gy, H)
        if padding_mode == "reflection":
            def reflect(v, size):
                if align_corners:
                    span = 2 * (size - 1)
                    v = jnp.abs(v) % span
                    return jnp.where(v > size - 1, span - v, v)
                span = 2 * size
                v = (v + 0.5) % span
                v = jnp.where(v > size, span - v, v)
                return jnp.clip(v - 0.5, 0, size - 1)
            fx = reflect(fx, W)
            fy = reflect(fy, H)

        def sample(ix, iy):
            inb = (ix >= 0) & (ix < W) & (iy >= 0) & (iy < H)
            ixc = jnp.clip(ix, 0, W - 1)
            iyc = jnp.clip(iy, 0, H - 1)
            lin = (iyc * W + ixc).reshape(N, -1)        # (N, Ho*Wo)
            flat = a.reshape(N, C, H * W)
            got = jnp.take_along_axis(flat, lin[:, None, :], axis=2)
            got = got.reshape(N, C, *ix.shape[1:])
            if padding_mode == "zeros":
                got = got * inb[:, None].astype(a.dtype)
            return got

        if mode == "nearest":
            return sample(jnp.round(fx).astype(jnp.int32),
                          jnp.round(fy).astype(jnp.int32))
        x0 = jnp.floor(fx)
        y0 = jnp.floor(fy)
        wx = (fx - x0).astype(a.dtype)[:, None]
        wy = (fy - y0).astype(a.dtype)[:, None]
        x0i = x0.astype(jnp.int32)
        y0i = y0.astype(jnp.int32)
        return (sample(x0i, y0i) * (1 - wx) * (1 - wy)
                + sample(x0i + 1, y0i) * wx * (1 - wy)
                + sample(x0i, y0i + 1) * (1 - wx) * wy
                + sample(x0i + 1, y0i + 1) * wx * wy)

    return apply_op("grid_sample", _f, x, grid)


def affine_grid(theta, out_shape, align_corners=True, name=None):
    """theta (N, 2, 3) -> sampling grid (N, H, W, 2) for grid_sample.
    Parity: nn/functional/vision.py affine_grid."""
    if hasattr(out_shape, "_data"):
        out_shape = [int(v) for v in np.asarray(out_shape._data)]
    N, C, H, W = [int(v) for v in out_shape]

    def _f(th):
        if align_corners:
            xs = jnp.linspace(-1, 1, W)
            ys = jnp.linspace(-1, 1, H)
        else:
            xs = (jnp.arange(W) * 2 + 1) / W - 1
            ys = (jnp.arange(H) * 2 + 1) / H - 1
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        base = jnp.stack([gx, gy, jnp.ones_like(gx)], axis=-1)  # (H, W, 3)
        return jnp.einsum("hwk,njk->nhwj", base, th)

    return apply_op("affine_grid", _f, theta)


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    """lengths (…,) -> mask (…, maxlen). Parity: paddle sequence_mask
    (extension.py:59, dtype defaults to int64)."""
    from ...core.dtype import convert_dtype

    if maxlen is None:
        data = x._data if hasattr(x, "_data") else x
        try:
            maxlen = int(jnp.max(data))
        except jax.errors.ConcretizationTypeError:
            raise ValueError(
                "sequence_mask under jit/to_static needs an explicit "
                "maxlen (the output shape cannot depend on data)") from None

    def _f(lens):
        pos = jnp.arange(maxlen)
        out = pos[None, :] < lens.reshape(-1, 1)
        out = out.reshape(tuple(lens.shape) + (maxlen,))
        return out.astype(convert_dtype(dtype))

    return apply_op("sequence_mask", _f, x)


def _max_unpool(x, indices, nd, kernel_size, stride, padding, output_size):
    ks = (kernel_size,) * nd if isinstance(kernel_size, int) \
        else tuple(kernel_size)
    st = tuple((stride,) * nd if isinstance(stride, int)
               else stride) if stride is not None else ks
    pd = (padding,) * nd if isinstance(padding, int) else tuple(padding)

    def _f(a, idx):
        spatial_in = a.shape[2:]
        if output_size is not None:
            spatial = tuple(int(s) for s in output_size[-nd:])
        else:
            spatial = tuple((si - 1) * s + k - 2 * p
                            for si, s, k, p in zip(spatial_in, st, ks, pd))
        N, C = a.shape[:2]
        size = int(np.prod(spatial))
        flat_idx = idx.reshape(N, C, -1).astype(jnp.int32)
        flat_val = a.reshape(N, C, -1)
        out = jnp.zeros((N, C, size), a.dtype)
        out = jax.vmap(jax.vmap(
            lambda o, i, v: o.at[i].set(v)))(out, flat_idx, flat_val)
        return out.reshape((N, C) + spatial)

    return apply_op("max_unpool", _f, x, indices)


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
    """Parity: pooling.py max_unpool1d (indices from max_pool(…,
    return_mask=True))."""
    return _max_unpool(x, indices, 1, kernel_size, stride, padding,
                       output_size)


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
    return _max_unpool(x, indices, 2, kernel_size, stride, padding,
                       output_size)


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
    return _max_unpool(x, indices, 3, kernel_size, stride, padding,
                       output_size)


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    def _f(a, b):
        d = a - b + epsilon
        return jnp.linalg.norm(d, ord=p, axis=-1, keepdims=keepdim)
    return apply_op("pairwise_distance", _f, x, y)


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW",
                   name=None):
    """TSM shift (parity: vision.py temporal_shift): shift a channel
    fraction one step along the segment (time) axis."""

    def _f(a):
        if data_format == "NHWC":
            a = jnp.transpose(a, (0, 3, 1, 2))
        NT, C, H, W = a.shape
        N = NT // seg_num
        v = a.reshape(N, seg_num, C, H, W)
        c1 = int(C * shift_ratio)
        c2 = int(C * 2 * shift_ratio)
        # phi temporal_shift_kernel.cc: channels [0, c1) read frame t-1,
        # channels [c1, c2) read frame t+1
        from_prev = jnp.concatenate(
            [jnp.zeros_like(v[:, :1, :c1]), v[:, :-1, :c1]], axis=1)
        from_next = jnp.concatenate(
            [v[:, 1:, c1:c2], jnp.zeros_like(v[:, :1, c1:c2])], axis=1)
        out = jnp.concatenate([from_prev, from_next, v[:, :, c2:]], axis=2)
        out = out.reshape(NT, C, H, W)
        if data_format == "NHWC":
            out = jnp.transpose(out, (0, 2, 3, 1))
        return out

    return apply_op("temporal_shift", _f, x)


def feature_alpha_dropout(x, p=0.5, training=True, name=None):
    """Alpha dropout over whole channels (parity: common.py
    feature_alpha_dropout)."""
    if not training or p == 0.0:
        return x
    from ...framework.random import rng_key
    key = rng_key()
    selu_alpha, selu_scale = 1.6732632423543772, 1.0507009873554805
    alpha_p = -selu_alpha * selu_scale   # same derivation as alpha_dropout

    def _f(a):
        shape = (a.shape[0], a.shape[1]) + (1,) * (a.ndim - 2)
        keep = jax.random.bernoulli(key, 1 - p, shape)
        q = 1 - p
        scale_a = (q + alpha_p ** 2 * q * (1 - q)) ** -0.5
        scale_b = -scale_a * alpha_p * (1 - q)
        return (jnp.where(keep, a, alpha_p) * scale_a + scale_b).astype(
            a.dtype)

    return apply_op("feature_alpha_dropout", _f, x)


def embedding_bag(input, weight, offsets=None, mode="mean", name=None):
    """Bagged embedding lookup: gather rows then reduce per bag.

    input (B, L) with per-row bags (offsets=None), or flat indices +
    offsets (B,) marking bag starts (reference embedding_bag contract)."""

    def _f(ids, w, *rest):
        reduce = {"mean": jnp.mean, "sum": jnp.sum, "max": jnp.max}[mode]
        if offsets is None:
            got = w[ids]                               # (B, L, D)
            return reduce(got, axis=1)
        offs = rest[0]
        flat = w[ids]                                  # (Ltot, D)
        B = offs.shape[0]
        Ltot = ids.shape[0]
        bag_id = jnp.searchsorted(offs, jnp.arange(Ltot),
                                  side="right") - 1
        if mode == "sum":
            return jax.ops.segment_sum(flat, bag_id, B)
        if mode == "mean":
            s = jax.ops.segment_sum(flat, bag_id, B)
            n = jax.ops.segment_sum(jnp.ones((Ltot, 1)), bag_id, B)
            return s / jnp.maximum(n, 1)
        return jax.ops.segment_max(flat, bag_id, B)

    args = [input, weight] + ([offsets] if offsets is not None else [])
    return apply_op("embedding_bag", _f, *args)


def _logaddexp(a, b):
    return jnp.logaddexp(a, b)


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False, name=None):
    """CTC loss as a log-domain forward DP compiled by XLA.

    Parity: nn/functional/loss.py ctc_loss (the reference dynloads
    warpctc). log_probs (T, B, V) log-softmaxed (raw logits accepted —
    log_softmax is applied), labels (B, S) int, lengths (B,).
    """
    def _f(lp, lab, in_len, lab_len):
        lp = jax.nn.log_softmax(lp, axis=-1)
        T, B, V = lp.shape
        S = lab.shape[1]
        # extended label sequence: blank y1 blank y2 ... yS blank (2S+1)
        ext = jnp.full((B, 2 * S + 1), blank, jnp.int32)
        ext = ext.at[:, 1::2].set(lab.astype(jnp.int32))
        L = 2 * S + 1
        # allow transition from l-2 when ext[l] != blank and != ext[l-2]
        ext_prev2 = jnp.concatenate(
            [jnp.full((B, 2), -1, jnp.int32), ext[:, :-2]], axis=1)
        can_skip = (ext != blank) & (ext != ext_prev2)
        alpha0 = jnp.full((B, L), NEG)
        alpha0 = alpha0.at[:, 0].set(lp[0, jnp.arange(B), ext[:, 0]])
        has1 = (L > 1)
        if has1:
            alpha0 = alpha0.at[:, 1].set(
                jnp.where(lab_len > 0,
                          lp[0, jnp.arange(B), ext[:, 1]], NEG))

        def step(alpha, lp_t):
            prev1 = jnp.concatenate(
                [jnp.full((B, 1), NEG), alpha[:, :-1]], axis=1)
            prev2 = jnp.concatenate(
                [jnp.full((B, 2), NEG), alpha[:, :-2]], axis=1)
            prev2 = jnp.where(can_skip, prev2, NEG)
            merged = _logaddexp(_logaddexp(alpha, prev1), prev2)
            emit = jnp.take_along_axis(lp_t, ext, axis=1)
            return merged + emit, None

        def scan_body(carry, t):
            alpha = carry
            new_alpha, _ = step(alpha, lp[t])
            # freeze rows past their input length
            new_alpha = jnp.where((t < in_len)[:, None], new_alpha, alpha)
            return new_alpha, None

        alpha, _ = jax.lax.scan(scan_body, alpha0, jnp.arange(1, T))
        # final: logsumexp of positions 2*lab_len and 2*lab_len - 1
        idx_last = (2 * lab_len).astype(jnp.int32)
        a_last = jnp.take_along_axis(alpha, idx_last[:, None], axis=1)[:, 0]
        idx_pen = jnp.maximum(idx_last - 1, 0)
        a_pen = jnp.where(lab_len > 0,
                          jnp.take_along_axis(alpha, idx_pen[:, None],
                                              axis=1)[:, 0], NEG)
        nll = -_logaddexp(a_last, a_pen)
        if norm_by_times:
            nll = nll / jnp.maximum(in_len.astype(nll.dtype), 1)
        if reduction == "mean":
            # reference warpctc mean: also divides each loss by label len
            return jnp.mean(nll
                            / jnp.maximum(lab_len.astype(nll.dtype), 1))
        if reduction == "sum":
            return jnp.sum(nll)
        return nll

    return apply_op("ctc_loss", _f, log_probs, labels, input_lengths,
                    label_lengths)


def rnnt_loss(logits, labels, logit_lengths, label_lengths, blank=0,
              fastemit_lambda=0.001, reduction="mean", name=None):
    """RNN-T (transducer) loss as a log-domain lattice DP.

    Parity: nn/functional/loss.py rnnt_loss:2061 (reference dynloads
    warprnnt; fastemit_lambda defaults 0.001 there too). logits
    (B, T, U+1, V) raw; labels (B, U) int; lengths (B,). FastEmit is the
    gradient-scaling formulation: emit-arc gradients scale by
    (1 + lambda) while the reported loss value is the plain RNN-T NLL —
    exactly warprnnt's behavior.
    """
    def _f(lg, lab, t_len, u_len):
        lp = jax.nn.log_softmax(lg, axis=-1)
        B, T, U1, V = lp.shape
        U = U1 - 1
        blank_lp = lp[..., blank]                      # (B, T, U+1)
        lab_i = lab.astype(jnp.int32)
        emit_lp = jnp.take_along_axis(
            lp[:, :, :U, :], lab_i[:, None, :, None], axis=3)[..., 0]
        if fastemit_lambda:
            # value unchanged, emit-arc gradient scaled by (1 + lambda)
            emit_lp = (emit_lp + fastemit_lambda
                       * (emit_lp - jax.lax.stop_gradient(emit_lp)))
        # emit padded to U+1 so u-scans can index u-1 in [0, U]
        emit_pad = jnp.concatenate(
            [emit_lp, jnp.full((B, T, 1), NEG)], axis=2)  # (B, T, U+1)
        valid_u = jnp.arange(U1)[None, :] <= u_len[:, None]

        def climb(base, t):
            """alpha(t, u) = logsumexp(base(u), alpha(t, u-1) + emit(t, u-1))
            — the vertical (label-emitting) closure within frame t."""
            def u_scan(carry, u):
                em = jnp.take_along_axis(
                    emit_pad[:, t, :],
                    jnp.maximum(u - 1, 0).repeat(B)[:, None], axis=1)[:, 0]
                val = jnp.where(u == 0, base[:, 0],
                                _logaddexp(
                                    jnp.take_along_axis(
                                        base, u.repeat(B)[:, None],
                                        axis=1)[:, 0],
                                    carry + em))
                return val, val
            _, cols = jax.lax.scan(u_scan, jnp.full((B,), NEG),
                                   jnp.arange(U1))
            return jnp.swapaxes(cols, 0, 1)

        # t = 0: only vertical emits from alpha(0,0)=0
        base0 = jnp.full((B, U1), NEG).at[:, 0].set(0.0)
        alpha = jnp.where(valid_u, climb(base0, 0), NEG)

        def t_body(alpha, t):
            base = alpha + blank_lp[:, t - 1, :]       # horizontal (blank)
            new_alpha = jnp.where(valid_u, climb(base, t), NEG)
            new_alpha = jnp.where((t < t_len)[:, None], new_alpha, alpha)
            return new_alpha, None

        alpha, _ = jax.lax.scan(t_body, alpha, jnp.arange(1, T))
        a_fin = jnp.take_along_axis(alpha, u_len.astype(jnp.int32)[:, None],
                                    axis=1)[:, 0]
        bidx = jnp.arange(B)
        final_blank = blank_lp[bidx, jnp.maximum(t_len - 1, 0),
                               u_len.astype(jnp.int32)]
        nll = -(a_fin + final_blank)
        if reduction == "mean":
            return jnp.mean(nll)
        if reduction == "sum":
            return jnp.sum(nll)
        return nll

    return apply_op("rnnt_loss", _f, logits, labels, logit_lengths,
                    label_lengths)


def _inplace_of(x, out):
    """Taped in-place: mutate x to out BUT first snapshot x's old tape
    identity and rebind the new node's input to the snapshot — otherwise
    the node's input would be the mutated x itself (a self-cycle that
    silently drops the op's gradient)."""
    from ...core.tensor import Tensor as _T
    node = out._grad_node
    if node is not None:
        old = _T(x._data, stop_gradient=x.stop_gradient)
        old._grad_node = x._grad_node
        old._grad_out_idx = x._grad_out_idx
        node.inputs = [old if t is x else t for t in node.inputs]
    x._data = out._data
    x._grad_node = node
    x._grad_out_idx = out._grad_out_idx
    x.stop_gradient = out.stop_gradient
    return x


def hardtanh_(x, min=-1.0, max=1.0, name=None):
    """In-place hardtanh (parity: functional hardtanh_)."""
    from .activation import hardtanh
    return _inplace_of(x, hardtanh(x, min, max))


def leaky_relu_(x, negative_slope=0.01, name=None):
    from .activation import leaky_relu
    return _inplace_of(x, leaky_relu(x, negative_slope))


def thresholded_relu_(x, threshold=1.0, value=0.0, name=None):
    from .activation import thresholded_relu
    return _inplace_of(x, thresholded_relu(x, threshold, value))


def fractional_max_pool2d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, name=None):
    """Functional over the FractionalMaxPool2D layer logic."""
    from ..layer.extra_layers import FractionalMaxPool2D
    return FractionalMaxPool2D(output_size, kernel_size, random_u,
                               return_mask)(x)


def fractional_max_pool3d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, name=None):
    from ..layer.extra_layers import FractionalMaxPool3D
    return FractionalMaxPool3D(output_size, kernel_size, random_u,
                               return_mask)(x)


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Functional hierarchical sigmoid over a complete binary tree with
    CALLER-OWNED weight/bias (parity: functional hsigmoid_loss; custom
    path tables unsupported, like the layer)."""
    if path_table is not None or path_code is not None:
        raise NotImplementedError("custom path tables not supported")
    from ..layer.extra_layers import HSigmoidLoss
    tmp = HSigmoidLoss.__new__(HSigmoidLoss)
    # borrow the layer's path precomputation without registering params
    from ..layer.layers import Layer
    Layer.__init__(tmp)
    import math as _m
    tmp.num_classes = num_classes
    tmp.depth = max(1, _m.ceil(_m.log2(max(num_classes, 2))))
    codes, signs, msk = HSigmoidLoss._build_paths(num_classes, tmp.depth)
    tmp._codes, tmp._signs, tmp._mask = codes, signs, msk
    tmp.weight, tmp.bias = weight, bias
    return tmp.forward(input, label)


def adaptive_log_softmax_with_loss(input, label, head_weight, head_bias,
                                   cutoffs, tail_weights, name=None):
    """Functional adaptive softmax with caller-owned projections (parity:
    functional adaptive_log_softmax_with_loss)."""
    from ..layer.extra_layers import AdaptiveLogSoftmaxWithLoss
    als = AdaptiveLogSoftmaxWithLoss.__new__(AdaptiveLogSoftmaxWithLoss)
    from ..layer.layers import Layer
    Layer.__init__(als)
    als.cutoffs = [int(c) for c in cutoffs]
    als.n_clusters = len(als.cutoffs) - 1
    als.head_size = als.cutoffs[0] + als.n_clusters
    als.head_weight, als.head_bias = head_weight, head_bias
    als._tails = [tuple(t) for t in tail_weights]
    return als.forward(input, label)


def gather_tree(ids, parents, name=None):
    """Trace beam-search ancestry back from the last step (parity:
    functional gather_tree over phi gather_tree kernel).
    ids/parents: (T, B, beam)."""
    def _f(i, p):
        T = i.shape[0]

        def step(carry, t):
            beams = carry                            # (B, beam) int
            out_t = jnp.take_along_axis(i[t], beams, axis=1)
            prev = jnp.take_along_axis(p[t], beams, axis=1)
            return prev, out_t

        init = jnp.broadcast_to(jnp.arange(i.shape[2], dtype=i.dtype),
                                i.shape[1:])
        _, outs = jax.lax.scan(step, init, jnp.arange(T - 1, -1, -1))
        return outs[::-1]

    return apply_op("gather_tree", _f, ids, parents)


def sparse_attention(query, key, value, sparse_csr_offset,
                     sparse_csr_columns, key_padding_mask=None,
                     attn_mask=None, name=None):
    """Block-sparse attention with an explicit CSR pattern (parity:
    functional sparse_attention over phi sparse_attention kernel);
    delegates to the sparse.nn implementation."""
    from ...sparse import sparse_csr_tensor
    from ...sparse.nn.functional import attention as _sp_attn
    off = sparse_csr_offset._data if hasattr(sparse_csr_offset, "_data") \
        else jnp.asarray(sparse_csr_offset)
    col = sparse_csr_columns._data if hasattr(sparse_csr_columns, "_data") \
        else jnp.asarray(sparse_csr_columns)
    B, H, S, _ = query.shape
    csr = sparse_csr_tensor(
        off.reshape(-1), col.reshape(-1),
        jnp.ones((int(np.prod(col.shape)),), jnp.float32),
        (B * H, S, S))
    return _sp_attn(query, key, value, csr,
                    key_padding_mask=key_padding_mask, attn_mask=attn_mask)


def flash_attn_qkvpacked(qkv, dropout=0.0, causal=False,
                         return_softmax=False, training=True, name=None):
    """Packed-QKV flash attention: qkv (B, S, 3, H, D) (parity:
    nn/functional/flash_attention.py flash_attn_qkvpacked)."""
    from .flash_attention import scaled_dot_product_attention

    def _pick(i):
        return apply_op("qkv_unpack", lambda a, j=i: a[:, :, j], qkv)
    q, k, v = _pick(0), _pick(1), _pick(2)
    out = scaled_dot_product_attention(q, k, v, None, dropout, causal,
                                       training)
    return out, None


def flash_attn_varlen_qkvpacked(qkv, cu_seqlens_q, cu_seqlens_k,
                                max_seqlen_q, max_seqlen_k, scale,
                                dropout=0.0, causal=False,
                                return_softmax=False, training=True,
                                name=None):
    """Packed varlen flash attention (parity: flash_attn_varlen_qkvpacked):
    unpacks and routes to flash_attn_unpadded."""
    from .flash_attention import flash_attn_unpadded

    # varlen packed layout is (total_tokens, 3, H, D) — axis 1 holds qkv
    def _pick(i):
        return apply_op("qkv_unpack", lambda a, j=i: a[:, j], qkv)
    q, k, v = _pick(0), _pick(1), _pick(2)
    return flash_attn_unpadded(q, k, v, cu_seqlens_q, cu_seqlens_k,
                               max_seqlen_q, max_seqlen_k, scale, dropout,
                               causal, return_softmax, training=training)


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean",
                         name=None):
    """ArcFace-family margin softmax CE (parity: functional
    margin_cross_entropy): cos(m1*theta + m2) - m3 applied to the target
    logit before the scaled softmax."""
    def _f(lg, lab):
        lab = lab.reshape(-1).astype(jnp.int32)
        cos = jnp.clip(lg, -1.0, 1.0)
        theta = jnp.arccos(cos)
        tgt = jnp.cos(margin1 * theta + margin2) - margin3
        onehot = jax.nn.one_hot(lab, lg.shape[-1], dtype=lg.dtype)
        adj = jnp.where(onehot > 0, tgt, cos) * scale
        logp = jax.nn.log_softmax(adj, axis=-1)
        nll = -jnp.take_along_axis(logp, lab[:, None], axis=1)[:, 0]
        sm = jnp.exp(logp)
        if reduction == "mean":
            out = jnp.mean(nll)
        elif reduction == "sum":
            out = jnp.sum(nll)
        else:
            out = nll
        return (out, sm) if return_softmax else out

    if group is not None and getattr(group, "nranks", 1) > 1:
        raise NotImplementedError(
            "model-parallel margin_cross_entropy: use "
            "fleet.mpu.ParallelCrossEntropy for the sharded-vocab path")
    return apply_op("margin_cross_entropy", _f, logits, label)
