"""Convolution functionals via lax.conv_general_dilated (XLA lowers these
onto the MXU; on TPU, NHWC/HWIO layouts avoid transposes, but the public API
keeps the reference's NCHW default and lets XLA's layout assignment handle it).

Parity: reference `python/paddle/nn/functional/conv.py` + phi conv kernels.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...ops.dispatch import apply_op

__all__ = ["conv1d", "conv2d", "conv3d", "conv1d_transpose",
           "conv2d_transpose", "conv3d_transpose"]


def _norm_tuple(v, n):
    if isinstance(v, (int, np.integer)):
        return (int(v),) * n
    return tuple(int(x) for x in v)


def _norm_padding(padding, n):
    """Returns (lax_padding, explicit) — lax padding spec for n spatial dims."""
    if isinstance(padding, str):
        return padding.upper()  # 'SAME' / 'VALID'
    if isinstance(padding, (int, np.integer)):
        return [(int(padding), int(padding))] * n
    padding = list(padding)
    if len(padding) == n and all(isinstance(p, (int, np.integer)) for p in padding):
        return [(int(p), int(p)) for p in padding]
    if len(padding) == 2 * n:
        return [(int(padding[2 * i]), int(padding[2 * i + 1])) for i in range(n)]
    # paddle also allows [[0,0],[0,0],[h0,h1],[w0,w1]]
    if len(padding) == n + 2:
        return [tuple(int(v) for v in p) for p in padding[2:]]
    raise ValueError(f"bad padding: {padding}")


def _conv(x, weight, bias, stride, padding, dilation, groups, data_format, n):
    """weight layout (paddle): (out_c, in_c/groups, *kernel)."""
    strides = _norm_tuple(stride, n)
    dil = _norm_tuple(dilation, n)
    pad = _norm_padding(padding, n)
    channel_last = data_format[-1] == "C"
    spatial = "DHW"[3 - n:]
    lhs_spec = ("N" + spatial + "C") if channel_last else ("NC" + spatial)
    out_spec = lhs_spec
    rhs_spec = "OI" + spatial

    def _f(a, w, b):
        dn = jax.lax.conv_dimension_numbers(a.shape, w.shape, (lhs_spec, rhs_spec, out_spec))
        out = jax.lax.conv_general_dilated(
            a, w, window_strides=strides, padding=pad,
            rhs_dilation=dil, dimension_numbers=dn,
            feature_group_count=groups,
            preferred_element_type=None)
        if b is not None:
            shape = [1] * out.ndim
            shape[out.ndim - 1 if channel_last else 1] = b.shape[0]
            out = out + b.reshape(shape)
        return out
    return apply_op("conv%dd" % n, _f, x, weight, bias,
                    op_attrs={"channel_last": bool(channel_last)})


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups,
                 "NWC" if data_format == "NLC" else "NCW", 1)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, data_format, 2)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, data_format, 3)


def _conv_transpose(x, weight, bias, stride, padding, output_padding,
                    dilation, groups, data_format, n, output_size=None):
    """weight layout (paddle transpose conv): (in_c, out_c/groups, *kernel)."""
    strides = _norm_tuple(stride, n)
    dil = _norm_tuple(dilation, n)
    opad = _norm_tuple(output_padding, n)
    pad = _norm_padding(padding, n)
    channel_last = data_format[-1] == "C"
    spatial = "DHW"[3 - n:]
    lhs_spec = ("N" + spatial + "C") if channel_last else ("NC" + spatial)
    rhs_spec = "IO" + spatial

    def _f(a, w, b):
        if isinstance(pad, str):
            pads = pad
        else:
            # conv_transpose padding: effective = dilation*(k-1) - pad
            ksizes = w.shape[2:]
            pads = [(dil[i] * (ksizes[i] - 1) - pad[i][0],
                     dil[i] * (ksizes[i] - 1) - pad[i][1] + opad[i]) for i in range(n)]
        dn = jax.lax.conv_dimension_numbers(a.shape, w.shape, (lhs_spec, rhs_spec, lhs_spec))
        if groups == 1:
            out = jax.lax.conv_general_dilated(
                a, w, window_strides=(1,) * n, padding=pads,
                lhs_dilation=strides, rhs_dilation=dil,
                dimension_numbers=dn)
        else:
            # grouped transpose conv: split along channel axis
            ch_ax = a.ndim - 1 if channel_last else 1
            a_parts = jnp.split(a, groups, axis=ch_ax)
            w_parts = jnp.split(w, groups, axis=0)
            outs = [jax.lax.conv_general_dilated(
                ap, wp, window_strides=(1,) * n, padding=pads,
                lhs_dilation=strides, rhs_dilation=dil, dimension_numbers=dn)
                for ap, wp in zip(a_parts, w_parts)]
            out = jnp.concatenate(outs, axis=ch_ax)
        # conv-transpose needs spatially flipped kernel
        if b is not None:
            shape = [1] * out.ndim
            shape[out.ndim - 1 if channel_last else 1] = b.shape[0]
            out = out + b.reshape(shape)
        return out

    # flip kernel spatially for true transpose-conv semantics
    def _f_flipped(a, w, b):
        w = jnp.flip(w, axis=tuple(range(2, 2 + n)))
        return _f(a, w, b)
    return apply_op("conv%dd_transpose" % n, _f_flipped, x, weight, bias)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1, output_size=None,
                     data_format="NCL", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups,
                           "NWC" if data_format == "NLC" else "NCW", 1, output_size)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1, output_size=None,
                     data_format="NCHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, data_format, 2, output_size)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1, output_size=None,
                     data_format="NCDHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, data_format, 3, output_size)
