"""Attention functionals.

Parity: reference `python/paddle/nn/functional/flash_attention.py`
(flash_attention:242, scaled_dot_product_attention:976, flashmask_attention:1098).

TPU-native: the default path is a jnp composition that XLA fuses well at
moderate sequence lengths; for long sequences `paddle_tpu.kernels.
flash_attention` provides a Pallas fused kernel (used automatically when
available and shapes allow). Layouts follow the reference: (batch, seqlen,
num_heads, head_dim).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...framework.random import rng_key
from ...ops.dispatch import apply_op

__all__ = ["scaled_dot_product_attention", "flash_attention",
           "flash_attn_unpadded", "flashmask_attention", "sdp_kernel"]

_USE_PALLAS = [True]


def _sdpa_ref(q, k, v, mask=None, dropout_p=0.0, causal=False, key=None,
              training=True, scale=None):
    """(B, S, H, D) attention, fp32 softmax accumulation."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    qt = jnp.swapaxes(q, 1, 2)  # B,H,S,D
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    scores = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) * scale
    scores = scores.astype(jnp.float32)
    if causal:
        cm = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        scores = jnp.where(cm, scores, -jnp.inf)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            scores = jnp.where(mask, scores, -jnp.inf)
        else:
            scores = scores + mask.astype(scores.dtype)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    if dropout_p > 0.0 and training and key is not None:
        keep = jax.random.bernoulli(key, 1.0 - dropout_p, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_p), 0.0)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vt)
    return jnp.swapaxes(out, 1, 2)  # B,S,H,D


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, name=None):
    """Parity: nn/functional/flash_attention.py:976. Shapes (B, S, H, D)."""
    can_pallas = (_USE_PALLAS[0] and attn_mask is None and dropout_p == 0.0)
    if can_pallas:
        try:
            from ...kernels import flash_attention as pallas_fa
            pallas_fa.check_supported(
                tuple(query.shape), tuple(key.shape), query.dtype)
            def _f(q, k, v):
                return pallas_fa.flash_attention_bshd(q, k, v, causal=is_causal)
            return apply_op("flash_attention", _f, query, key, value)
        except ValueError:
            pass  # unsupported shape: fall through to the XLA composition
    drop_key = rng_key() if (dropout_p > 0.0 and training) else None
    def _f(q, k, v, m):
        return _sdpa_ref(q, k, v, m, dropout_p, is_causal, drop_key, training)
    return apply_op("sdpa", _f, query, key, value, attn_mask)


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, fixed_seed_offset=None, rng_name="",
                    training=True, name=None):
    """Parity: nn/functional/flash_attention.py:242. Returns (out, softmax)."""
    out = scaled_dot_product_attention(query, key, value, None, dropout,
                                       causal, training)
    return out, None


def _segment_ids_from_cu(cu, total):
    """cu_seqlens (B+1,) prefix sums -> per-position segment ids (total,)."""
    pos = jnp.arange(total)
    return jnp.searchsorted(cu[1:].astype(pos.dtype), pos,
                            side="right").astype(jnp.int32)


def flash_attn_unpadded(query, key, value, cu_seqlens_q, cu_seqlens_k,
                        max_seqlen_q, max_seqlen_k, scale, dropout=0.0,
                        causal=False, return_softmax=False,
                        fixed_seed_offset=None, rng_name="", training=True,
                        name=None):
    """Varlen (packed) flash attention. Parity: flash_attn_unpadded
    (reference nn/functional/flash_attention.py).

    query/key/value: (total_tokens, num_heads, head_dim) — sequences packed
    along dim 0; cu_seqlens_*: (batch+1,) int32 prefix sums. Runs the Pallas
    varlen kernel (segment-id masking with block skipping) when shapes
    allow; falls back to a masked XLA composition otherwise.
    """
    total_q, H, D = query.shape
    total_k = key.shape[0]

    def _seg_pos(cq, ck):
        """Segment ids + per-sequence causal positions. The query position
        is adjusted by the per-sequence (k_len - q_len) difference so
        causal means "key pos-in-seq <= query pos-in-seq + len_diff(seq)"
        — a single packed-global offset is wrong when the differences are
        non-uniform."""
        segq = _segment_ids_from_cu(cq, total_q)
        segk = _segment_ids_from_cu(ck, total_k)
        pq = jnp.arange(total_q) - jnp.take(cq, segq, mode="clip")
        pk = jnp.arange(total_k) - jnp.take(ck, segk, mode="clip")
        qlen = jnp.diff(cq)
        klen = jnp.diff(ck)
        ldiff = jnp.take(klen, segq, mode="clip") - jnp.take(qlen, segq,
                                                             mode="clip")
        return segq, segk, (pq + ldiff).astype(jnp.int32), pk.astype(jnp.int32)

    can_pallas = _USE_PALLAS[0] and dropout == 0.0
    if can_pallas:
        try:
            from ...kernels import flash_attention as pallas_fa
            pallas_fa.check_supported((1, total_q, H, D), (1, total_k, H, D),
                                      query.dtype)

            def _f(q, k, v, cq, ck):
                segq, segk, pq, pk = _seg_pos(cq, ck)
                return pallas_fa.flash_attention_varlen_bshd(
                    q[None], k[None], v[None], segq[None], segk[None],
                    causal=causal, sm_scale=scale, q_positions=pq[None],
                    kv_positions=pk[None])[0]

            out = apply_op("flash_attn_unpadded", _f, query, key, value,
                           cu_seqlens_q, cu_seqlens_k)
            return out, None
        except ValueError:
            pass

    drop_key = rng_key() if (dropout > 0.0 and training) else None

    def _f(q, k, v, cq, ck):
        segq, segk, pq, pk = _seg_pos(cq, ck)
        allow = segq[:, None] == segk[None, :]
        if causal:
            allow = allow & (pk[None, :] <= pq[:, None])
        return _sdpa_ref(q[None], k[None], v[None], allow[None, None],
                         dropout, False, drop_key, training, scale=scale)[0]

    out = apply_op("flash_attn_unpadded", _f, query, key, value,
                   cu_seqlens_q, cu_seqlens_k)
    return out, None


def flashmask_attention(query, key, value, startend_row_indices=None,
                        dropout=0.0, causal=True, window_size=None, name=None):
    """Sparse-mask attention (parity: flashmask_attention:1098).

    startend_row_indices: (B, H_or_1, S, 1|2|4) int32 — per-column row
    bounds defining the mask, as in the reference. Runs a block-sparse
    Pallas kernel that rebuilds the mask tile-by-tile from the O(S*C)
    bounds (skipping fully-masked K/V blocks for the causal document-mask
    case); falls back to a dense-mask XLA composition for unsupported
    shapes or dropout.
    """
    if window_size is not None:
        if startend_row_indices is not None:
            raise ValueError(
                "pass either window_size or startend_row_indices, not both")
        # sliding window -> flashmask bounds. Causal (left w): key col c is
        # masked for rows >= c + w + 1 (C==1). Non-causal (left, right):
        # masked for rows >= c + left + 1 or rows < c - right (C==2).
        w = window_size if isinstance(window_size, (tuple, list)) \
            else (window_size, window_size)
        sk = key.shape[1]
        b = query.shape[0]
        from ...core.tensor import Tensor
        cols = jnp.arange(sk)
        start = jnp.minimum(cols + int(w[0]) + 1, sk).astype(jnp.int32)
        if causal:
            idx = start[None, None, :, None]
            startend_row_indices = Tensor(
                jnp.broadcast_to(idx, (b, 1, sk, 1)))
        else:
            end = jnp.maximum(cols - int(w[1]), 0).astype(jnp.int32)
            idx = jnp.stack([start, end], axis=-1)[None, None]
            startend_row_indices = Tensor(
                jnp.broadcast_to(idx, (b, 1, sk, 2)))
    if startend_row_indices is None:
        return scaled_dot_product_attention(query, key, value, None, dropout,
                                            causal)
    B, Sq, H, D = query.shape
    if Sq != key.shape[1]:
        raise ValueError("flashmask_attention requires Sq == Sk (row bounds "
                         "index a square score matrix)")
    can_pallas = _USE_PALLAS[0] and dropout == 0.0
    if can_pallas:
        try:
            from ...kernels import flash_attention as pallas_fa
            pallas_fa.check_supported(tuple(query.shape), tuple(key.shape),
                                      query.dtype)
            C = startend_row_indices.shape[-1]
            if causal and C not in (1, 2):
                raise ValueError("unsupported bound count")
            if not causal and C not in (2, 4):
                raise ValueError("unsupported bound count")

            def _f(q, k, v, idx):
                return pallas_fa.flashmask_attention_bshd(q, k, v, idx,
                                                          causal=causal)

            return apply_op("flashmask_attention", _f, query, key, value,
                            startend_row_indices)
        except ValueError:
            pass

    def _build_mask(idx, sq, sk):
        # idx: (B, H, Sk, C); rows r of column c are masked per bounds
        rows = jnp.arange(sq)[None, None, :, None]  # 1,1,Sq,1
        c = idx.shape[-1]
        idxb = jnp.swapaxes(idx, 2, 3)  # B,H,C,Sk
        if causal:
            if c == 1:
                start = idxb[:, :, 0][:, :, None, :]  # B,H,1,Sk
                masked = rows >= start
            else:
                start = idxb[:, :, 0][:, :, None, :]
                end = idxb[:, :, 1][:, :, None, :]
                masked = (rows >= start) & (rows < end)
            cm = jnp.tril(jnp.ones((sq, sk), bool))
            allow = cm[None, None] & ~masked
        else:
            if c == 2:
                start_u = idxb[:, :, 0][:, :, None, :]
                end_d = idxb[:, :, 1][:, :, None, :]
                masked = (rows >= start_u) | (rows < end_d)
            else:
                start_u = idxb[:, :, 0][:, :, None, :]
                end_u = idxb[:, :, 1][:, :, None, :]
                start_d = idxb[:, :, 2][:, :, None, :]
                end_d = idxb[:, :, 3][:, :, None, :]
                masked = ((rows >= start_u) & (rows < end_u)) | \
                         ((rows >= start_d) & (rows < end_d))
            allow = ~masked
        return allow

    sq, sk = query.shape[1], key.shape[1]
    drop_key = rng_key() if dropout > 0.0 else None

    def _f(q, k, v, idx):
        allow = _build_mask(idx, sq, sk)
        # broadcast mask over heads: allow is B,H,Sq,Sk (H may be 1)
        return _sdpa_ref(q, k, v, allow, dropout, False, drop_key, True)
    return apply_op("flashmask_attention", _f, query, key, value,
                    startend_row_indices)


class sdp_kernel:
    """Context manager for kernel selection (parity: paddle sdp_kernel)."""

    def __init__(self, enable_flash=True, enable_math=True,
                 enable_mem_efficient=True):
        self.enable_flash = enable_flash
        self._prev = None

    def __enter__(self):
        self._prev = _USE_PALLAS[0]
        _USE_PALLAS[0] = self.enable_flash
        return self

    def __exit__(self, *a):
        _USE_PALLAS[0] = self._prev
        return False
