"""Attention functionals.

Parity: reference `python/paddle/nn/functional/flash_attention.py`
(flash_attention:242, scaled_dot_product_attention:976, flashmask_attention:1098).

TPU-native: the default path is a jnp composition that XLA fuses well at
moderate sequence lengths; for long sequences `paddle_tpu.kernels.
flash_attention` provides a Pallas fused kernel (used automatically when
available and shapes allow). Layouts follow the reference: (batch, seqlen,
num_heads, head_dim).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...framework.random import rng_key
from ...ops.dispatch import apply_op

__all__ = ["scaled_dot_product_attention", "flash_attention",
           "flashmask_attention", "sdp_kernel"]

_USE_PALLAS = [True]


def _sdpa_ref(q, k, v, mask=None, dropout_p=0.0, causal=False, key=None, training=True):
    """(B, S, H, D) attention, fp32 softmax accumulation."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    scale = 1.0 / math.sqrt(d)
    qt = jnp.swapaxes(q, 1, 2)  # B,H,S,D
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    scores = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) * scale
    scores = scores.astype(jnp.float32)
    if causal:
        cm = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        scores = jnp.where(cm, scores, -jnp.inf)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            scores = jnp.where(mask, scores, -jnp.inf)
        else:
            scores = scores + mask.astype(scores.dtype)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    if dropout_p > 0.0 and training and key is not None:
        keep = jax.random.bernoulli(key, 1.0 - dropout_p, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_p), 0.0)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vt)
    return jnp.swapaxes(out, 1, 2)  # B,S,H,D


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, name=None):
    """Parity: nn/functional/flash_attention.py:976. Shapes (B, S, H, D)."""
    can_pallas = (_USE_PALLAS[0] and attn_mask is None and dropout_p == 0.0)
    if can_pallas:
        try:
            from ...kernels import flash_attention as pallas_fa
            pallas_fa.check_supported(
                tuple(query.shape), tuple(key.shape), query.dtype)
            def _f(q, k, v):
                return pallas_fa.flash_attention_bshd(q, k, v, causal=is_causal)
            return apply_op("flash_attention", _f, query, key, value)
        except ValueError:
            pass  # unsupported shape: fall through to the XLA composition
    drop_key = rng_key() if (dropout_p > 0.0 and training) else None
    def _f(q, k, v, m):
        return _sdpa_ref(q, k, v, m, dropout_p, is_causal, drop_key, training)
    return apply_op("sdpa", _f, query, key, value, attn_mask)


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, fixed_seed_offset=None, rng_name="",
                    training=True, name=None):
    """Parity: nn/functional/flash_attention.py:242. Returns (out, softmax)."""
    out = scaled_dot_product_attention(query, key, value, None, dropout,
                                       causal, training)
    return out, None


def flashmask_attention(query, key, value, startend_row_indices=None,
                        dropout=0.0, causal=True, window_size=None, name=None):
    """Sparse-mask attention (parity: flashmask_attention:1098).

    startend_row_indices: (B, H_or_1, S, 1|2|4) int32 — per-column row bounds
    defining the mask, as in the reference. This implementation materializes
    the boolean mask from the indices and runs the fused SDPA path; a
    block-sparse Pallas kernel is the planned upgrade.
    """
    if startend_row_indices is None:
        return scaled_dot_product_attention(query, key, value, None, dropout,
                                            causal)

    def _build_mask(idx, sq, sk):
        # idx: (B, H, Sk, C); rows r of column c are masked per bounds
        rows = jnp.arange(sq)[None, None, :, None]  # 1,1,Sq,1
        c = idx.shape[-1]
        idxb = jnp.swapaxes(idx, 2, 3)  # B,H,C,Sk
        if causal:
            if c == 1:
                start = idxb[:, :, 0][:, :, None, :]  # B,H,1,Sk
                masked = rows >= start
            else:
                start = idxb[:, :, 0][:, :, None, :]
                end = idxb[:, :, 1][:, :, None, :]
                masked = (rows >= start) & (rows < end)
            cm = jnp.tril(jnp.ones((sq, sk), bool))
            allow = cm[None, None] & ~masked
        else:
            if c == 2:
                start_u = idxb[:, :, 0][:, :, None, :]
                end_d = idxb[:, :, 1][:, :, None, :]
                masked = (rows >= start_u) | (rows < end_d)
            else:
                start_u = idxb[:, :, 0][:, :, None, :]
                end_u = idxb[:, :, 1][:, :, None, :]
                start_d = idxb[:, :, 2][:, :, None, :]
                end_d = idxb[:, :, 3][:, :, None, :]
                masked = ((rows >= start_u) & (rows < end_u)) | \
                         ((rows >= start_d) & (rows < end_d))
            allow = ~masked
        return allow

    sq, sk = query.shape[1], key.shape[1]

    def _f(q, k, v, idx):
        allow = _build_mask(idx, sq, sk)
        # broadcast mask over heads: allow is B,H,Sq,Sk (H may be 1)
        return _sdpa_ref(q, k, v, allow, dropout, False, None, True)
    return apply_op("flashmask_attention", _f, query, key, value,
                    startend_row_indices)


class sdp_kernel:
    """Context manager for kernel selection (parity: paddle sdp_kernel)."""

    def __init__(self, enable_flash=True, enable_math=True,
                 enable_mem_efficient=True):
        self.enable_flash = enable_flash
        self._prev = None

    def __enter__(self):
        self._prev = _USE_PALLAS[0]
        _USE_PALLAS[0] = self.enable_flash
        return self

    def __exit__(self, *a):
        _USE_PALLAS[0] = self._prev
        return False
