"""Normalization functionals.

Parity: reference `python/paddle/nn/functional/norm.py` + phi kernels
layer_norm / batch_norm / group_norm / instance_norm and the fused
`rms_norm_kernel.h`. On TPU these are VPU-bound; XLA fuses them into
neighbors. A Pallas fused rms_norm lives in paddle_tpu.kernels for the
residual-add variant.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor
from ...ops.dispatch import apply_op

__all__ = ["layer_norm", "batch_norm", "group_norm", "instance_norm",
           "local_response_norm", "rms_norm", "spectral_norm"]


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-05, name=None):
    if isinstance(normalized_shape, int):
        normalized_shape = [normalized_shape]
    n_axes = len(list(normalized_shape))

    def _f(a, w, b):
        axes = tuple(range(a.ndim - n_axes, a.ndim))
        mean = jnp.mean(a, axis=axes, keepdims=True)
        var = jnp.var(a, axis=axes, keepdims=True)
        out = (a - mean) * jax.lax.rsqrt(var + epsilon)
        if w is not None:
            out = out * w
        if b is not None:
            out = out + b
        return out
    return apply_op("layer_norm", _f, x, weight, bias)


def rms_norm(x, weight=None, bias=None, epsilon=1e-6, begin_norm_axis=-1,
             residual=None, name=None):
    """Fused-capable RMSNorm (+optional residual add).
    Parity: reference `paddle/phi/kernels/rms_norm_kernel.h`."""
    def _f(a, w, b, res):
        if res is not None:
            a = a + res
        ax = begin_norm_axis % a.ndim
        axes = tuple(range(ax, a.ndim))
        ms = jnp.mean(jnp.square(a.astype(jnp.float32)), axis=axes, keepdims=True)
        out = (a.astype(jnp.float32) * jax.lax.rsqrt(ms + epsilon)).astype(a.dtype)
        if w is not None:
            out = out * w
        if b is not None:
            out = out + b
        return out
    return apply_op("rms_norm", _f, x, weight, bias, residual)


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-05,
               data_format="NCHW", use_global_stats=None, name=None):
    """Running stats are updated in-place on the passed Tensors (the
    reference mutates the same way: phi batch_norm kernel's mean_out/var_out)."""
    channel_ax = 1 if data_format.startswith("NC") else -1
    use_stats = (not training) if use_global_stats is None else use_global_stats

    def _f(a, w, b, rm, rv):
        ax = channel_ax % a.ndim
        red_axes = tuple(i for i in range(a.ndim) if i != ax)
        if use_stats:
            mean, var = rm, rv
        else:
            mean = jnp.mean(a, axis=red_axes)
            var = jnp.var(a, axis=red_axes)
        shape = [1] * a.ndim
        shape[ax] = a.shape[ax]
        out = (a - mean.reshape(shape)) * jax.lax.rsqrt(var.reshape(shape) + epsilon)
        if w is not None:
            out = out * w.reshape(shape)
        if b is not None:
            out = out + b.reshape(shape)
        return out, mean, var

    out, batch_mean, batch_var = apply_op(
        "batch_norm", _f, x, weight, bias,
        running_mean.detach() if isinstance(running_mean, Tensor) else running_mean,
        running_var.detach() if isinstance(running_var, Tensor) else running_var)

    if training and not use_stats and isinstance(running_mean, Tensor):
        m = momentum
        running_mean._data = running_mean._data * m + batch_mean._data * (1 - m)
        running_var._data = running_var._data * m + batch_var._data * (1 - m)
    return out


def group_norm(x, num_groups, epsilon=1e-05, weight=None, bias=None,
               data_format="NCHW", name=None):
    def _f(a, w, b):
        channel_last = data_format[-1] == "C"
        if channel_last:
            a_t = jnp.moveaxis(a, -1, 1)
        else:
            a_t = a
        n, c = a_t.shape[0], a_t.shape[1]
        g = int(num_groups)
        grouped = a_t.reshape((n, g, c // g) + a_t.shape[2:])
        axes = tuple(range(2, grouped.ndim))
        mean = jnp.mean(grouped, axis=axes, keepdims=True)
        var = jnp.var(grouped, axis=axes, keepdims=True)
        out = (grouped - mean) * jax.lax.rsqrt(var + epsilon)
        out = out.reshape(a_t.shape)
        shape = [1] * a_t.ndim
        shape[1] = c
        if w is not None:
            out = out * w.reshape(shape)
        if b is not None:
            out = out + b.reshape(shape)
        if channel_last:
            out = jnp.moveaxis(out, 1, -1)
        return out
    return apply_op("group_norm", _f, x, weight, bias)


def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats=True, momentum=0.9, eps=1e-05,
                  data_format="NCHW", name=None):
    def _f(a, w, b):
        axes = tuple(range(2, a.ndim))
        mean = jnp.mean(a, axis=axes, keepdims=True)
        var = jnp.var(a, axis=axes, keepdims=True)
        out = (a - mean) * jax.lax.rsqrt(var + eps)
        if w is not None:
            shape = [1, -1] + [1] * (a.ndim - 2)
            out = out * w.reshape(shape)
        if b is not None:
            shape = [1, -1] + [1] * (a.ndim - 2)
            out = out + b.reshape(shape)
        return out
    return apply_op("instance_norm", _f, x, weight, bias)


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    def _f(a):
        channel_last = data_format[-1] == "C"
        ch_ax = a.ndim - 1 if channel_last else 1
        sq = jnp.square(a)
        # sum over a window of `size` channels centered at each channel
        pad_lo = (size - 1) // 2
        pad_hi = size - 1 - pad_lo
        pads = [(0, 0)] * a.ndim
        pads[ch_ax] = (pad_lo, pad_hi)
        padded = jnp.pad(sq, pads)
        window = [1] * a.ndim
        window[ch_ax] = size
        summed = jax.lax.reduce_window(padded, 0.0, jax.lax.add,
                                       tuple(window), (1,) * a.ndim,
                                       [(0, 0)] * a.ndim)
        div = (k + alpha * summed) ** beta
        return a / div
    return apply_op("local_response_norm", _f, x)


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    def _f(w):
        wm = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
        u = jnp.ones((wm.shape[0],), w.dtype) / np.sqrt(wm.shape[0])
        v = jnp.ones((wm.shape[1],), w.dtype) / np.sqrt(wm.shape[1])
        for _ in range(power_iters):
            v = wm.T @ u
            v = v / (jnp.linalg.norm(v) + eps)
            u = wm @ v
            u = u / (jnp.linalg.norm(u) + eps)
        sigma = u @ wm @ v
        return w / sigma
    return apply_op("spectral_norm", _f, weight)
