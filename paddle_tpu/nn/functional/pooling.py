"""Pooling functionals via lax.reduce_window.

Parity: reference `python/paddle/nn/functional/pooling.py`.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...ops.dispatch import apply_op

__all__ = [
    "avg_pool1d", "avg_pool2d", "avg_pool3d", "max_pool1d", "max_pool2d",
    "max_pool3d", "adaptive_avg_pool1d", "adaptive_avg_pool2d",
    "adaptive_avg_pool3d", "adaptive_max_pool1d", "adaptive_max_pool2d",
    "adaptive_max_pool3d", "lp_pool1d", "lp_pool2d",
]


def _norm_tuple(v, n):
    if isinstance(v, (int, np.integer)):
        return (int(v),) * n
    return tuple(int(x) for x in v)


def _pool(x, kernel_size, stride, padding, n, reducer, init, data_format,
          ceil_mode=False, count_include_pad=True, divisor_override=None,
          is_avg=False):
    ks = _norm_tuple(kernel_size, n)
    st = _norm_tuple(stride if stride is not None else kernel_size, n)
    if isinstance(padding, str):
        pad_spec = padding.upper()
    else:
        pd = _norm_tuple(padding, n) if not (isinstance(padding, (list, tuple))
                                             and isinstance(padding[0], (list, tuple))) else padding
        if isinstance(pd[0], tuple) or isinstance(pd[0], list):
            pad_spec = [tuple(p) for p in pd]
        else:
            pad_spec = [(p, p) for p in pd]
    channel_last = data_format[-1] == "C"
    if channel_last:
        window = (1,) + ks + (1,)
        strides = (1,) + st + (1,)
        pads = [(0, 0)] + (pad_spec if isinstance(pad_spec, list) else None) + [(0, 0)] \
            if not isinstance(pad_spec, str) else pad_spec
    else:
        window = (1, 1) + ks
        strides = (1, 1) + st
        pads = [(0, 0), (0, 0)] + (pad_spec if isinstance(pad_spec, list) else None) \
            if not isinstance(pad_spec, str) else pad_spec

    def _f(a):
        if isinstance(pads, str):
            padding_cfg = pads
        else:
            padding_cfg = pads
            if ceil_mode:
                # extend right pads so that ceil-division windows fit
                padding_cfg = list(padding_cfg)
                sp_axes = range(1, 1 + n) if channel_last else range(2, 2 + n)
                for i, ax in enumerate(sp_axes):
                    size = a.shape[ax] + padding_cfg[ax][0] + padding_cfg[ax][1]
                    k, s = window[ax], strides[ax]
                    rem = (size - k) % s
                    if rem != 0:
                        padding_cfg[ax] = (padding_cfg[ax][0], padding_cfg[ax][1] + (s - rem))
        if is_avg:
            ones = jnp.ones_like(a)
            summed = jax.lax.reduce_window(a, 0.0 if a.dtype != jnp.bool_ else False,
                                           jax.lax.add, window, strides, padding_cfg)
            if divisor_override:
                return summed / divisor_override
            if count_include_pad and not isinstance(padding_cfg, str):
                div = float(np.prod(ks))
                return summed / div
            counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, strides, padding_cfg)
            return summed / counts
        init_val = -jnp.inf if jnp.issubdtype(a.dtype, jnp.floating) else jnp.iinfo(a.dtype).min
        return jax.lax.reduce_window(a, init_val, jax.lax.max, window, strides, padding_cfg)
    return apply_op("pool", _f, x)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, name=None):
    return _pool(x, kernel_size, stride, padding, 1, None, 0.0, "NCW",
                 ceil_mode, count_include_pad=not exclusive, is_avg=True)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW", name=None):
    return _pool(x, kernel_size, stride, padding, 2, None, 0.0, data_format,
                 ceil_mode, count_include_pad=not exclusive,
                 divisor_override=divisor_override, is_avg=True)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW", name=None):
    return _pool(x, kernel_size, stride, padding, 3, None, 0.0, data_format,
                 ceil_mode, count_include_pad=not exclusive,
                 divisor_override=divisor_override, is_avg=True)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, name=None):
    out = _pool(x, kernel_size, stride, padding, 1, None, None, "NCW", ceil_mode)
    if return_mask:
        return out, _max_pool_indices(x, kernel_size, stride, padding, 1, "NCW")
    return out


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    out = _pool(x, kernel_size, stride, padding, 2, None, None, data_format, ceil_mode)
    if return_mask:
        return out, _max_pool_indices(x, kernel_size, stride, padding, 2, data_format)
    return out


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    out = _pool(x, kernel_size, stride, padding, 3, None, None, data_format, ceil_mode)
    if return_mask:
        return out, _max_pool_indices(x, kernel_size, stride, padding, 3, data_format)
    return out


def _max_pool_indices(x, kernel_size, stride, padding, n, data_format):
    """Flat spatial argmax indices, paddle-style (int64)."""
    ks = _norm_tuple(kernel_size, n)
    st = _norm_tuple(stride if stride is not None else kernel_size, n)
    pd = _norm_tuple(padding, n)

    def _f(a):
        # build index array of flat spatial positions and reduce with max-by-value
        channel_last = data_format[-1] == "C"
        sp_shape = a.shape[1:-1] if channel_last else a.shape[2:]
        flat = jnp.arange(int(np.prod(sp_shape)), dtype=jnp.int32).reshape(sp_shape)
        if channel_last:
            idx = jnp.broadcast_to(flat[None, ..., None], a.shape)
            window = (1,) + ks + (1,)
            strides = (1,) + st + (1,)
            pads = [(0, 0)] + [(p, p) for p in pd] + [(0, 0)]
        else:
            idx = jnp.broadcast_to(flat[None, None], a.shape)
            window = (1, 1) + ks
            strides = (1, 1) + st
            pads = [(0, 0), (0, 0)] + [(p, p) for p in pd]
        neg = jnp.finfo(a.dtype).min if jnp.issubdtype(a.dtype, jnp.floating) else jnp.iinfo(a.dtype).min

        def reducer(acc, cur):
            av, ai = acc
            cv, ci = cur
            take_cur = cv > av
            return (jnp.where(take_cur, cv, av), jnp.where(take_cur, ci, ai))

        _, out_idx = jax.lax.reduce_window(
            (a, idx), (jnp.asarray(neg, a.dtype), jnp.asarray(0, jnp.int32)),
            reducer, window, strides, pads)
        return out_idx.astype(jnp.int64)
    return apply_op("max_pool_indices", _f, x)


def _adaptive_pool(x, output_size, n, is_avg, data_format):
    os_ = _norm_tuple(output_size, n)

    def _f(a):
        channel_last = data_format[-1] == "C"
        sp_axes = list(range(1, 1 + n)) if channel_last else list(range(2, 2 + n))
        out = a
        for i, ax in enumerate(sp_axes):
            in_size = out.shape[ax]
            o = os_[i] if os_[i] is not None else in_size
            if in_size == o:
                continue
            if in_size % o == 0:
                k = in_size // o
                new_shape = out.shape[:ax] + (o, k) + out.shape[ax + 1:]
                r = out.reshape(new_shape)
                out = jnp.mean(r, axis=ax + 1) if is_avg else jnp.max(r, axis=ax + 1)
            else:
                # general adaptive: variable window per output position
                starts = (np.arange(o) * in_size) // o
                ends = ((np.arange(o) + 1) * in_size + o - 1) // o
                pieces = []
                for s, e in zip(starts, ends):
                    sl = jax.lax.slice_in_dim(out, int(s), int(e), axis=ax)
                    red = jnp.mean(sl, axis=ax, keepdims=True) if is_avg \
                        else jnp.max(sl, axis=ax, keepdims=True)
                    pieces.append(red)
                out = jnp.concatenate(pieces, axis=ax)
        return out
    return apply_op("adaptive_pool", _f, x)


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_pool(x, output_size, 1, True, "NCW")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_pool(x, output_size, 2, True, data_format)


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_pool(x, output_size, 3, True, data_format)


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 1, False, "NCW")


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 2, False, "NCHW")


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 3, False, "NCDHW")


def lp_pool1d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCL", name=None):
    p = float(norm_type)
    from ...ops.dispatch import apply_op as _ap
    powed = _ap("lp_pow", lambda a: jnp.abs(a) ** p, x)
    pooled = _pool(powed, kernel_size, stride, padding, 1, None, 0.0,
                   "NCW", ceil_mode, is_avg=True)
    ks = _norm_tuple(kernel_size, 1)
    return _ap("lp_root", lambda a: (a * float(np.prod(ks))) ** (1.0 / p), pooled)


def lp_pool2d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCHW", name=None):
    p = float(norm_type)
    from ...ops.dispatch import apply_op as _ap
    powed = _ap("lp_pow", lambda a: jnp.abs(a) ** p, x)
    pooled = _pool(powed, kernel_size, stride, padding, 2, None, 0.0,
                   data_format, ceil_mode, is_avg=True)
    ks = _norm_tuple(kernel_size, 2)
    return _ap("lp_root", lambda a: (a * float(np.prod(ks))) ** (1.0 / p), pooled)
