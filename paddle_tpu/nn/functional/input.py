from .common import embedding, one_hot  # noqa: F401
