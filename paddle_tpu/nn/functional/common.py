"""Common functionals: linear, dropout, pad, normalize, interpolate, embedding.

Parity: reference `python/paddle/nn/functional/common.py` + `input.py`.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor
from ...framework.random import rng_key
from ...ops.dispatch import apply_op, def_op

__all__ = [
    "linear", "dropout", "dropout2d", "dropout3d", "alpha_dropout", "pad",
    "zeropad2d", "normalize", "embedding", "one_hot", "interpolate",
    "upsample", "pixel_shuffle", "pixel_unshuffle", "channel_shuffle",
    "cosine_similarity", "bilinear", "label_smooth", "class_center_sample",
    "fold", "unfold",
]


@def_op("linear")
def linear(x, weight, bias=None, name=None):
    """y = x @ W (+ b). Weight layout matches the reference: (in, out)
    (`python/paddle/nn/functional/common.py` linear)."""
    out = jnp.matmul(x, weight)
    if bias is not None:
        out = out + bias
    return out


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training:
            return apply_op("dropout_infer", lambda a: a * (1.0 - p), x)
        return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))
    key = rng_key()
    def _f(a):
        shape = list(a.shape)
        if axis is not None:
            axes = axis if isinstance(axis, (list, tuple)) else [axis]
            shape = [s if i in [ax % a.ndim for ax in axes] else 1
                     for i, s in enumerate(a.shape)]
        keep = jax.random.bernoulli(key, 1.0 - p, tuple(shape))
        if mode == "upscale_in_train":
            return jnp.where(keep, a / (1.0 - p), jnp.zeros((), a.dtype))
        return jnp.where(keep, a, jnp.zeros((), a.dtype))
    return apply_op("dropout", _f, x)


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    ax = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p, axis=ax, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    ax = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p, axis=ax, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return x
    key = rng_key()
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale
    def _f(a):
        keep = jax.random.bernoulli(key, 1.0 - p, a.shape)
        coef_a = ((1.0 - p) * (1.0 + p * alpha_p ** 2)) ** -0.5
        coef_b = -coef_a * p * alpha_p
        return coef_a * jnp.where(keep, a, alpha_p) + coef_b
    return apply_op("alpha_dropout", _f, x)


def _pad_mode_to_np(mode):
    return {"constant": "constant", "reflect": "reflect",
            "replicate": "edge", "circular": "wrap"}[mode]


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", pad_from_left_axis=True, name=None):
    if isinstance(pad, Tensor):
        pad = [int(v) for v in np.asarray(pad._data)]
    pad = [int(p) for p in pad]
    # ONE per-dim widths resolution feeds both the kernel and the SPMD
    # pad rule (two parallel copies of paddle's two pad-list layouts
    # would silently desync)
    nd = x.ndim
    if len(pad) == 2 * nd:
        # full-tensor pad, paddle order: axis-major from first axis
        widths = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
    else:
        # partial pad on spatial dims, paddle order: last-dim-first pairs
        n_spatial = len(pad) // 2
        widths = [(0, 0)] * nd
        if data_format.endswith("C"):  # NHWC-ish: spatial dims are 1..nd-1
            spatial = list(range(1, nd - 1))
        else:  # NCHW-ish: spatial dims are 2..nd-1
            spatial = list(range(2, nd))
        # paddle pads [left,right] for the LAST spatial dim first
        for i in range(n_spatial):
            dim = spatial[-(i + 1)] if n_spatial <= len(spatial) else i
            widths[dim] = (pad[2 * i], pad[2 * i + 1])

    def _f(a):
        if mode == "constant":
            return jnp.pad(a, widths, mode="constant", constant_values=value)
        return jnp.pad(a, widths, mode=_pad_mode_to_np(mode))
    padded = [i for i, (lo, hi) in enumerate(widths) if lo or hi]
    return apply_op("pad", _f, x, op_attrs={"padded_dims": padded})


def zeropad2d(x, padding, data_format="NCHW", name=None):
    return pad(x, padding, mode="constant", value=0.0, data_format=data_format)


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    def _f(a):
        norm = jnp.sum(jnp.abs(a) ** p, axis=axis, keepdims=True) ** (1.0 / p)
        return a / jnp.maximum(norm, epsilon)
    return apply_op("normalize", _f, x)


@def_op("embedding")
def embedding(x, weight, padding_idx=None, sparse=False, max_norm=None, norm_type=2.0, name=None):
    """Parity: `python/paddle/nn/functional/input.py` embedding. TPU note:
    gathers from an HBM-resident table; with a sharded table this becomes the
    c_embedding/VocabParallelEmbedding path (see distributed.mpu)."""
    w = weight
    if padding_idx is not None:
        pidx = padding_idx if padding_idx >= 0 else w.shape[0] + padding_idx
        w = w.at[pidx].set(jnp.zeros((w.shape[1],), w.dtype))
    return jnp.take(w, x, axis=0)


def one_hot(x, num_classes, name=None):
    return apply_op("one_hot",
                    lambda a: jax.nn.one_hot(a, int(num_classes), dtype=jnp.float32),
                    x, op_attrs={"num_classes": int(num_classes)})


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format=None, name=None):
    if data_format is None:
        data_format = "NCHW" if (x.ndim == 4) else ("NCDHW" if x.ndim == 5 else "NCW")
    channel_last = data_format[-1] == "C"
    nd = x.ndim - 2
    if isinstance(size, Tensor):
        size = [int(v) for v in np.asarray(size._data)]
    if size is not None and not isinstance(size, (list, tuple)):
        size = [int(size)] * nd
    if scale_factor is not None and not isinstance(scale_factor, (list, tuple)):
        scale_factor = [float(scale_factor)] * nd

    def _f(a):
        arr = a
        if not channel_last:
            # move channels last for jax.image
            perm = [0] + list(range(2, arr.ndim)) + [1]
            arr = jnp.transpose(arr, perm)
        spatial = arr.shape[1:-1]
        if size is not None:
            out_spatial = tuple(int(s) for s in size)
        else:
            out_spatial = tuple(int(np.floor(s * f)) for s, f in zip(spatial, scale_factor))
        out_shape = (arr.shape[0],) + out_spatial + (arr.shape[-1],)
        m = {"nearest": "nearest", "bilinear": "bilinear", "trilinear": "trilinear",
             "linear": "linear", "bicubic": "cubic", "area": "linear"}[mode]
        if mode == "nearest":
            out = jax.image.resize(arr, out_shape, method="nearest")
        elif align_corners and mode in ("bilinear", "linear", "trilinear", "bicubic"):
            # jax.image.resize has no align_corners; emulate via coordinate map
            out = _resize_align_corners(arr, out_spatial, m)
        else:
            out = jax.image.resize(arr, out_shape, method=m)
        if not channel_last:
            inv = [0, arr.ndim - 1] + list(range(1, arr.ndim - 1))
            out = jnp.transpose(out, inv)
        return out
    return apply_op("interpolate", _f, x)


def _resize_align_corners(arr, out_spatial, method):
    # arr: (N, *spatial, C). Per-dim linear interpolation with align_corners.
    out = arr
    for d, new_size in enumerate(out_spatial):
        axis = 1 + d
        old_size = out.shape[axis]
        if new_size == old_size:
            continue
        if new_size == 1 or old_size == 1:
            idx = jnp.zeros((new_size,), jnp.int32)
            out = jnp.take(out, idx, axis=axis)
            continue
        pos = jnp.linspace(0.0, old_size - 1.0, new_size)
        lo = jnp.floor(pos).astype(jnp.int32)
        hi = jnp.minimum(lo + 1, old_size - 1)
        w = (pos - lo).astype(out.dtype)
        shape = [1] * out.ndim
        shape[axis] = new_size
        w = w.reshape(shape)
        out = jnp.take(out, lo, axis=axis) * (1 - w) + jnp.take(out, hi, axis=axis) * w
    return out


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, align_mode=0, data_format=None, name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode, data_format)


@def_op("pixel_shuffle")
def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = int(upscale_factor)
    if data_format == "NCHW":
        n, c, h, w = x.shape
        out = x.reshape(n, c // (r * r), r, r, h, w)
        out = jnp.transpose(out, (0, 1, 4, 2, 5, 3))
        return out.reshape(n, c // (r * r), h * r, w * r)
    n, h, w, c = x.shape
    out = x.reshape(n, h, w, r, r, c // (r * r))
    out = jnp.transpose(out, (0, 1, 3, 2, 4, 5))
    return out.reshape(n, h * r, w * r, c // (r * r))


@def_op("pixel_unshuffle")
def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    r = int(downscale_factor)
    if data_format == "NCHW":
        n, c, h, w = x.shape
        out = x.reshape(n, c, h // r, r, w // r, r)
        out = jnp.transpose(out, (0, 1, 3, 5, 2, 4))
        return out.reshape(n, c * r * r, h // r, w // r)
    n, h, w, c = x.shape
    out = x.reshape(n, h // r, r, w // r, r, c)
    out = jnp.transpose(out, (0, 1, 3, 2, 4, 5))
    return out.reshape(n, h // r, w // r, c * r * r)


@def_op("channel_shuffle")
def channel_shuffle(x, groups, data_format="NCHW", name=None):
    g = int(groups)
    if data_format == "NCHW":
        n, c, h, w = x.shape
        out = x.reshape(n, g, c // g, h, w)
        out = jnp.swapaxes(out, 1, 2)
        return out.reshape(n, c, h, w)
    n, h, w, c = x.shape
    out = x.reshape(n, h, w, g, c // g)
    out = jnp.swapaxes(out, 3, 4)
    return out.reshape(n, h, w, c)


@def_op("cosine_similarity")
def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    dot = jnp.sum(x1 * x2, axis=axis)
    n1 = jnp.linalg.norm(x1, axis=axis)
    n2 = jnp.linalg.norm(x2, axis=axis)
    return dot / jnp.maximum(n1 * n2, eps)


@def_op("bilinear")
def bilinear(x1, x2, weight, bias=None, name=None):
    # weight: (out_features, in1, in2)
    out = jnp.einsum("bi,oij,bj->bo", x1, weight, x2)
    if bias is not None:
        out = out + bias
    return out


@def_op("label_smooth")
def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    k = label.shape[-1]
    if prior_dist is not None:
        return (1.0 - epsilon) * label + epsilon * prior_dist
    return (1.0 - epsilon) * label + epsilon / k


def class_center_sample(label, num_classes, num_samples, group=None):
    raise NotImplementedError(
        "class_center_sample (PartialFC) is not implemented; use "
        "distributed.mpu.ParallelCrossEntropy for large-vocab classification.")


@def_op("unfold")
def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """im2col. Parity: python/paddle/nn/functional/common.py unfold."""
    ks = kernel_sizes if isinstance(kernel_sizes, (list, tuple)) else [kernel_sizes] * 2
    st = strides if isinstance(strides, (list, tuple)) else [strides] * 2
    dl = dilations if isinstance(dilations, (list, tuple)) else [dilations] * 2
    pd = paddings if isinstance(paddings, (list, tuple)) else [paddings] * 2
    if len(pd) == 2:
        pd = [pd[0], pd[0], pd[1], pd[1]]
    n, c, h, w = x.shape
    xp = jnp.pad(x, ((0, 0), (0, 0), (pd[0], pd[1]), (pd[2], pd[3])))
    oh = (xp.shape[2] - (dl[0] * (ks[0] - 1) + 1)) // st[0] + 1
    ow = (xp.shape[3] - (dl[1] * (ks[1] - 1) + 1)) // st[1] + 1
    patches = []
    for i in range(ks[0]):
        for j in range(ks[1]):
            sl = xp[:, :, i * dl[0]: i * dl[0] + (oh - 1) * st[0] + 1: st[0],
                    j * dl[1]: j * dl[1] + (ow - 1) * st[1] + 1: st[1]]
            patches.append(sl)
    out = jnp.stack(patches, axis=2)  # (N, C, kh*kw, OH, OW)
    return out.reshape(n, c * ks[0] * ks[1], oh * ow)


@def_op("fold")
def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    os_ = output_sizes if isinstance(output_sizes, (list, tuple)) else [output_sizes] * 2
    ks = kernel_sizes if isinstance(kernel_sizes, (list, tuple)) else [kernel_sizes] * 2
    st = strides if isinstance(strides, (list, tuple)) else [strides] * 2
    dl = dilations if isinstance(dilations, (list, tuple)) else [dilations] * 2
    pd = paddings if isinstance(paddings, (list, tuple)) else [paddings] * 2
    if len(pd) == 2:
        pd = [pd[0], pd[0], pd[1], pd[1]]
    n, ckk, L = x.shape
    c = ckk // (ks[0] * ks[1])
    ph, pw = os_[0] + pd[0] + pd[1], os_[1] + pd[2] + pd[3]
    oh = (ph - (dl[0] * (ks[0] - 1) + 1)) // st[0] + 1
    ow = (pw - (dl[1] * (ks[1] - 1) + 1)) // st[1] + 1
    xr = x.reshape(n, c, ks[0], ks[1], oh, ow)
    out = jnp.zeros((n, c, ph, pw), x.dtype)
    for i in range(ks[0]):
        for j in range(ks[1]):
            out = out.at[:, :, i * dl[0]: i * dl[0] + (oh - 1) * st[0] + 1: st[0],
                         j * dl[1]: j * dl[1] + (ow - 1) * st[1] + 1: st[1]].add(xr[:, :, i, j])
    return out[:, :, pd[0]: ph - pd[1], pd[2]: pw - pd[3]]
