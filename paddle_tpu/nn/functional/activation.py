"""Activation functionals.

Parity: reference `python/paddle/nn/functional/activation.py`. All are jnp
compositions that XLA fuses into surrounding matmuls (the reference needs
hand-fused CUDA kernels like fused_bias_act for this; on TPU the compiler
does it).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...ops.dispatch import apply_op, def_op

__all__ = [
    "relu", "relu_", "relu6", "gelu", "silu", "swish", "sigmoid", "softmax",
    "softmax_", "log_softmax", "tanh", "tanh_", "leaky_relu", "elu", "elu_",
    "selu", "celu", "hardswish", "hardsigmoid", "hardtanh", "mish",
    "softplus", "softshrink", "hardshrink", "tanhshrink", "thresholded_relu",
    "glu", "swiglu", "prelu", "rrelu", "maxout", "log_sigmoid", "softsign",
    "gumbel_softmax",
]


def _unary(op_name, fn):
    def op(x, name=None):
        return apply_op(op_name, fn, x)
    op.__name__ = op_name
    op.raw = fn
    return op


relu = _unary("relu", jax.nn.relu)
relu6 = _unary("relu6", jax.nn.relu6)
silu = _unary("silu", jax.nn.silu)
sigmoid = _unary("sigmoid", jax.nn.sigmoid)
tanh = _unary("tanh", jnp.tanh)
log_sigmoid = _unary("log_sigmoid", jax.nn.log_sigmoid)
softsign = _unary("softsign", jax.nn.soft_sign)
mish = _unary("mish", jax.nn.mish)
hardswish = _unary("hardswish", jax.nn.hard_swish)


def relu_(x, name=None):
    out = relu(x)
    x._data = out._data
    x._grad_node = out._grad_node
    x._grad_out_idx = out._grad_out_idx
    x.stop_gradient = out.stop_gradient
    return x


tanh_ = tanh
softmax_ = None  # set below


def gelu(x, approximate=False, name=None):
    return apply_op("gelu", lambda a: jax.nn.gelu(a, approximate=approximate), x)


def swish(x, name=None):
    return apply_op("swish", jax.nn.silu, x)


def softmax(x, axis=-1, dtype=None, name=None):
    from ...core.dtype import convert_dtype
    d = convert_dtype(dtype)
    def _f(a):
        if d is not None:
            a = a.astype(d)
        return jax.nn.softmax(a, axis=int(axis))
    return apply_op("softmax", _f, x)


softmax_ = softmax


def log_softmax(x, axis=-1, dtype=None, name=None):
    from ...core.dtype import convert_dtype
    d = convert_dtype(dtype)
    def _f(a):
        if d is not None:
            a = a.astype(d)
        return jax.nn.log_softmax(a, axis=int(axis))
    return apply_op("log_softmax", _f, x)


def leaky_relu(x, negative_slope=0.01, name=None):
    return apply_op("leaky_relu", lambda a: jax.nn.leaky_relu(a, negative_slope), x)


def elu(x, alpha=1.0, name=None):
    return apply_op("elu", lambda a: jax.nn.elu(a, alpha), x)


def elu_(x, alpha=1.0, name=None):
    out = elu(x, alpha)
    x._data = out._data
    x._grad_node = out._grad_node
    x._grad_out_idx = out._grad_out_idx
    x.stop_gradient = out.stop_gradient
    return x


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return apply_op("selu",
                    lambda a: scale * jnp.where(a > 0, a, alpha * jnp.expm1(a)), x)


def celu(x, alpha=1.0, name=None):
    return apply_op("celu", lambda a: jax.nn.celu(a, alpha), x)


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return apply_op("hardsigmoid",
                    lambda a: jnp.clip(slope * a + offset, 0.0, 1.0), x)


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return apply_op("hardtanh", lambda a: jnp.clip(a, min, max), x)


def softplus(x, beta=1.0, threshold=20.0, name=None):
    def _f(a):
        scaled = beta * a
        return jnp.where(scaled > threshold, a, jnp.log1p(jnp.exp(scaled)) / beta)
    return apply_op("softplus", _f, x)


def softshrink(x, threshold=0.5, name=None):
    return apply_op("softshrink",
                    lambda a: jnp.where(a > threshold, a - threshold,
                                        jnp.where(a < -threshold, a + threshold, 0.0)), x)


def hardshrink(x, threshold=0.5, name=None):
    return apply_op("hardshrink",
                    lambda a: jnp.where(jnp.abs(a) > threshold, a, 0.0), x)


def tanhshrink(x, name=None):
    return apply_op("tanhshrink", lambda a: a - jnp.tanh(a), x)


def thresholded_relu(x, threshold=1.0, value=0.0, name=None):
    return apply_op("thresholded_relu",
                    lambda a: jnp.where(a > threshold, a, value), x)


def glu(x, axis=-1, name=None):
    return apply_op("glu", lambda a: jax.nn.glu(a, axis=axis), x)


@def_op("swiglu")
def swiglu(x, y=None, name=None):
    """Parity: reference `paddle/phi/kernels/swiglu_kernel.h` — silu(x) * y.
    If y is None, x is split in half along the last axis."""
    if y is None:
        x, y = jnp.split(x, 2, axis=-1)
    return jax.nn.silu(x) * y


def prelu(x, weight, data_format="NCHW", name=None):
    def _f(a, w):
        if w.size == 1:
            wb = w.reshape(())
        else:
            shape = [1] * a.ndim
            ch_axis = 1 if data_format == "NCHW" else a.ndim - 1
            if a.ndim > 1:
                shape[ch_axis] = w.size
            wb = w.reshape(shape)
        return jnp.where(a > 0, a, wb * a)
    return apply_op("prelu", _f, x, weight)


def rrelu(x, lower=0.125, upper=0.3333333333333333, training=True, name=None):
    from ...framework.random import rng_key
    if training:
        import jax.random as jrandom
        key = rng_key()
        def _f(a):
            slope = jrandom.uniform(key, a.shape, a.dtype, lower, upper)
            return jnp.where(a >= 0, a, slope * a)
        return apply_op("rrelu", _f, x)
    mid = (lower + upper) / 2.0
    return apply_op("rrelu", lambda a: jnp.where(a >= 0, a, mid * a), x)


def maxout(x, groups, axis=1, name=None):
    def _f(a):
        ax = axis % a.ndim
        c = a.shape[ax]
        new_shape = a.shape[:ax] + (groups, c // groups) + a.shape[ax + 1:]
        return jnp.max(a.reshape(new_shape), axis=ax + 1)
    return apply_op("maxout", _f, x)


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from ...framework.random import rng_key
    key = rng_key()
    def _f(a):
        g = jax.random.gumbel(key, a.shape, a.dtype)
        y = jax.nn.softmax((a + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            y_hard = jnp.zeros_like(y)
            y_hard = jnp.put_along_axis(y_hard, idx, 1.0, axis=axis, inplace=False)
            # straight-through estimator
            y = y_hard + (y - jax.lax.stop_gradient(y))
        return y
    return apply_op("gumbel_softmax", _f, x)
