"""nn.initializer.lazy_init — LazyGuard (module-path parity).

Parity: reference nn/initializer/lazy_init.py — defer parameter
materialization until the first forward. Eager jax arrays are cheap to
create, so the guard is a recorded no-op scope (parameters initialize
immediately; the deferral buys nothing on TPU where init compiles into
the first jit anyway)."""
import contextlib

__all__ = ["LazyGuard"]


class LazyGuard:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False
