"""Gradient clipping. Parity: reference python/paddle/nn/clip.py
(ClipGradByGlobalNorm/Norm/Value, applied by optimizers pre-step)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor
from ..ops.dispatch import apply_op

__all__ = ["ClipGradByGlobalNorm", "ClipGradByNorm", "ClipGradByValue",
           "clip_grad_norm_", "clip_grad_value_"]


class ClipGradByGlobalNorm:
    def __init__(self, clip_norm=1.0, group_name="default_group",
                 auto_skip_clip=False):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        """Global-norm clip over fp32 UPCASTS of the raw gradients —
        fully on-device and traceable (a leftover host-fetch `float()`
        reduction here used to break the whole train step out of
        to_static AND pay a per-step relay round trip). The scale is a
        function of the gradients only: `moment_dtype`/`fused` narrow
        optimizer STORAGE after clipping, so the clip sees identical
        fp32 values whatever the accumulators store
        (tests/test_fused_optimizer.py pins this)."""
        grads = [g for _, g in params_grads if g is not None]
        if not grads:
            return params_grads
        total = jnp.sqrt(jnp.asarray(
            sum(jnp.sum(jnp.square(g._data.astype(jnp.float32))) for g in grads)))
        scale = jnp.minimum(self.clip_norm / jnp.maximum(total, 1e-6), 1.0)
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
            else:
                out.append((p, Tensor((g._data.astype(jnp.float32) * scale).astype(g.dtype))))
        return out


class ClipGradByNorm:
    def __init__(self, clip_norm=1.0):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            n = jnp.linalg.norm(g._data.astype(jnp.float32))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(n, 1e-6), 1.0)
            out.append((p, Tensor((g._data * scale.astype(g.dtype)))))
        return out


class ClipGradByValue:
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -float(max)

    def __call__(self, params_grads):
        return [(p, Tensor(jnp.clip(g._data, self.min, self.max)) if g is not None else g)
                for p, g in params_grads]


def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    grads = [p._grad_buffer for p in parameters if p._grad_buffer is not None]
    if not grads:
        return Tensor(jnp.zeros(()))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(g)) for g in grads]))
    else:
        total = jnp.sum(jnp.stack(
            [jnp.sum(jnp.abs(g.astype(jnp.float32)) ** norm_type) for g in grads])) ** (1.0 / norm_type)
    scale = jnp.minimum(max_norm / jnp.maximum(total, 1e-6), 1.0)
    for p in parameters:
        if p._grad_buffer is not None:
            p._grad_buffer = (p._grad_buffer * scale).astype(p.dtype)
    return Tensor(total)


def clip_grad_value_(parameters, clip_value):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    for p in parameters:
        if p._grad_buffer is not None:
            p._grad_buffer = jnp.clip(p._grad_buffer, -clip_value, clip_value)
