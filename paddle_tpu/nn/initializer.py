"""Parameter initializers.

Parity: reference `python/paddle/nn/initializer/` (Constant, Normal,
TruncatedNormal, Uniform, XavierNormal/Uniform, KaimingNormal/Uniform,
Assign, Orthogonal, Dirac).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..framework.random import rng_key

__all__ = [
    "Initializer", "Constant", "Normal", "TruncatedNormal", "Uniform",
    "XavierNormal", "XavierUniform", "KaimingNormal", "KaimingUniform",
    "Assign", "Orthogonal", "calculate_gain",
]


def calculate_gain(nonlinearity, param=None):
    gains = {
        "sigmoid": 1.0, "linear": 1.0, "conv1d": 1.0, "conv2d": 1.0,
        "conv3d": 1.0, "tanh": 5.0 / 3.0, "relu": math.sqrt(2.0),
        "leaky_relu": math.sqrt(2.0 / (1 + (param if param is not None else 0.01) ** 2)),
        "selu": 3.0 / 4.0,
    }
    return gains[nonlinearity]


def _fan_in_out(shape):
    shape = tuple(shape)
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    receptive = int(np.prod(shape[2:]))
    # conv weight (out_c, in_c, *k) — paddle computes fan on this layout
    fan_in = shape[1] * receptive
    fan_out = shape[0] * receptive
    return fan_in, fan_out


class Initializer:
    def __call__(self, shape, dtype):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype):
        return jnp.full(shape, self.value, dtype)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        return self.mean + self.std * jax.random.normal(rng_key(), shape, dtype)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, a=-2.0, b=2.0):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def __call__(self, shape, dtype):
        z = jax.random.truncated_normal(rng_key(), self.a, self.b, shape, dtype)
        return self.mean + self.std * z


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype):
        return jax.random.uniform(rng_key(), shape, dtype, self.low, self.high)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return std * jax.random.normal(rng_key(), shape, dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(rng_key(), shape, dtype, -limit, limit)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in, self.slope, self.nonlinearity = fan_in, negative_slope, nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.slope)
        std = gain / math.sqrt(fi)
        return std * jax.random.normal(rng_key(), shape, dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in, self.slope, self.nonlinearity = fan_in, negative_slope, nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.slope)
        limit = gain * math.sqrt(3.0 / fi)
        return jax.random.uniform(rng_key(), shape, dtype, -limit, limit)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype):
        v = self.value._data if isinstance(self.value, Tensor) else jnp.asarray(self.value)
        return v.astype(dtype).reshape(shape)


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype):
        rows = shape[0]
        cols = int(np.prod(shape[1:])) if len(shape) > 1 else 1
        flat = jax.random.normal(rng_key(), (max(rows, cols), min(rows, cols)), jnp.float32)
        q, r = jnp.linalg.qr(flat)
        q = q * jnp.sign(jnp.diag(r))
        if rows < cols:
            q = q.T
        return (self.gain * q[:rows, :cols]).reshape(shape).astype(dtype)


# default initializer used by create_parameter
def _init_tensor(shape, dtype, initializer=None, is_bias=False):
    if initializer is None:
        initializer = _global_initializer["bias" if is_bias else "weight"]
    if initializer is None:
        initializer = Constant(0.0) if is_bias else XavierUniform()
    if callable(initializer) and not isinstance(initializer, Initializer):
        # support paddle-style ParamAttr(initializer=...) or plain callables
        init = initializer
        arr = init(shape, dtype)
        arr = arr._data if isinstance(arr, Tensor) else arr
    else:
        arr = initializer(shape, dtype)
    t = Tensor(arr, stop_gradient=False)
    t._is_param = True
    return t


class Dirac(Initializer):
    """Parity: nn.initializer.Dirac — identity-preserving conv init:
    weight[i, i % in_c, center...] = 1 (groups split the identity)."""

    def __init__(self, groups=1, name=None):
        self.groups = groups

    def __call__(self, shape, dtype):
        import numpy as np
        if len(shape) < 3:
            raise ValueError("Dirac needs a conv weight (>=3 dims)")
        w = np.zeros(shape, np.float32)
        out_c, in_c = shape[0], shape[1]
        per = out_c // self.groups
        center = tuple(s // 2 for s in shape[2:])
        for i in range(out_c):
            w[(i,) + ((i % per) % in_c,) + center] = 1.0
        return jnp.asarray(w, dtype)


class Bilinear(Initializer):
    """Parity: nn.initializer.Bilinear — upsampling-kernel init for
    transposed conv weights."""

    def __call__(self, shape, dtype):
        import numpy as np
        if len(shape) < 4:
            raise ValueError("Bilinear needs a 4-D conv weight")
        kh, kw = shape[-2], shape[-1]
        fh, fw = (kh + 1) // 2, (kw + 1) // 2
        cy = fh - 1 if kh % 2 == 1 else fh - 0.5
        cx = fw - 1 if kw % 2 == 1 else fw - 0.5
        yy, xx = np.meshgrid(np.arange(kh), np.arange(kw), indexing="ij")
        filt = (1 - np.abs(yy - cy) / fh) * (1 - np.abs(xx - cx) / fw)
        w = np.zeros(shape, np.float32)
        w[range(shape[0]), list(np.arange(shape[0]) % shape[1]), :, :] = filt
        return jnp.asarray(w, dtype)


_global_initializer = {"weight": None, "bias": None}


def set_global_initializer(weight_init, bias_init=None):
    """Parity: nn.initializer.set_global_initializer — default inits for
    subsequently created parameters (None restores the built-ins)."""
    _global_initializer["weight"] = weight_init
    _global_initializer["bias"] = bias_init


__all__ += ["Dirac", "Bilinear", "set_global_initializer"]


# module-path parity: nn.initializer.lazy_init
from . import initializer_lazy as lazy_init  # noqa: E402
from .initializer_lazy import LazyGuard  # noqa: E402,F401
__all__ += ["lazy_init", "LazyGuard"]
