"""paddle_tpu.nn — parity with python/paddle/nn/."""
from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from . import quant  # noqa: F401
from . import utils  # noqa: F401
from .layer.layers import Layer  # noqa: F401
from .layer.container import *  # noqa: F401,F403
from .layer.common import *  # noqa: F401,F403
from .layer.conv import *  # noqa: F401,F403
from .layer.norm import *  # noqa: F401,F403
from .layer.pooling import *  # noqa: F401,F403
from .layer.activation import *  # noqa: F401,F403
from .layer.loss import *  # noqa: F401,F403

from .layer import container, common, conv, norm, pooling, activation, loss  # noqa: F401

# transformer/rnn imported lazily at the bottom (they use the above)
from .layer.transformer import *  # noqa: F401,F403
from .layer.rnn import *  # noqa: F401,F403
from .layer import transformer, rnn  # noqa: F401

from .clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue  # noqa: F401
from .utils_ import ParamAttr  # noqa: F401


from .layer.extra_layers import (  # noqa: E402,F401
    ParameterDict, ZeroPad1D, ZeroPad3D, HSigmoidLoss,
    AdaptiveLogSoftmaxWithLoss, FractionalMaxPool2D, FractionalMaxPool3D,
    BeamSearchDecoder, dynamic_decode, CTCLoss, RNNTLoss, MaxUnPool1D,
    MaxUnPool2D, MaxUnPool3D, FeatureAlphaDropout)
from .layer.rnn import RNNCellBase  # noqa: E402,F401
