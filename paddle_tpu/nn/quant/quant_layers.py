"""nn.quant.quant_layers — module-path parity (reference
nn/quant/quant_layers.py QuantizedLinear etc.); the live implementations
are the quantization package's QuantedLinear + fake quanters."""
from ...quantization import (  # noqa: F401
    QuantedLinear, FakeQuanterWithAbsMaxObserver)

QuantizedLinear = QuantedLinear

__all__ = ["QuantizedLinear", "QuantedLinear",
           "FakeQuanterWithAbsMaxObserver"]


from ...quantization import (  # noqa: E402
    AbsmaxObserver as _Absmax,
    AbsMaxChannelWiseWeightObserver as _ChAbsmax)

# reference quant_layers fake-quant class names over our quanter set
FakeQuantAbsMax = _Absmax
FakeQuantChannelWiseAbsMax = _ChAbsmax
FakeQuantMovingAverageAbsMax = FakeQuanterWithAbsMaxObserver


class MovingAverageAbsMaxScale(FakeQuanterWithAbsMaxObserver):
    """Parity: quant_layers.MovingAverageAbsMaxScale — tracks the scale
    without quantizing the pass-through value."""

    def forward(self, x):
        super().forward(x)       # update the running scale
        return x


class MAOutputScaleLayer:
    """Parity: quant_layers.MAOutputScaleLayer — wrap a layer and record
    its output scale."""

    def __init__(self, layer, moving_rate=0.9, name=None):
        self._layer = layer
        self._scale = MovingAverageAbsMaxScale(moving_rate=moving_rate)

    def __call__(self, *args, **kwargs):
        return self._scale(self._layer(*args, **kwargs))


FakeQuantMAOutputScaleLayer = MAOutputScaleLayer


class QuantizedConv2D:
    """Parity: quant_layers.QuantizedConv2D — conv with fake-quantized
    weights/activations."""

    def __init__(self, layer, weight_bits=8, activation_bits=8,
                 moving_rate=0.9, **kwargs):
        self._layer = layer
        self._wq = FakeQuantAbsMax()
        self._aq = FakeQuanterWithAbsMaxObserver(moving_rate=moving_rate)

    def __call__(self, x):
        from ...core.tensor import Tensor
        w = self._layer.weight
        saved = w._data
        w._data = self._wq(Tensor(saved))._data
        try:
            return self._layer(self._aq(x))
        finally:
            w._data = saved


class QuantizedConv2DTranspose(QuantizedConv2D):
    """Parity: quant_layers.QuantizedConv2DTranspose."""


__all__ += ["FakeQuantAbsMax", "FakeQuantMovingAverageAbsMax",
            "FakeQuantChannelWiseAbsMax", "QuantizedConv2D",
            "QuantizedConv2DTranspose", "MovingAverageAbsMaxScale",
            "MAOutputScaleLayer", "FakeQuantMAOutputScaleLayer"]
