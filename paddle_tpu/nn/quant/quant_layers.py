"""nn.quant.quant_layers — module-path parity (reference
nn/quant/quant_layers.py QuantizedLinear etc.); the live implementations
are the quantization package's QuantedLinear + fake quanters."""
from ...quantization import (  # noqa: F401
    QuantedLinear, FakeQuanterWithAbsMaxObserver)

QuantizedLinear = QuantedLinear

__all__ = ["QuantizedLinear", "QuantedLinear",
           "FakeQuanterWithAbsMaxObserver"]


from ...quantization import (  # noqa: E402
    AbsmaxObserver as _Absmax,
    AbsMaxChannelWiseWeightObserver as _ChAbsmax)

# reference quant_layers fake-quant class names over our quanter set
FakeQuantAbsMax = _Absmax
FakeQuantChannelWiseAbsMax = _ChAbsmax
FakeQuantMovingAverageAbsMax = FakeQuanterWithAbsMaxObserver


class MovingAverageAbsMaxScale(FakeQuanterWithAbsMaxObserver):
    """Parity: quant_layers.MovingAverageAbsMaxScale — tracks the scale
    without quantizing the pass-through value."""

    def forward(self, x):
        super().forward(x)       # update the running scale
        return x


class MAOutputScaleLayer:
    """Parity: quant_layers.MAOutputScaleLayer — wrap a layer and record
    its output scale."""

    def __init__(self, layer, moving_rate=0.9, name=None):
        self._layer = layer
        self._scale = MovingAverageAbsMaxScale(moving_rate=moving_rate)

    def __call__(self, *args, **kwargs):
        return self._scale(self._layer(*args, **kwargs))


FakeQuantMAOutputScaleLayer = MAOutputScaleLayer


class QuantizedConv2D:
    """Parity: quant_layers.QuantizedConv2D — conv with fake-quantized
    weights/activations."""

    def __init__(self, layer, weight_bits=8, activation_bits=8,
                 moving_rate=0.9, **kwargs):
        self._layer = layer
        self._wq = FakeQuantAbsMax()
        self._aq = FakeQuanterWithAbsMaxObserver(moving_rate=moving_rate)

    def __call__(self, x):
        from ...core.tensor import Tensor
        w = self._layer.weight
        saved = w._data
        w._data = self._wq(Tensor(saved))._data
        try:
            return self._layer(self._aq(x))
        finally:
            w._data = saved


class QuantizedConv2DTranspose(QuantizedConv2D):
    """Parity: quant_layers.QuantizedConv2DTranspose."""


# Parity: reference quant_layers.py:541 `QuantStub =
# MovingAverageAbsMaxScale` — records the input scale, passes through.
QuantStub = MovingAverageAbsMaxScale


def _per_channel_fake_quant(w, bits):
    """Fake-quantize a (in, out) weight per OUTPUT channel (the
    reference's _linear_quant_axis=1) with straight-through gradients.
    TP note: the reference computes channel absmax per shard and
    all-reduces it with reduce_type='max' over the mp group
    (quant_layers.py:902); here the TP weight is ONE sharded array under
    GSPMD, so the channel absmax already spans every shard and the max
    reduction is implicit in the compiled reduce."""
    import jax
    import jax.numpy as jnp
    from ...quantization import _fake_quant
    qmax = 2.0 ** (bits - 1) - 1
    absmax = jnp.max(jnp.abs(jax.lax.stop_gradient(w)), axis=0)
    scale = jnp.maximum(absmax / qmax, 1e-10)
    return _fake_quant(w, scale, qmax)


class _QuantizedParallelLinearBase:
    """Shared QAT machinery for the TP linears: moving-average absmax on
    the input activation, per-output-channel fake-quant on the weight,
    then the WRAPPED layer's own forward (its GSPMD sharding constraints
    play the reference's c_identity/c_concat/allreduce collectives)."""

    def __init__(self, layer, weight_bits=8, activation_bits=8,
                 moving_rate=0.9, weight_quantize_type="abs_max",
                 activation_quantize_type="moving_average_abs_max",
                 weight_pre_layer=None, act_pre_layer=None,
                 weight_quant_layer=None, act_quant_layer=None):
        if weight_quant_layer is not None or act_quant_layer is not None:
            raise AssertionError(
                "When quantizing a parallel Linear, weight_quant_layer "
                "and act_quant_layer should be None (reference "
                "quant_layers.py:875-880 contract)")
        self._layer = layer
        self._weight_bits = weight_bits
        self._fake_quant_input = FakeQuanterWithAbsMaxObserver(
            moving_rate=moving_rate, bit_length=activation_bits)
        self._act_preprocess = act_pre_layer() if act_pre_layer else None
        self._weight_preprocess = \
            weight_pre_layer() if weight_pre_layer else None

    # the reference exposes the wrapped layer's weight/bias directly
    @property
    def weight(self):
        return self._layer.weight

    @property
    def bias(self):
        return self._layer.bias

    def parameters(self):
        return self._layer.parameters()

    def __call__(self, x):
        if self._act_preprocess is not None:
            x = self._act_preprocess(x)
        qx = self._fake_quant_input(x)
        w = self._layer.weight
        # preprocess (if any) feeds the fake quant, and the result is
        # swapped into the LAYER's weight so its forward actually uses it
        src = w if self._weight_preprocess is None \
            else self._weight_preprocess(w)
        saved = w._data
        w._data = _per_channel_fake_quant(src._data, self._weight_bits)
        try:
            return self._layer(qx)
        finally:
            w._data = saved

    forward = __call__


class QuantizedColumnParallelLinear(_QuantizedParallelLinearBase):
    """Parity: quant_layers.py:850 QuantizedColumnParallelLinear — QAT
    over the column-parallel linear: identity-forward of the replicated
    input (GSPMD's version of _c_identity), fake-quant input + weight,
    the wrapped layer's gather_output constraint stands in for
    _c_concat."""

    def __init__(self, layer, **kwargs):
        from ...distributed.fleet.mpu import ColumnParallelLinear
        if not isinstance(layer, ColumnParallelLinear):
            raise TypeError(
                f"QuantizedColumnParallelLinear wraps a "
                f"ColumnParallelLinear, got {type(layer).__name__}")
        super().__init__(layer, **kwargs)
        self.gather_output = layer.gather_output


class QuantizedRowParallelLinear(_QuantizedParallelLinearBase):
    """Parity: quant_layers.py:953 QuantizedRowParallelLinear — QAT over
    the row-parallel linear; the wrapped forward's P() output constraint
    is the reference's mp_allreduce_sum."""

    def __init__(self, layer, **kwargs):
        from ...distributed.fleet.mpu import RowParallelLinear
        if not isinstance(layer, RowParallelLinear):
            raise TypeError(
                f"QuantizedRowParallelLinear wraps a RowParallelLinear, "
                f"got {type(layer).__name__}")
        super().__init__(layer, **kwargs)
        self.input_is_parallel = layer.input_is_parallel


class QuantizedMatmul:
    """Parity: quant_layers.py:1060 QuantizedMatmul — both operands fake
    quantized (activation quanters), then paddle.matmul."""

    def __init__(self, layer=None, weight_bits=8, activation_bits=8,
                 moving_rate=0.9, weight_quantize_type="abs_max",
                 activation_quantize_type="moving_average_abs_max",
                 weight_pre_layer=None, act_pre_layer=None,
                 weight_quant_layer=None, act_quant_layer=None):
        mk = act_quant_layer if act_quant_layer is not None else (
            lambda: FakeQuanterWithAbsMaxObserver(
                moving_rate=moving_rate, bit_length=activation_bits))
        self._fake_quant_x = mk()
        self._fake_quant_y = mk()
        self._act_preprocess_x = act_pre_layer() if act_pre_layer else None
        self._act_preprocess_y = act_pre_layer() if act_pre_layer else None

    def __call__(self, x, y, transpose_x=False, transpose_y=False,
                 name=None):
        from ...ops.linalg import matmul
        if self._act_preprocess_x is not None:
            x = self._act_preprocess_x(x)
        if self._act_preprocess_y is not None:
            y = self._act_preprocess_y(y)
        return matmul(self._fake_quant_x(x), self._fake_quant_y(y),
                      transpose_x=transpose_x, transpose_y=transpose_y)

    forward = __call__


__all__ += ["FakeQuantAbsMax", "FakeQuantMovingAverageAbsMax",
            "FakeQuantChannelWiseAbsMax", "QuantizedConv2D",
            "QuantizedConv2DTranspose", "MovingAverageAbsMaxScale",
            "MAOutputScaleLayer", "FakeQuantMAOutputScaleLayer",
            "QuantStub", "QuantizedColumnParallelLinear",
            "QuantizedRowParallelLinear", "QuantizedMatmul"]
