"""Quantized-compute functionals: weight-only int8/int4 linear.

Parity: reference `python/paddle/nn/quant/quantized_linear.py`
(weight_quantize:56, weight_dequantize:123, weight_only_linear:183,
llm_int8_linear:276) over the phi `weight_only_linear` /
`weight_quantize` CUDA kernels (`paddle/phi/kernels/
weight_only_linear_kernel.h`).

TPU-native: weights live in HBM as int8 (or int4 packed two-per-byte)
with per-output-channel fp scales; the matmul dequantizes in-kernel — a
Pallas kernel streams int8 weight blocks and converts on the VMEM side,
halving (or quartering) weight bandwidth, which is what weight-only
quantization buys on bandwidth-bound decode. Falls back to an XLA
dequant+matmul composition off-TPU or for unsupported shapes.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor
from ...ops.dispatch import apply_op

__all__ = ["weight_quantize", "weight_dequantize", "weight_only_linear",
           "llm_int8_linear"]


def weight_quantize(x, algo="weight_only_int8", arch=None, group_size=-1):
    """(in, out) weight -> (quantized weight, per-out-channel scale).

    algo: 'weight_only_int8' -> int8 rows; 'weight_only_int4' -> two
    4-bit values packed per int8 byte along the in dim.
    Parity: quantized_linear.py:56."""
    def _f(w):
        absmax = jnp.maximum(jnp.max(jnp.abs(w), axis=0), 1e-10)   # (out,)
        if algo == "weight_only_int8":
            scale = absmax / 127.0
            q = jnp.clip(jnp.round(w / scale[None, :]), -127, 127)
            return q.astype(jnp.int8), scale.astype(jnp.float32)
        if algo == "weight_only_int4":
            s4 = (absmax / 7.0).astype(jnp.float32)
            qi = jnp.clip(jnp.round(w / s4[None, :]), -7, 7).astype(jnp.int8)
            if qi.shape[0] % 2:
                raise ValueError("int4 packing needs even in-features")
            lo = qi[0::2] & 0x0F
            hi = (qi[1::2] & 0x0F) << 4
            packed = (lo | hi).astype(jnp.int8)
            return packed, s4
        raise ValueError(f"unknown algo {algo!r}")
    return apply_op("weight_quantize", _f, x)


def _unpack_int4(packed):
    """(K/2, N) int8 -> (K, N) int8 of signed 4-bit values."""
    lo = (packed << 4).astype(jnp.int8) >> 4       # sign-extend low nibble
    hi = packed >> 4                               # arithmetic shift: high
    k2, n = packed.shape
    out = jnp.zeros((k2 * 2, n), jnp.int8)
    return out.at[0::2].set(lo).at[1::2].set(hi)


def weight_dequantize(x, scale, algo="weight_only_int8", out_dtype="float32"):
    """Inverse of weight_quantize. Parity: quantized_linear.py:123."""
    from ...core.dtype import convert_dtype
    dt = jnp.dtype(convert_dtype(out_dtype) or "float32")

    def _f(q, s):
        if algo == "weight_only_int4":
            q = _unpack_int4(q)
        return (q.astype(jnp.float32) * s[None, :]).astype(dt)
    return apply_op("weight_dequantize", _f, x, scale)


# ------------------------------------------------------ Pallas int8 matmul
def _wint8_kernel(x_ref, w_ref, s_ref, o_ref, acc_ref, *, nk):
    """acc[m, n] += x[m, k] @ dequant(w[k, n]); scale applied at flush."""
    from jax.experimental import pallas as pl
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    x = x_ref[...].astype(jnp.float32)
    w = w_ref[...].astype(jnp.float32)             # int8 -> f32 in VMEM
    acc_ref[...] += jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _flush():
        o_ref[...] = (acc_ref[...] * s_ref[0][None, :]).astype(o_ref.dtype)


def _wint8_matmul_pallas(x2d, qw, scale):
    """x2d (M, K) float; qw (K, N) int8; scale (N,) -> (M, N)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    from ...jax_compat import patch_pltpu
    from ...kernels.flash_attention import _interpret_mode

    patch_pltpu()

    M, K = x2d.shape
    N = qw.shape[1]
    bm = M if M <= 256 else (256 if M % 256 == 0 else M)
    bk = K if K <= 512 else (512 if K % 512 == 0 else K)
    bn = N if N <= 512 else (512 if N % 512 == 0 else N)
    nk = K // bk
    grid = (M // bm, N // bn, nk)
    return pl.pallas_call(
        functools.partial(_wint8_kernel, nk=nk),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, k: (i, k)),
            pl.BlockSpec((bk, bn), lambda i, j, k: (k, j)),
            pl.BlockSpec((1, bn), lambda i, j, k: (np.int32(0), j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, k: (i, j)),
        out_shape=jax.ShapeDtypeStruct((M, N), x2d.dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret_mode(),
    )(x2d, qw, scale[None, :])


@jax.custom_vjp
def _wint8_mm(x2d, qw, scale):
    return _wint8_matmul_pallas(x2d, qw, scale)


def _wint8_mm_fwd(x2d, qw, scale):
    return _wint8_matmul_pallas(x2d, qw, scale), (x2d, qw, scale)


def _wint8_mm_bwd(res, g):
    # pallas_call has no AD rule; d/dx and d/dscale computed analytically
    x2d, qw, scale = res
    gf = g.astype(jnp.float32)
    wf = qw.astype(jnp.float32)
    dx = ((gf * scale[None, :]) @ wf.T).astype(x2d.dtype)
    base = x2d.astype(jnp.float32) @ wf
    dscale = jnp.sum(gf * base, axis=0).astype(scale.dtype)
    return dx, np.zeros(qw.shape, jax.dtypes.float0), dscale


_wint8_mm.defvjp(_wint8_mm_fwd, _wint8_mm_bwd)


def _wint8_supported(M, K, N):
    """Shapes whose block tiling stays VMEM-sized: every dim either fits
    one bounded block or divides the target block exactly (a degenerate
    whole-array block on a large unaligned dim would blow VMEM)."""
    if K % 8 != 0 or N % 128 != 0 or M % 8 != 0:
        return False
    if M > 256 and M % 256 != 0:
        return False
    if K > 512 and K % 512 != 0:
        return False
    if N > 512 and N % 512 != 0:
        return False
    return True


def weight_only_linear(x, weight, bias=None, weight_scale=None,
                       weight_dtype="int8", arch=None, group_size=-1):
    """x @ dequant(weight) + bias with int8/int4 HBM-resident weights.
    Parity: quantized_linear.py:183."""
    if weight_scale is None:
        raise ValueError("weight_scale is required")

    def _f(xx, qw, s, b):
        lead = xx.shape[:-1]
        K = xx.shape[-1]
        x2d = xx.reshape((-1, K))
        if weight_dtype == "int4":
            wq = _unpack_int4(qw)
        else:
            wq = qw
        M, N = x2d.shape[0], wq.shape[1]
        if weight_dtype == "int8" and _wint8_supported(M, K, N):
            out = _wint8_mm(x2d, wq, s)
        else:
            wf = wq.astype(jnp.float32) * s[None, :]
            out = (x2d.astype(jnp.float32) @ wf).astype(xx.dtype)
        if b is not None:
            out = out + b
        return out.reshape(lead + (N,))

    if bias is None:
        return apply_op("weight_only_linear",
                        lambda xx, qw, s: _f(xx, qw, s, None),
                        x, weight, weight_scale)
    return apply_op("weight_only_linear", _f, x, weight, weight_scale, bias)


def llm_int8_linear(x, weight, bias=None, weight_scale=None, threshold=6.0):
    """LLM.int8-style linear (simplified: dense int8 dequant matmul — the
    outlier split is a no-op on TPU where fp accumulate is used anyway).
    Parity: quantized_linear.py:276."""
    return weight_only_linear(x, weight, bias, weight_scale, "int8")


class Stub(object):
    """Parity: nn.quant.Stub — a placeholder layer the quantization
    config replaces with a quanter during QAT model conversion."""

    def __init__(self, observer=None):
        self._observer = observer

    def forward(self, input):
        return input

    def __call__(self, input):
        return self.forward(input)


from . import quant_layers  # noqa: E402,F401
__all__ += ["Stub", "quant_layers"]
