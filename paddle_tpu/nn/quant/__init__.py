"""Quantized-compute functionals: weight-only int8/int4 linear.

Parity: reference `python/paddle/nn/quant/quantized_linear.py`
(weight_quantize:56, weight_dequantize:123, weight_only_linear:183,
llm_int8_linear:276) over the phi `weight_only_linear` /
`weight_quantize` CUDA kernels (`paddle/phi/kernels/
weight_only_linear_kernel.h`).

TPU-native: weights live in HBM as int8 (or int4 packed two-per-byte)
with per-output-channel fp scales; the matmul dequantizes in-kernel — a
Pallas kernel streams int8 weight blocks and converts on the VMEM side,
halving (or quartering) weight bandwidth, which is what weight-only
quantization buys on bandwidth-bound decode. Falls back to an XLA
dequant+matmul composition off-TPU or for unsupported shapes.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor
from ...ops.dispatch import apply_op

__all__ = ["weight_quantize", "weight_dequantize", "weight_only_linear",
           "llm_int8_linear", "WeightOnlyLinear", "quantize_for_serving",
           "SERVING_WQ_TARGETS"]


def weight_quantize(x, algo="weight_only_int8", arch=None, group_size=-1):
    """(in, out) weight -> (quantized weight, per-out-channel scale).

    algo: 'weight_only_int8' -> int8 rows; 'weight_only_int4' -> two
    4-bit values packed per int8 byte along the in dim.
    Parity: quantized_linear.py:56."""
    def _f(w):
        absmax = jnp.maximum(jnp.max(jnp.abs(w), axis=0), 1e-10)   # (out,)
        if algo == "weight_only_int8":
            scale = absmax / 127.0
            q = jnp.clip(jnp.round(w / scale[None, :]), -127, 127)
            return q.astype(jnp.int8), scale.astype(jnp.float32)
        if algo == "weight_only_int4":
            s4 = (absmax / 7.0).astype(jnp.float32)
            qi = jnp.clip(jnp.round(w / s4[None, :]), -7, 7).astype(jnp.int8)
            if qi.shape[0] % 2:
                raise ValueError("int4 packing needs even in-features")
            lo = qi[0::2] & 0x0F
            hi = (qi[1::2] & 0x0F) << 4
            packed = (lo | hi).astype(jnp.int8)
            return packed, s4
        raise ValueError(f"unknown algo {algo!r}")
    return apply_op("weight_quantize", _f, x)


def _unpack_int4(packed):
    """(K/2, N) int8 -> (K, N) int8 of signed 4-bit values."""
    lo = (packed << 4).astype(jnp.int8) >> 4       # sign-extend low nibble
    hi = packed >> 4                               # arithmetic shift: high
    k2, n = packed.shape
    out = jnp.zeros((k2 * 2, n), jnp.int8)
    return out.at[0::2].set(lo).at[1::2].set(hi)


def weight_dequantize(x, scale, algo="weight_only_int8", out_dtype="float32"):
    """Inverse of weight_quantize. Parity: quantized_linear.py:123."""
    from ...core.dtype import convert_dtype
    dt = jnp.dtype(convert_dtype(out_dtype) or "float32")

    def _f(q, s):
        if algo == "weight_only_int4":
            q = _unpack_int4(q)
        return (q.astype(jnp.float32) * s[None, :]).astype(dt)
    return apply_op("weight_dequantize", _f, x, scale)


# ------------------------------------------------------ Pallas int8 matmul
# The kernel itself lives in kernels/quant_matmul.py (ISSUE 6): fused
# dequant-matmul with VMEM-sized blocks picked against the tpu-lint A3
# estimator, int32 index maps, and a legality-enumerable blockspec set.
# This module keeps the custom_vjp wrapper (QAT trains THROUGH the
# quantized forward) and the Tensor-level weight_only_linear API.
def _wint8_matmul_pallas(x2d, qw, scale):
    """x2d (M, K) float; qw (K, N) int8; scale (N,) -> (M, N)."""
    from ...kernels.quant_matmul import quant_matmul
    return quant_matmul(x2d, qw, scale)


@jax.custom_vjp
def _wint8_mm(x2d, qw, scale):
    return _wint8_matmul_pallas(x2d, qw, scale)


def _wint8_mm_fwd(x2d, qw, scale):
    return _wint8_matmul_pallas(x2d, qw, scale), (x2d, qw, scale)


def _wint8_mm_bwd(res, g):
    # pallas_call has no AD rule; d/dx and d/dscale computed analytically
    x2d, qw, scale = res
    gf = g.astype(jnp.float32)
    wf = qw.astype(jnp.float32)
    dx = ((gf * scale[None, :]) @ wf.T).astype(x2d.dtype)
    base = x2d.astype(jnp.float32) @ wf
    dscale = jnp.sum(gf * base, axis=0).astype(scale.dtype)
    return dx, np.zeros(qw.shape, jax.dtypes.float0), dscale


_wint8_mm.defvjp(_wint8_mm_fwd, _wint8_mm_bwd)


def _wint8_supported(M, K, N):
    """Shapes with a VMEM-legal Pallas tiling (kernels/quant_matmul's
    estimator-driven pick); everything else takes the XLA composition.
    K/N still need basic lane/sublane alignment even for whole-dim
    blocks — the weight block's trailing dims are (K, N) then."""
    from ...kernels.quant_matmul import quant_matmul_supported
    if K % 8 != 0 or N % 128 != 0:
        return False
    return quant_matmul_supported(M, K, N)


def weight_only_linear(x, weight, bias=None, weight_scale=None,
                       weight_dtype="int8", arch=None, group_size=-1):
    """x @ dequant(weight) + bias with int8/int4 HBM-resident weights.
    Parity: quantized_linear.py:183."""
    if weight_scale is None:
        raise ValueError("weight_scale is required")

    def _f(xx, qw, s, b):
        lead = xx.shape[:-1]
        K = xx.shape[-1]
        x2d = xx.reshape((-1, K))
        if weight_dtype == "int4":
            wq = _unpack_int4(qw)
        else:
            wq = qw
        M, N = x2d.shape[0], wq.shape[1]
        if weight_dtype == "int8" and _wint8_supported(M, K, N):
            out = _wint8_mm(x2d, wq, s)
        else:
            wf = wq.astype(jnp.float32) * s[None, :]
            out = (x2d.astype(jnp.float32) @ wf).astype(xx.dtype)
        if b is not None:
            out = out + b
        return out.reshape(lead + (N,))

    if bias is None:
        return apply_op("weight_only_linear",
                        lambda xx, qw, s: _f(xx, qw, s, None),
                        x, weight, weight_scale)
    return apply_op("weight_only_linear", _f, x, weight, weight_scale, bias)


def llm_int8_linear(x, weight, bias=None, weight_scale=None, threshold=6.0):
    """LLM.int8-style linear (simplified: dense int8 dequant matmul — the
    outlier split is a no-op on TPU where fp accumulate is used anyway).
    Parity: quantized_linear.py:276."""
    return weight_only_linear(x, weight, bias, weight_scale, "int8")


# --------------------------------------------- serving weight conversion
class WeightOnlyLinear:
    """Inference linear with int8/int4 HBM-resident weights: qweight +
    per-out-channel scale as PERSISTABLE BUFFERS (they must ride
    state_dict so the serving engine's functional_call programs rebind
    them), forward through `weight_only_linear` (the Pallas fused
    dequant-matmul when the tiling is legal, XLA composition
    otherwise). Built lazily as a real nn.Layer subclass (import-cycle:
    nn.Layer imports are deferred exactly like QuantedLinear's)."""

    def __new__(cls, *args, **kwargs):
        return _weight_only_linear_cls()(*args, **kwargs)


_WOL_CLS = None


def _weight_only_linear_cls():
    global _WOL_CLS
    if _WOL_CLS is not None:
        return _WOL_CLS
    from ..layer.layers import Layer

    class _WeightOnlyLinear(Layer):
        def __init__(self, weight, bias=None, algo="weight_only_int8"):
            super().__init__()
            if algo not in ("weight_only_int8", "weight_only_int4"):
                raise ValueError(f"unknown algo {algo!r}")
            self.weight_dtype = "int8" if algo.endswith("int8") else "int4"
            w = weight if isinstance(weight, Tensor) else Tensor(weight)
            self.in_features, self.out_features = (int(w.shape[0]),
                                                   int(w.shape[1]))
            qw, scale = weight_quantize(w, algo=algo)
            # TP serving (ISSUE 8): the quantized buffers inherit the
            # source weight's mesh spec — qweight keeps the (in, out)
            # layout (int4 packs along `in`, which both column- and
            # row-parallel specs survive), the per-OUT-channel scale
            # shards like the out dim
            spec = getattr(w, "_spec", None)
            if spec is not None:
                qw._spec = spec
                scale._spec = type(spec)(spec[-1])
            self.register_buffer("qweight", qw)
            self.register_buffer("weight_scale", scale)
            if bias is not None:
                self.register_buffer(
                    "bias", bias if isinstance(bias, Tensor)
                    else Tensor(bias))
            else:
                self.bias = None

        def forward(self, x):
            b = self._buffers.get("bias")
            return weight_only_linear(x, self.qweight, b,
                                      self.weight_scale, self.weight_dtype)

    _WeightOnlyLinear.__name__ = "WeightOnlyLinear"
    _WOL_CLS = _WeightOnlyLinear
    return _WOL_CLS


# Decode-regime projections: the GEMMs that are weight-bandwidth-bound
# at M = batch (MLP + LM head). Attention qkv/o are deliberately NOT on
# the default list — their weights are a small fraction of the decode
# bytes next to the KV read, and quantizing them buys accuracy risk for
# little bandwidth (SERVING.md "Quantized KV & weights").
SERVING_WQ_TARGETS = ("gate_proj", "up_proj", "down_proj", "lm_head")


def quantize_for_serving(model, algo="weight_only_int8",
                         targets=SERVING_WQ_TARGETS):
    """Replace `targets`-named linear sublayers (matched by their leaf
    attribute name, anywhere in the tree) with WeightOnlyLinear — IN
    PLACE, weights quantized once at conversion. Returns the number of
    layers converted. The serving engine's `wq=` config calls this
    before snapshotting state, so the quantized buffers (int8 qweight +
    fp scale) ride the compiled programs and the fused dequant-matmul
    serves every decode/verify/prefill launch."""
    from ..layer.layers import Layer
    converted = 0
    stack = [model]
    while stack:
        layer = stack.pop()
        for name, sub in list(layer._sub_layers.items()):
            if sub is None:
                continue
            w = getattr(sub, "weight", None)
            if (name in targets and w is not None
                    and len(getattr(w, "shape", ())) == 2
                    and not isinstance(sub, _weight_only_linear_cls())):
                bias = getattr(sub, "bias", None)
                setattr(layer, name,
                        WeightOnlyLinear(w, bias=bias, algo=algo))
                converted += 1
            elif isinstance(sub, Layer):
                stack.append(sub)
    return converted


class Stub(object):
    """Parity: nn.quant.Stub — a placeholder layer the quantization
    config replaces with a quanter during QAT model conversion."""

    def __init__(self, observer=None):
        self._observer = observer

    def forward(self, input):
        return input

    def __call__(self, input):
        return self.forward(input)


from . import quant_layers  # noqa: E402,F401
__all__ += ["Stub", "quant_layers"]
