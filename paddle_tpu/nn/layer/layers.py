"""The Layer base class (module system).

Parity: reference `paddle.nn.Layer`
(`/root/reference/python/paddle/nn/layer/layers.py:354`): parameter/buffer/
sublayer registries, forward hooks, train/eval, state_dict/set_state_dict,
apply, to(dtype), named_* traversals.

TPU-native addition: `raw_state()`/`load_raw_state()` expose the parameter
pytree as jax arrays so a whole Layer can cross a jax.jit/pjit boundary —
this is the bridge the reference needs dy2static + program translation for.
"""
from __future__ import annotations

import collections
from typing import Callable, Dict, Iterator, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...core.dtype import convert_dtype, get_default_dtype
from ...core.tensor import Tensor
from ..initializer import Constant, Initializer, XavierUniform, _init_tensor

__all__ = ["Layer"]


class HookRemoveHelper:
    _next_id = [0]

    def __init__(self, hooks: dict):
        self._hooks = hooks
        self._id = HookRemoveHelper._next_id[0]
        HookRemoveHelper._next_id[0] += 1

    def remove(self):
        self._hooks.pop(self._id, None)


class Layer:
    def __init__(self, name_scope=None, dtype=None):
        object.__setattr__(self, "_parameters", collections.OrderedDict())
        object.__setattr__(self, "_buffers", collections.OrderedDict())
        object.__setattr__(self, "_sub_layers", collections.OrderedDict())
        self._non_persistable_buffer_names = set()
        self._forward_pre_hooks = collections.OrderedDict()
        self._forward_post_hooks = collections.OrderedDict()
        self.training = True
        self._dtype = convert_dtype(dtype) or get_default_dtype()
        self._name_scope = name_scope or self.__class__.__name__.lower()

    # ------------------------------------------------------------- registry
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Tensor) and value._is_param:
            if params is None:
                raise RuntimeError("call Layer.__init__() before assigning parameters")
            params[name] = value
            for d in (layers, buffers):
                if d is not None:
                    d.pop(name, None)
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer):
            layers[name] = value
            for d in (params, buffers):
                if d is not None:
                    d.pop(name, None)
            self.__dict__.pop(name, None)
        elif isinstance(value, Tensor) and buffers is not None and name in buffers:
            buffers[name] = value
        else:
            for d in (params, layers, buffers):
                if d is not None and name in d:
                    del d[name]
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for reg in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(reg)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(f"{type(self).__name__} has no attribute {name!r}")

    def __delattr__(self, name):
        for reg in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(reg)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        return list(super().__dir__()) + list(self._parameters) + \
            list(self._buffers) + list(self._sub_layers)

    # -------------------------------------------------------- param helpers
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        """Parity: Layer.create_parameter (layers.py:780 in reference)."""
        d = convert_dtype(dtype) or self._dtype
        init = default_initializer
        if init is None and attr is not None:
            init = getattr(attr, "initializer", None)
        if init is None and attr is not None and not isinstance(attr, bool):
            init = None
        if attr is False:
            return None
        t = _init_tensor(tuple(int(s) for s in shape), d, init, is_bias=is_bias)
        lr = getattr(attr, "learning_rate", None)
        if lr is not None:
            t._lr_scale = lr
        return t

    def add_parameter(self, name, parameter):
        if parameter is not None:
            parameter._is_param = True
        self._parameters[name] = parameter
        return parameter

    def register_buffer(self, name, tensor, persistable=True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    def add_sublayer(self, name, sublayer):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    # ----------------------------------------------------------- traversal
    def named_parameters(self, prefix="", include_sublayers=True) -> Iterator[Tuple[str, Tensor]]:
        seen = set()
        for name, layer in self.named_sublayers(prefix=prefix, include_self=True):
            for pname, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield (name + ("." if name else "") + pname, p)
            if not include_sublayers:
                break

    def parameters(self, include_sublayers=True) -> List[Tensor]:
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        seen = set()
        for name, layer in self.named_sublayers(prefix=prefix, include_self=True):
            for bname, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield (name + ("." if name else "") + bname, b)
            if not include_sublayers:
                break

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def named_sublayers(self, prefix="", include_self=False, layers_set=None):
        if layers_set is None:
            layers_set = set()
        if id(self) in layers_set:
            return
        layers_set.add(id(self))
        if include_self:
            yield prefix, self
        for name, sub in self._sub_layers.items():
            if sub is None:
                continue
            sub_prefix = prefix + ("." if prefix else "") + name
            yield from sub.named_sublayers(prefix=sub_prefix, include_self=True,
                                           layers_set=layers_set)

    def sublayers(self, include_self=False):
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def children(self):
        return iter([l for l in self._sub_layers.values() if l is not None])

    def named_children(self):
        return iter([(n, l) for n, l in self._sub_layers.items() if l is not None])

    def apply(self, fn):
        for l in self.sublayers(include_self=True):
            fn(l)
        return self

    # ------------------------------------------------------------- forward
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in list(self._forward_pre_hooks.values()):
            result = hook(self, inputs)
            if result is not None:
                inputs = result if isinstance(result, tuple) else (result,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in list(self._forward_post_hooks.values()):
            result = hook(self, inputs, outputs)
            if result is not None:
                outputs = result
        return outputs

    def register_forward_pre_hook(self, hook):
        helper = HookRemoveHelper(self._forward_pre_hooks)
        self._forward_pre_hooks[helper._id] = hook
        return helper

    def register_forward_post_hook(self, hook):
        helper = HookRemoveHelper(self._forward_post_hooks)
        self._forward_post_hooks[helper._id] = hook
        return helper

    # ---------------------------------------------------------- train/eval
    def train(self):
        for l in self.sublayers(include_self=True):
            l.training = True
        return self

    def eval(self):
        for l in self.sublayers(include_self=True):
            l.training = False
        return self

    # ----------------------------------------------------------- state IO
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix="", use_hook=True) -> Dict[str, Tensor]:
        out = collections.OrderedDict() if destination is None else destination
        for name, p in self.named_parameters(prefix=structured_name_prefix):
            out[name] = p
        for name, layer in self.named_sublayers(prefix=structured_name_prefix,
                                                include_self=True):
            for bname, b in layer._buffers.items():
                if b is None or bname in layer._non_persistable_buffer_names:
                    continue
                out[name + ("." if name else "") + bname] = b
        return out

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        for name, t in own.items():
            if name in state_dict:
                src = state_dict[name]
                arr = src._data if isinstance(src, Tensor) else jnp.asarray(np.asarray(src))
                if tuple(arr.shape) != tuple(t._data.shape):
                    raise ValueError(
                        f"shape mismatch for {name}: {arr.shape} vs {t._data.shape}")
                t._data = arr.astype(t.dtype)
            else:
                missing.append(name)
        for name in state_dict:
            if name not in own:
                unexpected.append(name)
        return missing, unexpected

    load_dict = set_state_dict
    set_dict = set_state_dict

    # ------------------------------------------------------------- casting
    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            d = convert_dtype(dtype)
            self._cast_params(d)
        return self

    def astype(self, dtype):
        self._cast_params(convert_dtype(dtype))
        return self

    def _cast_params(self, d, floats_only=True):
        for _, p in list(self.named_parameters()) + list(self.named_buffers()):
            if p is None:
                continue
            if not floats_only or jnp.issubdtype(p.dtype, jnp.floating):
                p._data = p._data.astype(d)
        for l in self.sublayers(include_self=True):
            l._dtype = d
        return self

    def float(self):
        return self._cast_params(jnp.float32)

    def bfloat16(self):
        return self._cast_params(jnp.bfloat16)

    def half(self):
        return self._cast_params(jnp.float16)

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

    # ----------------------------------------------- functional-state bridge
    def raw_state(self):
        """Parameter+buffer pytree as jax arrays (for jit/pjit boundaries)."""
        return {k: v._data for k, v in self.state_dict().items()}

    def load_raw_state(self, raw):
        sd = self.state_dict()
        for k, v in raw.items():
            if k in sd:
                sd[k]._data = v

    def full_name(self):
        return self._name_scope

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = [f"{self.__class__.__name__}({extra}"]
        for name, sub in self._sub_layers.items():
            sub_repr = repr(sub).replace("\n", "\n  ")
            lines.append(f"  ({name}): {sub_repr}")
        return "\n".join(lines) + ")" if len(lines) > 1 else lines[0] + ")"
