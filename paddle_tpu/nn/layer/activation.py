"""Activation layers. Parity: reference python/paddle/nn/layer/activation.py."""
from __future__ import annotations

from .. import functional as F
from ..initializer import Constant
from .layers import Layer

__all__ = ["ReLU", "ReLU6", "GELU", "Silu", "Swish", "Sigmoid", "Softmax",
           "LogSoftmax", "Tanh", "LeakyReLU", "ELU", "SELU", "CELU",
           "Hardswish", "Hardsigmoid", "Hardtanh", "Mish", "Softplus",
           "Softshrink", "Hardshrink", "Tanhshrink", "ThresholdedReLU",
           "GLU", "PReLU", "RReLU", "Maxout", "LogSigmoid", "Softsign",
           "Softmax2D"]


def _simple(fname, **defaults):
    fn = getattr(F, fname)

    class _Act(Layer):
        def __init__(self, *args, **kwargs):
            super().__init__()
            merged = dict(defaults)
            names = list(defaults.keys())
            for i, a in enumerate(args):
                merged[names[i]] = a
            merged.update({k: v for k, v in kwargs.items() if k != "name"})
            self._kw = merged

        def forward(self, x):
            return fn(x, **self._kw)

    _Act.__name__ = fname.capitalize()
    return _Act


ReLU = _simple("relu")
ReLU6 = _simple("relu6")
GELU = _simple("gelu", approximate=False)
Silu = _simple("silu")
Swish = _simple("swish")
Sigmoid = _simple("sigmoid")
Tanh = _simple("tanh")
LogSigmoid = _simple("log_sigmoid")
Softsign = _simple("softsign")
Mish = _simple("mish")
Hardswish = _simple("hardswish")
LeakyReLU = _simple("leaky_relu", negative_slope=0.01)
ELU = _simple("elu", alpha=1.0)
SELU = _simple("selu", scale=1.0507009873554805, alpha=1.6732632423543772)
CELU = _simple("celu", alpha=1.0)
Hardsigmoid = _simple("hardsigmoid")
Hardtanh = _simple("hardtanh", min=-1.0, max=1.0)
Softplus = _simple("softplus", beta=1.0, threshold=20.0)
Softshrink = _simple("softshrink", threshold=0.5)
Hardshrink = _simple("hardshrink", threshold=0.5)
Tanhshrink = _simple("tanhshrink")
ThresholdedReLU = _simple("thresholded_relu", threshold=1.0, value=0.0)
GLU = _simple("glu", axis=-1)
Softmax = _simple("softmax", axis=-1)
LogSoftmax = _simple("log_softmax", axis=-1)


class Softmax2D(Layer):
    def forward(self, x):
        return F.softmax(x, axis=-3)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self._data_format = data_format
        self.weight = self.create_parameter(
            (num_parameters,), attr=weight_attr,
            default_initializer=Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, data_format=self._data_format)


class RReLU(Layer):
    def __init__(self, lower=1.0 / 8.0, upper=1.0 / 3.0, name=None):
        super().__init__()
        self.lower, self.upper = lower, upper

    def forward(self, x):
        return F.rrelu(x, self.lower, self.upper, training=self.training)


class Maxout(Layer):
    def __init__(self, groups, axis=1, name=None):
        super().__init__()
        self.groups, self.axis = groups, axis

    def forward(self, x):
        return F.maxout(x, self.groups, self.axis)
