"""Normalization layers. Parity: reference python/paddle/nn/layer/norm.py."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor
from .. import functional as F
from ..initializer import Constant
from .layers import Layer

__all__ = ["LayerNorm", "BatchNorm", "BatchNorm1D", "BatchNorm2D",
           "BatchNorm3D", "SyncBatchNorm", "GroupNorm", "InstanceNorm1D",
           "InstanceNorm2D", "InstanceNorm3D", "LocalResponseNorm", "RMSNorm",
           "SpectralNorm"]


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        if weight_attr is False:
            self.weight = None
            self._parameters["weight"] = None
        else:
            self.weight = self.create_parameter(
                self._normalized_shape, attr=weight_attr,
                default_initializer=Constant(1.0))
        if bias_attr is False:
            self.bias = None
            self._parameters["bias"] = None
        else:
            self.bias = self.create_parameter(
                self._normalized_shape, attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight, self.bias,
                            self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}, epsilon={self._epsilon}"


class RMSNorm(Layer):
    """TPU-native RMSNorm layer (reference exposes rms_norm as incubate API +
    fused kernel `paddle/phi/kernels/rms_norm_kernel.h`)."""

    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None, name=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            (hidden_size,), attr=weight_attr, default_initializer=Constant(1.0))

    def forward(self, x, residual=None):
        return F.rms_norm(x, self.weight, epsilon=self._epsilon, residual=residual)


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        if weight_attr is False:
            self.weight = None
            self._parameters["weight"] = None
        else:
            self.weight = self.create_parameter(
                (num_features,), attr=weight_attr, default_initializer=Constant(1.0))
        if bias_attr is False:
            self.bias = None
            self._parameters["bias"] = None
        else:
            self.bias = self.create_parameter(
                (num_features,), attr=bias_attr, is_bias=True)
        self.register_buffer("_mean", Tensor(jnp.zeros((num_features,), self._dtype)))
        self.register_buffer("_variance", Tensor(jnp.ones((num_features,), self._dtype)))

    def forward(self, x):
        return F.batch_norm(x, self._mean, self._variance, self.weight,
                            self.bias, training=self.training,
                            momentum=self._momentum, epsilon=self._epsilon,
                            data_format=self._data_format,
                            use_global_stats=self._use_global_stats)


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCL",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, "NCW" if data_format == "NCL" else data_format,
                         use_global_stats, name)


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, data_format, use_global_stats, name)


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica batch norm. On TPU meshes, batch stats are synchronized
    automatically when the batch axis is sharded under pjit/GSPMD (mean/var
    lower to psum over the data axis); eager single-process falls back to
    local stats. Parity: python/paddle/nn/layer/norm.py SyncBatchNorm."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        out = layer
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, SyncBatchNorm):
            out = SyncBatchNorm(layer._num_features, layer._momentum,
                                layer._epsilon, data_format=layer._data_format)
            if layer.weight is not None:
                out.weight._data = layer.weight._data
            if layer.bias is not None:
                out.bias._data = layer.bias._data
            out._mean._data = layer._mean._data
            out._variance._data = layer._variance._data
        for name, sub in list(layer._sub_layers.items()):
            out._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return out


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self._data_format = data_format
        if weight_attr is False:
            self.weight = None
            self._parameters["weight"] = None
        else:
            self.weight = self.create_parameter(
                (num_channels,), attr=weight_attr, default_initializer=Constant(1.0))
        if bias_attr is False:
            self.bias = None
            self._parameters["bias"] = None
        else:
            self.bias = self.create_parameter(
                (num_channels,), attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight,
                            self.bias, self._data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._epsilon = epsilon
        if weight_attr is False:
            self.weight = None
            self._parameters["weight"] = None
        else:
            self.weight = self.create_parameter(
                (num_features,), attr=weight_attr, default_initializer=Constant(1.0))
        if bias_attr is False:
            self.bias = None
            self._parameters["bias"] = None
        else:
            self.bias = self.create_parameter(
                (num_features,), attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias,
                               eps=self._epsilon)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.args = (size, alpha, beta, k, data_format)

    def forward(self, x):
        return F.local_response_norm(x, *self.args)


class SpectralNorm(Layer):
    def __init__(self, weight_shape, dim=0, power_iters=1, epsilon=1e-12,
                 dtype="float32"):
        super().__init__()
        self._dim, self._power_iters, self._epsilon = dim, power_iters, epsilon

    def forward(self, weight):
        return F.spectral_norm(weight, self._dim, self._power_iters, self._epsilon)
