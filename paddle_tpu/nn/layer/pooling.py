"""Pooling layers. Parity: reference python/paddle/nn/layer/pooling.py."""
from __future__ import annotations

from .. import functional as F
from .layers import Layer

__all__ = ["AvgPool1D", "AvgPool2D", "AvgPool3D", "MaxPool1D", "MaxPool2D",
           "MaxPool3D", "AdaptiveAvgPool1D", "AdaptiveAvgPool2D",
           "AdaptiveAvgPool3D", "AdaptiveMaxPool1D", "AdaptiveMaxPool2D",
           "AdaptiveMaxPool3D", "LPPool1D", "LPPool2D"]


class _Pool(Layer):
    def __init__(self, **kw):
        super().__init__()
        self.kw = kw


class AvgPool1D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, exclusive=True,
                 ceil_mode=False, name=None):
        super().__init__(kernel_size=kernel_size, stride=stride, padding=padding,
                         exclusive=exclusive, ceil_mode=ceil_mode)

    def forward(self, x):
        return F.avg_pool1d(x, **self.kw)


class AvgPool2D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None, data_format="NCHW", name=None):
        super().__init__(kernel_size=kernel_size, stride=stride, padding=padding,
                         ceil_mode=ceil_mode, exclusive=exclusive,
                         divisor_override=divisor_override, data_format=data_format)

    def forward(self, x):
        return F.avg_pool2d(x, **self.kw)


class AvgPool3D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None, data_format="NCDHW", name=None):
        super().__init__(kernel_size=kernel_size, stride=stride, padding=padding,
                         ceil_mode=ceil_mode, exclusive=exclusive,
                         divisor_override=divisor_override, data_format=data_format)

    def forward(self, x):
        return F.avg_pool3d(x, **self.kw)


class MaxPool1D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False,
                 ceil_mode=False, name=None):
        super().__init__(kernel_size=kernel_size, stride=stride, padding=padding,
                         return_mask=return_mask, ceil_mode=ceil_mode)

    def forward(self, x):
        return F.max_pool1d(x, **self.kw)


class MaxPool2D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False,
                 ceil_mode=False, data_format="NCHW", name=None):
        super().__init__(kernel_size=kernel_size, stride=stride, padding=padding,
                         return_mask=return_mask, ceil_mode=ceil_mode,
                         data_format=data_format)

    def forward(self, x):
        return F.max_pool2d(x, **self.kw)


class MaxPool3D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False,
                 ceil_mode=False, data_format="NCDHW", name=None):
        super().__init__(kernel_size=kernel_size, stride=stride, padding=padding,
                         return_mask=return_mask, ceil_mode=ceil_mode,
                         data_format=data_format)

    def forward(self, x):
        return F.max_pool3d(x, **self.kw)


class AdaptiveAvgPool1D(_Pool):
    def __init__(self, output_size, name=None):
        super().__init__(output_size=output_size)

    def forward(self, x):
        return F.adaptive_avg_pool1d(x, **self.kw)


class AdaptiveAvgPool2D(_Pool):
    def __init__(self, output_size, data_format="NCHW", name=None):
        super().__init__(output_size=output_size, data_format=data_format)

    def forward(self, x):
        return F.adaptive_avg_pool2d(x, **self.kw)


class AdaptiveAvgPool3D(_Pool):
    def __init__(self, output_size, data_format="NCDHW", name=None):
        super().__init__(output_size=output_size, data_format=data_format)

    def forward(self, x):
        return F.adaptive_avg_pool3d(x, **self.kw)


class AdaptiveMaxPool1D(_Pool):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__(output_size=output_size, return_mask=return_mask)

    def forward(self, x):
        return F.adaptive_max_pool1d(x, **self.kw)


class AdaptiveMaxPool2D(_Pool):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__(output_size=output_size, return_mask=return_mask)

    def forward(self, x):
        return F.adaptive_max_pool2d(x, **self.kw)


class AdaptiveMaxPool3D(_Pool):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__(output_size=output_size, return_mask=return_mask)

    def forward(self, x):
        return F.adaptive_max_pool3d(x, **self.kw)


class LPPool1D(_Pool):
    def __init__(self, norm_type, kernel_size, stride=None, padding=0,
                 ceil_mode=False, data_format="NCL", name=None):
        super().__init__(norm_type=norm_type, kernel_size=kernel_size,
                         stride=stride, padding=padding, ceil_mode=ceil_mode,
                         data_format=data_format)

    def forward(self, x):
        return F.lp_pool1d(x, **self.kw)


class LPPool2D(_Pool):
    def __init__(self, norm_type, kernel_size, stride=None, padding=0,
                 ceil_mode=False, data_format="NCHW", name=None):
        super().__init__(norm_type=norm_type, kernel_size=kernel_size,
                         stride=stride, padding=padding, ceil_mode=ceil_mode,
                         data_format=data_format)

    def forward(self, x):
        return F.lp_pool2d(x, **self.kw)
