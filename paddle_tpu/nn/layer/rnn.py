"""Recurrent layers via lax.scan (compiler-friendly sequential control flow).

Parity: reference `python/paddle/nn/layer/rnn.py` (SimpleRNN/LSTM/GRU +
cells). The reference dispatches to cuDNN fused RNN kernels; the TPU-native
formulation is a `lax.scan` over time with the gate matmuls batched so XLA
pipelines them onto the MXU.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor
from ...ops.dispatch import apply_op
from ..initializer import Uniform
from .layers import Layer

__all__ = ["SimpleRNNCell", "LSTMCell", "GRUCell", "RNN", "BiRNN",
           "SimpleRNN", "LSTM", "GRU"]


class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        b = batch_ref.shape[batch_dim_idx]
        from ...ops.creation import full
        st = self.state_shape
        if isinstance(st[0], (list, tuple)):
            return tuple(full([b] + list(s), init_value, dtype or "float32") for s in st)
        return full([b] + list(st), init_value, dtype or "float32")


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        self.activation = activation
        k = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-k, k)
        self.weight_ih = self.create_parameter((hidden_size, input_size),
                                               weight_ih_attr, default_initializer=init)
        self.weight_hh = self.create_parameter((hidden_size, hidden_size),
                                               weight_hh_attr, default_initializer=init)
        self.bias_ih = self.create_parameter((hidden_size,), bias_ih_attr,
                                             is_bias=True, default_initializer=init)
        self.bias_hh = self.create_parameter((hidden_size,), bias_hh_attr,
                                             is_bias=True, default_initializer=init)

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        act = jnp.tanh if self.activation == "tanh" else jax.nn.relu

        def _f(x, h, wih, whh, bih, bhh):
            out = act(x @ wih.T + bih + h @ whh.T + bhh)
            return out
        out = apply_op("rnn_cell", _f, inputs, states, self.weight_ih,
                       self.weight_hh, self.bias_ih, self.bias_hh)
        return out, out


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 proj_size=None, name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        k = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-k, k)
        self.weight_ih = self.create_parameter((4 * hidden_size, input_size),
                                               weight_ih_attr, default_initializer=init)
        self.weight_hh = self.create_parameter((4 * hidden_size, hidden_size),
                                               weight_hh_attr, default_initializer=init)
        self.bias_ih = self.create_parameter((4 * hidden_size,), bias_ih_attr,
                                             is_bias=True, default_initializer=init)
        self.bias_hh = self.create_parameter((4 * hidden_size,), bias_hh_attr,
                                             is_bias=True, default_initializer=init)

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        h, c = states

        def _f(x, hh, cc, wih, whh, bih, bhh):
            gates = x @ wih.T + bih + hh @ whh.T + bhh
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
            g = jnp.tanh(g)
            new_c = f * cc + i * g
            new_h = o * jnp.tanh(new_c)
            return new_h, new_c
        new_h, new_c = apply_op("lstm_cell", _f, inputs, h, c, self.weight_ih,
                                self.weight_hh, self.bias_ih, self.bias_hh)
        return new_h, (new_h, new_c)


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        k = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-k, k)
        self.weight_ih = self.create_parameter((3 * hidden_size, input_size),
                                               weight_ih_attr, default_initializer=init)
        self.weight_hh = self.create_parameter((3 * hidden_size, hidden_size),
                                               weight_hh_attr, default_initializer=init)
        self.bias_ih = self.create_parameter((3 * hidden_size,), bias_ih_attr,
                                             is_bias=True, default_initializer=init)
        self.bias_hh = self.create_parameter((3 * hidden_size,), bias_hh_attr,
                                             is_bias=True, default_initializer=init)

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)

        def _f(x, h, wih, whh, bih, bhh):
            xg = x @ wih.T + bih
            hg = h @ whh.T + bhh
            xr, xz, xn = jnp.split(xg, 3, axis=-1)
            hr, hz, hn = jnp.split(hg, 3, axis=-1)
            r = jax.nn.sigmoid(xr + hr)
            z = jax.nn.sigmoid(xz + hz)
            n = jnp.tanh(xn + r * hn)
            return (1 - z) * n + z * h
        out = apply_op("gru_cell", _f, inputs, states, self.weight_ih,
                       self.weight_hh, self.bias_ih, self.bias_hh)
        return out, out


class RNN(Layer):
    """Wraps a cell into a sequence scanner. Parity: paddle.nn.RNN."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ...ops import manipulation as M
        time_axis = 0 if self.time_major else 1
        steps = inputs.shape[time_axis]
        xs = M.unbind(inputs, time_axis)
        if self.is_reverse:
            xs = xs[::-1]
        states = initial_states
        outs = []
        for x in xs:
            out, states = self.cell(x, states)
            outs.append(out)
        if self.is_reverse:
            outs = outs[::-1]
        outputs = M.stack(outs, axis=time_axis)
        return outputs, states


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, False, time_major)
        self.rnn_bw = RNN(cell_bw, True, time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ...ops import manipulation as M
        st_fw, st_bw = (initial_states if initial_states is not None else (None, None))
        out_fw, fw_states = self.rnn_fw(inputs, st_fw)
        out_bw, bw_states = self.rnn_bw(inputs, st_bw)
        return M.concat([out_fw, out_bw], axis=-1), (fw_states, bw_states)


class _RNNBase(Layer):
    def __init__(self, mode, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        from .container import LayerList
        self.mode = mode
        self.num_layers = num_layers
        self.hidden_size = hidden_size
        self.time_major = time_major
        self.dropout = dropout
        bidirect = direction in ("bidirect", "bidirectional")
        self.num_directions = 2 if bidirect else 1
        cell_cls = {"RNN_TANH": SimpleRNNCell, "LSTM": LSTMCell,
                    "GRU": GRUCell}[mode if mode != "RNN_RELU" else "RNN_TANH"]

        def make_cell(isz):
            if mode == "RNN_RELU":
                return SimpleRNNCell(isz, hidden_size, "relu", weight_ih_attr,
                                     weight_hh_attr, bias_ih_attr, bias_hh_attr)
            return cell_cls(isz, hidden_size, weight_ih_attr, weight_hh_attr,
                            bias_ih_attr, bias_hh_attr)

        rnns = []
        for layer_i in range(num_layers):
            isz = input_size if layer_i == 0 else hidden_size * self.num_directions
            if bidirect:
                rnns.append(BiRNN(make_cell(isz), make_cell(isz), time_major))
            else:
                rnns.append(RNN(make_cell(isz), False, time_major))
        self.rnns = LayerList(rnns)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from .. import functional as F
        out = inputs
        final_states = []
        for i, rnn in enumerate(self.rnns):
            st = None
            if initial_states is not None:
                st = self._slice_states(initial_states, i)
            out, states = rnn(out, st)
            final_states.append(states)
            if self.dropout > 0.0 and i < self.num_layers - 1:
                out = F.dropout(out, self.dropout, training=self.training)
        return out, self._stack_states(final_states)

    def _slice_states(self, initial_states, layer_i):
        from ...ops import manipulation as M
        d = self.num_directions
        if self.mode == "LSTM":
            h, c = initial_states
            if d == 2:
                return ((h[layer_i * 2], c[layer_i * 2]),
                        (h[layer_i * 2 + 1], c[layer_i * 2 + 1]))
            return (h[layer_i], c[layer_i])
        h = initial_states
        if d == 2:
            return (h[layer_i * 2], h[layer_i * 2 + 1])
        return h[layer_i]

    def _stack_states(self, final_states):
        from ...ops import manipulation as M
        d = self.num_directions
        if self.mode == "LSTM":
            hs, cs = [], []
            for st in final_states:
                if d == 2:
                    (h_f, c_f), (h_b, c_b) = st
                    hs += [h_f, h_b]
                    cs += [c_f, c_b]
                else:
                    h, c = st
                    hs.append(h)
                    cs.append(c)
            return (M.stack(hs, 0), M.stack(cs, 0))
        hs = []
        for st in final_states:
            if d == 2:
                hs += [st[0], st[1]]
            else:
                hs.append(st)
        return M.stack(hs, 0)


class SimpleRNN(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", **kw):
        mode = "RNN_RELU" if activation == "relu" else "RNN_TANH"
        super().__init__(mode, input_size, hidden_size, num_layers, direction,
                         time_major, dropout, **kw)


class LSTM(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 proj_size=None, **kw):
        super().__init__("LSTM", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, **kw)


class GRU(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0, **kw):
        super().__init__("GRU", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, **kw)
