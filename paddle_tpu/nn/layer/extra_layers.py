"""nn layer tail: ParameterDict, ZeroPad1D/3D, HSigmoidLoss,
AdaptiveLogSoftmaxWithLoss, FractionalMaxPool2D/3D, BeamSearchDecoder +
dynamic_decode.

Parity: reference `python/paddle/nn/` — container.py ParameterDict,
padding ZeroPad1D/3D, loss.py HSigmoidLoss (complete-binary-tree
hierarchical sigmoid, `phi/kernels/hsigmoid_loss_kernel.h`),
AdaptiveLogSoftmaxWithLoss (cluster-partitioned vocabulary softmax),
pooling.py FractionalMaxPool2D/3D (pseudo-random pooling regions,
`phi/kernels/fractional_max_pool2d_kernel.h`), decode.py
BeamSearchDecoder/dynamic_decode.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor
from ...ops.dispatch import apply_op
from .layers import Layer

from ..functional.extra import (ctc_loss, feature_alpha_dropout,
                                max_unpool1d, max_unpool2d, max_unpool3d,
                                rnnt_loss)

__all__ = ["ParameterDict", "ZeroPad1D", "ZeroPad3D", "HSigmoidLoss",
           "AdaptiveLogSoftmaxWithLoss", "FractionalMaxPool2D",
           "FractionalMaxPool3D", "BeamSearchDecoder", "dynamic_decode",
           "CTCLoss", "RNNTLoss", "MaxUnPool1D", "MaxUnPool2D",
           "MaxUnPool3D", "FeatureAlphaDropout"]


class CTCLoss(Layer):
    """Parity: paddle.nn.CTCLoss over F.ctc_loss."""

    def __init__(self, blank=0, reduction="mean"):
        super().__init__()
        self.blank, self.reduction = blank, reduction

    def forward(self, logits, labels, input_lengths, label_lengths,
                norm_by_times=False):
        return ctc_loss(logits, labels, input_lengths, label_lengths,
                        blank=self.blank, reduction=self.reduction,
                        norm_by_times=norm_by_times)


class RNNTLoss(Layer):
    """Parity: paddle.nn.RNNTLoss over F.rnnt_loss."""

    def __init__(self, blank=0, fastemit_lambda=0.001, reduction="mean",
                 name=None):
        super().__init__()
        self.blank = blank
        self.fastemit_lambda = fastemit_lambda
        self.reduction = reduction

    def forward(self, logits, labels, logit_lengths, label_lengths):
        return rnnt_loss(logits, labels, logit_lengths, label_lengths,
                         blank=self.blank,
                         fastemit_lambda=self.fastemit_lambda,
                         reduction=self.reduction)


class _UnpoolBase(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format=None, output_size=None, name=None):
        super().__init__()
        self._cfg = (kernel_size, stride, padding, output_size)


class MaxUnPool1D(_UnpoolBase):
    def forward(self, x, indices):
        k, s, p, o = self._cfg
        return max_unpool1d(x, indices, k, s, p, output_size=o)


class MaxUnPool2D(_UnpoolBase):
    def forward(self, x, indices):
        k, s, p, o = self._cfg
        return max_unpool2d(x, indices, k, s, p, output_size=o)


class MaxUnPool3D(_UnpoolBase):
    def forward(self, x, indices):
        k, s, p, o = self._cfg
        return max_unpool3d(x, indices, k, s, p, output_size=o)


class FeatureAlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return feature_alpha_dropout(x, self.p, self.training)


class ParameterDict(Layer):
    """Keyed parameter container (parity: nn.ParameterDict)."""

    def __init__(self, parameters=None):
        super().__init__()
        if parameters:
            items = parameters.items() if hasattr(parameters, "items") \
                else parameters
            for k, v in items:
                self.add_parameter(str(k), v)

    def __getitem__(self, key):
        return self._parameters[key]

    def __setitem__(self, key, param):
        self.add_parameter(str(key), param)

    def __len__(self):
        return len(self._parameters)

    def __iter__(self):
        return iter(self._parameters)

    def keys(self):
        return self._parameters.keys()

    def values(self):
        return self._parameters.values()

    def items(self):
        return self._parameters.items()

    def update(self, parameters):
        for k, v in (parameters.items() if hasattr(parameters, "items")
                     else parameters):
            self.add_parameter(str(k), v)


from .common import _PadNd


class ZeroPad1D(_PadNd):
    """2-line subclass over F.pad, like the existing ZeroPad2D."""

    def __init__(self, padding, data_format="NCL", name=None):
        super().__init__(padding, "constant", 0.0, data_format)


class ZeroPad3D(_PadNd):
    def __init__(self, padding, data_format="NCDHW", name=None):
        super().__init__(padding, "constant", 0.0, data_format)


class HSigmoidLoss(Layer):
    """Hierarchical sigmoid over a complete binary tree (the reference's
    default, non-custom-tree mode): each class's probability is a product
    of sigmoid decisions along its path; loss = -log p(label)."""

    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False,
                 name=None):
        super().__init__()
        if is_custom:
            raise NotImplementedError("custom trees not supported")
        self.num_classes = num_classes
        self.depth = max(1, math.ceil(math.log2(max(num_classes, 2))))
        n_nodes = num_classes - 1  # internal nodes of the complete tree
        self.weight = self.create_parameter(
            (max(n_nodes, 1), feature_size), attr=weight_attr)
        self.add_parameter("weight", self.weight)
        self.bias = None if bias_attr is False else self.create_parameter(
            (max(n_nodes, 1),), attr=bias_attr, is_bias=True)
        if self.bias is not None:
            self.add_parameter("bias", self.bias)
        self._codes, self._signs, self._mask = self._build_paths(
            num_classes, self.depth)

    @staticmethod
    def _build_paths(num_classes, depth):
        """(node index, direction) paths per class: classes are leaves of
        a complete binary tree in heap layout (node i children 2i,
        2i+1)."""
        n_nodes = num_classes - 1
        codes = np.zeros((num_classes, depth), np.int32)
        signs = np.zeros((num_classes, depth), np.float32)
        mask = np.zeros((num_classes, depth), np.float32)
        for c in range(num_classes):
            node = c + num_classes  # leaves occupy [num_classes, 2N)
            path = []
            while node > 1:
                parent = node // 2
                path.append((parent - 1, 1.0 if node % 2 == 0 else -1.0))
                node = parent
            for d, (idx, sgn) in enumerate(reversed(path)):
                if d < depth and idx < max(n_nodes, 1):
                    codes[c, d] = idx
                    signs[c, d] = sgn
                    mask[c, d] = 1.0
        return jnp.asarray(codes), jnp.asarray(signs), jnp.asarray(mask)

    def forward(self, input, label):
        def _f(x, lab, w, *maybe_b):
            b = maybe_b[0] if maybe_b else None
            lab = lab.reshape(-1).astype(jnp.int32)
            nodes = self._codes[lab]                  # (B, depth)
            sgn = self._signs[lab]
            msk = self._mask[lab]
            wv = w[nodes]                             # (B, depth, F)
            logits = jnp.einsum("bdf,bf->bd", wv, x)
            if b is not None:
                logits = logits + b[nodes]
            # sign convention: +1 -> left (sigmoid), -1 -> right
            logp = jax.nn.log_sigmoid(sgn * logits) * msk
            return -(logp.sum(axis=1, keepdims=True))

        args = [input, label, self.weight]
        if self.bias is not None:
            args.append(self.bias)
        return apply_op("hsigmoid_loss", _f, *args)


class AdaptiveLogSoftmaxWithLoss(Layer):
    """Cluster-partitioned softmax (parity: nn.AdaptiveLogSoftmaxWithLoss):
    a head over [shortlist + one token per tail cluster], each tail
    cluster projected down by div_value^i and scored lazily."""

    def __init__(self, in_features, n_classes, cutoffs, div_value=4.0,
                 head_bias=False, name=None):
        super().__init__()
        cutoffs = list(cutoffs)
        if not cutoffs or cutoffs != sorted(set(cutoffs)) \
                or cutoffs[-1] > n_classes - 1:
            raise ValueError(f"bad cutoffs {cutoffs}")
        self.cutoffs = cutoffs + [n_classes]
        self.n_clusters = len(self.cutoffs) - 1
        self.head_size = self.cutoffs[0] + self.n_clusters
        self.head_weight = self.create_parameter(
            (in_features, self.head_size))
        self.add_parameter("head_weight", self.head_weight)
        self.head_bias = self.create_parameter(
            (self.head_size,), is_bias=True) if head_bias else None
        if self.head_bias is not None:
            self.add_parameter("head_bias", self.head_bias)
        self._tails = []
        for i in range(self.n_clusters):
            hsz = max(1, int(in_features // (div_value ** (i + 1))))
            osz = self.cutoffs[i + 1] - self.cutoffs[i]
            p1 = self.create_parameter((in_features, hsz))
            p2 = self.create_parameter((hsz, osz))
            self.add_parameter(f"tail_{i}_proj", p1)
            self.add_parameter(f"tail_{i}_out", p2)
            self._tails.append((p1, p2))

    def _head_logprob(self, x_arr, params):
        hw, hb = params[0], params[1]
        logits = x_arr @ hw
        if hb is not None:
            logits = logits + hb
        return jax.nn.log_softmax(logits, axis=-1)

    def forward(self, input, label):
        def _f(x, lab, *ps):
            hb = ps[1] if self.head_bias is not None else None
            tails = ps[2:] if self.head_bias is not None else ps[1:]
            head_lp = self._head_logprob(x, (ps[0], hb))
            lab = lab.reshape(-1).astype(jnp.int32)
            out = jnp.zeros(lab.shape, x.dtype)
            short = lab < self.cutoffs[0]
            gathered = jnp.take_along_axis(
                head_lp, jnp.clip(lab, 0, self.cutoffs[0] - 1)[:, None],
                axis=1)[:, 0]
            out = jnp.where(short, gathered, out)
            concrete = not isinstance(lab, jax.core.Tracer)
            for i in range(self.n_clusters):
                lo, hi = self.cutoffs[i], self.cutoffs[i + 1]
                in_c = (lab >= lo) & (lab < hi)
                if concrete and not bool(jnp.any(in_c)):
                    continue   # lazy: skip untouched clusters in eager
                p1, p2 = tails[2 * i], tails[2 * i + 1]
                tail_lp = jax.nn.log_softmax((x @ p1) @ p2, axis=-1)
                rel = jnp.clip(lab - lo, 0, hi - lo - 1)
                t = jnp.take_along_axis(tail_lp, rel[:, None], axis=1)[:, 0]
                cluster_lp = head_lp[:, self.cutoffs[0] + i]
                out = jnp.where(in_c, cluster_lp + t, out)
            return out, -jnp.mean(out)

        args = [input, label, self.head_weight]
        if self.head_bias is not None:
            args.append(self.head_bias)
        for p1, p2 in self._tails:
            args += [p1, p2]
        return apply_op("adaptive_log_softmax", _f, *args)

    def log_prob(self, input):
        def _f(x, *ps):
            hb = ps[1] if self.head_bias is not None else None
            tails = ps[2:] if self.head_bias is not None else ps[1:]
            head_lp = self._head_logprob(x, (ps[0], hb))
            parts = [head_lp[:, :self.cutoffs[0]]]
            for i in range(self.n_clusters):
                p1, p2 = tails[2 * i], tails[2 * i + 1]
                tail_lp = jax.nn.log_softmax((x @ p1) @ p2, axis=-1)
                parts.append(head_lp[:, self.cutoffs[0] + i][:, None]
                             + tail_lp)
            return jnp.concatenate(parts, axis=1)

        args = [input, self.head_weight]
        if self.head_bias is not None:
            args.append(self.head_bias)
        for p1, p2 in self._tails:
            args += [p1, p2]
        return apply_op("adaptive_log_softmax_logprob", _f, *args)

    def predict(self, input):
        lp = self.log_prob(input)
        from ...ops.search import argmax
        return argmax(lp, axis=-1)


class _FractionalMaxPoolNd(Layer):
    _nd = 2

    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        nd = self._nd
        self._out = (output_size,) * nd if isinstance(output_size, int) \
            else tuple(output_size)
        self._return_mask = return_mask
        self._k = None if kernel_size is None else (
            (kernel_size,) * nd if isinstance(kernel_size, int)
            else tuple(kernel_size))
        self._u = random_u

    def forward(self, x):
        nd = self._nd
        outs = self._out
        ksz = self._k
        want_mask = self._return_mask

        def _f(a):
            spatial = a.shape[-nd:]
            from ...framework.random import rng_key
            # pseudo-random region boundaries (fractional pooling,
            # Graham 2014): alpha = in/out, row i starts at
            # ceil(alpha*(i+u)) - ceil(alpha*u); disjoint regions end at
            # the next start, overlapping mode uses kernel_size windows
            if self._u is not None:
                us = [float(self._u)] * nd
            else:
                key = rng_key()
                try:
                    us = [float(v) for v in np.asarray(
                        jax.random.uniform(key, (nd,), minval=0.0,
                                           maxval=1.0))]
                except jax.errors.TracerArrayConversionError:
                    raise ValueError(
                        "FractionalMaxPool under jit/to_static needs an "
                        "explicit random_u (region boundaries are host-"
                        "computed)") from None
            bounds_per_dim = []
            for d, (size, out, u) in enumerate(zip(spatial, outs, us)):
                alpha = size / out
                starts = [int(np.ceil(alpha * (i + u))) - int(
                    np.ceil(alpha * u)) for i in range(out + 1)]
                starts[-1] = size
                spans = []
                for i in range(out):
                    s0 = min(starts[i], size - 1)
                    if ksz is not None:
                        e0 = min(s0 + ksz[d], size)
                    else:
                        e0 = max(starts[i + 1], s0 + 1)
                    spans.append((s0, min(max(e0, s0 + 1), size)))
                bounds_per_dim.append(spans)

            def region(idx):
                sl = [slice(None)] * (a.ndim - nd)
                off = []
                for d, i in enumerate(idx):
                    s0, e0 = bounds_per_dim[d][i]
                    sl.append(slice(s0, e0))
                    off.append(s0)
                reg = a[tuple(sl)]
                red_axes = tuple(range(a.ndim - nd, a.ndim))
                mx = reg.max(axis=red_axes)
                if not want_mask:
                    return mx, None
                flat = reg.reshape(reg.shape[:a.ndim - nd] + (-1,))
                am = jnp.argmax(flat, axis=-1)
                # unravel within the region, shift by offsets, linearize
                # into the full spatial frame (paddle mask convention)
                rshape = reg.shape[a.ndim - nd:]
                lin = jnp.zeros_like(am)
                rem = am
                for d in range(nd):
                    stride = int(np.prod(rshape[d + 1:])) or 1
                    coord = rem // stride + off[d]
                    rem = rem % stride
                    lin = lin * spatial[d] + coord
                return mx, lin

            import itertools
            cells = [region(idx) for idx in
                     itertools.product(*[range(o) for o in outs])]
            out_arr = jnp.stack([c[0] for c in cells], axis=-1)
            out_arr = out_arr.reshape(a.shape[:-nd] + outs)
            if want_mask:
                mask = jnp.stack([c[1] for c in cells], axis=-1)
                mask = mask.reshape(a.shape[:-nd] + outs)
                return out_arr, mask
            return out_arr

        return apply_op("fractional_max_pool", _f, x)


class FractionalMaxPool2D(_FractionalMaxPoolNd):
    _nd = 2


class FractionalMaxPool3D(_FractionalMaxPoolNd):
    _nd = 3


class BeamSearchDecoder:
    """Beam search over an RNN cell (parity: nn/decode.py
    BeamSearchDecoder — the eager seq2seq decoding API)."""

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    def _logits(self, tok, states):
        emb = self.embedding_fn(tok) if self.embedding_fn else tok
        out, new_states = self.cell(emb, states)
        if self.output_fn is not None:
            out = self.output_fn(out)
        return out, new_states


def dynamic_decode(decoder, inits=None, max_step_num=32, batch_size=1,
                   **kwargs):
    """Run beam search until every beam emits end_token or max_step_num.

    Returns (token ids (B, beam, T), final scores (B, beam)) for
    batch_size independent decodes (eager host loop — parity:
    nn/decode.py dynamic_decode; the compiled serving path is
    models/generation.jit_generate)."""
    import numpy as np

    beam = decoder.beam_size
    all_ids, all_scores = [], []
    for _b in range(batch_size):
        # inits is the cell's initial state, passed verbatim (tuple states
        # like LSTM (h, c) included); per-batch variation belongs in the
        # cell's own state handling
        states = inits
        first = Tensor(jnp.asarray([[decoder.start_token]], jnp.int64))
        logits, states = decoder._logits(first, states)
        lp = jax.nn.log_softmax(
            logits._data[0, -1] if logits._data.ndim == 3
            else logits._data[0], axis=-1)
        top_lp, top_id = jax.lax.top_k(lp, beam)
        seqs = [[int(t)] for t in np.asarray(top_id)]
        scores = np.asarray(top_lp, np.float64).copy()
        beam_states = [states] * beam
        done = [s[-1] == decoder.end_token for s in seqs]
        for _ in range(max_step_num - 1):
            if all(done):
                break
            cand = []
            for b in range(beam):
                if done[b]:
                    cand.append((scores[b], b, decoder.end_token,
                                 beam_states[b]))
                    continue
                tok = Tensor(jnp.asarray([[seqs[b][-1]]], jnp.int64))
                logits, st = decoder._logits(tok, beam_states[b])
                lp = jax.nn.log_softmax(
                    logits._data[0, -1] if logits._data.ndim == 3
                    else logits._data[0], axis=-1)
                t_lp, t_id = jax.lax.top_k(lp, beam)
                for l, i in zip(np.asarray(t_lp), np.asarray(t_id)):
                    cand.append((scores[b] + float(l), b, int(i), st))
            cand.sort(key=lambda c: -c[0])
            new_seqs, new_scores, new_states, new_done = [], [], [], []
            for sc, b, tok, st in cand[:beam]:
                new_seqs.append(seqs[b] + ([tok] if not done[b] else []))
                new_scores.append(sc)
                new_states.append(st)
                new_done.append(done[b] or tok == decoder.end_token)
            seqs, beam_states, done = new_seqs, new_states, new_done
            scores = np.asarray(new_scores)
        T = max(len(s) for s in seqs)
        ids = np.full((beam, T), decoder.end_token, np.int64)
        for b, s in enumerate(seqs):
            ids[b, :len(s)] = s
        all_ids.append(ids)
        all_scores.append(scores)
    T = max(a.shape[1] for a in all_ids)
    out = np.full((batch_size, beam, T), decoder.end_token, np.int64)
    for i, a in enumerate(all_ids):
        out[i, :, :a.shape[1]] = a
    return Tensor(jnp.asarray(out)), Tensor(jnp.asarray(
        np.stack(all_scores)))
