"""paddle.nn.utils — gradient clipping, weight/spectral norm, param vecs.

Parity: reference `python/paddle/nn/utils/` — clip_grad_norm_ /
clip_grad_value_ (clip_grad.py), weight_norm / remove_weight_norm
(weight_norm_hook.py: reparameterize weight = g * v/||v||), spectral_norm
(spectral_norm_hook.py: power-iteration largest singular value),
parameters_to_vector / vector_to_parameters (transform_parameters.py).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor

__all__ = ["clip_grad_norm_", "clip_grad_value_", "parameters_to_vector",
           "vector_to_parameters", "weight_norm", "remove_weight_norm",
           "spectral_norm"]


def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    """Scale grads in place so the global norm <= max_norm; returns the
    pre-clip total norm (parity: clip_grad.py clip_grad_norm_)."""
    params = [parameters] if isinstance(parameters, Tensor) else \
        [p for p in parameters]
    grads = [p._grad_buffer for p in params if p._grad_buffer is not None]
    if not grads:
        return Tensor(jnp.zeros(()))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(g)) for g in grads]))
    else:
        total = jnp.sum(jnp.stack(
            [jnp.sum(jnp.abs(g.astype(jnp.float32)) ** norm_type)
             for g in grads])) ** (1.0 / norm_type)
    if error_if_nonfinite and not bool(jnp.isfinite(total)):
        raise RuntimeError("non-finite total gradient norm")
    scale = jnp.minimum(max_norm / (total + 1e-6), 1.0)
    for p in params:
        if p._grad_buffer is not None:
            p._grad_buffer = (p._grad_buffer.astype(jnp.float32)
                              * scale).astype(p._grad_buffer.dtype)
    return Tensor(total)


def clip_grad_value_(parameters, clip_value):
    params = [parameters] if isinstance(parameters, Tensor) else \
        [p for p in parameters]
    v = float(clip_value)
    for p in params:
        if p._grad_buffer is not None:
            p._grad_buffer = jnp.clip(p._grad_buffer, -v, v)


def parameters_to_vector(parameters, name=None):
    arrs = [p._data.reshape(-1) for p in parameters]
    return Tensor(jnp.concatenate(arrs))


def vector_to_parameters(vec, parameters, name=None):
    data = vec._data if isinstance(vec, Tensor) else jnp.asarray(vec)
    off = 0
    for p in parameters:
        n = int(np.prod(p.shape))
        p._data = data[off:off + n].reshape(p._data.shape).astype(p.dtype)
        off += n


def _norm_except(v, dim):
    axes = tuple(i for i in range(v.ndim) if i != dim)
    return jnp.sqrt(jnp.sum(jnp.square(v), axis=axes, keepdims=True))


def weight_norm(layer, name="weight", dim=0):
    """Reparameterize `name` as g * v/||v|| recomputed every forward
    (parity: weight_norm_hook.py). Registers `{name}_g` / `{name}_v`."""
    from ...ops.dispatch import apply_op

    w = getattr(layer, name)
    dim = dim if dim is not None else 0
    v0 = w._data
    g0 = _norm_except(v0, dim)
    layer.add_parameter(name + "_v", Tensor(v0, stop_gradient=False))
    layer.add_parameter(name + "_g", Tensor(g0, stop_gradient=False))

    def recompute(l, inputs):
        gv = l._parameters[name + "_g"]
        vv = l._parameters[name + "_v"]
        w_new = apply_op(
            "weight_norm",
            lambda g, v: g * v / jnp.maximum(_norm_except(v, dim), 1e-12),
            gv, vv)
        cur = l._parameters.get(name)
        if cur is not None:
            cur._data = w_new._data
            cur._grad_node = w_new._grad_node
            cur._grad_out_idx = w_new._grad_out_idx
            cur.stop_gradient = w_new.stop_gradient
        return None

    handle = layer.register_forward_pre_hook(recompute)
    layer._weight_norm_handle = handle
    layer._weight_norm_name = name
    return layer


def remove_weight_norm(layer, name="weight"):
    """Bake the current g*v/||v|| back into `name` and drop the hooks."""
    gv = layer._parameters.pop(name + "_g")
    vv = layer._parameters.pop(name + "_v")
    dim_norm = _norm_except(vv._data, 0)
    w = gv._data * vv._data / jnp.maximum(dim_norm, 1e-12)
    layer._parameters[name] = Tensor(w, stop_gradient=False)
    handle = getattr(layer, "_weight_norm_handle", None)
    if handle is not None:
        handle.remove()
    return layer


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12,
                  dim=0):
    """Divide `name` by its largest singular value, estimated by power
    iteration each forward (parity: spectral_norm_hook.py)."""
    from ...ops.dispatch import apply_op

    w = getattr(layer, name)
    w2d = np.asarray(w._data).reshape(w.shape[dim], -1) if dim == 0 else \
        np.moveaxis(np.asarray(w._data), dim, 0).reshape(w.shape[dim], -1)
    rng = np.random.RandomState(0)
    u = rng.randn(w2d.shape[0]).astype(np.float32)
    layer.register_buffer(name + "_u",
                          Tensor(jnp.asarray(u / np.linalg.norm(u))),
                          persistable=False)
    layer.add_parameter(name + "_orig", Tensor(w._data, stop_gradient=False))

    def recompute(l, inputs):
        orig = l._parameters[name + "_orig"]
        u_t = l._buffers[name + "_u"]

        def _sn(wa, ua):
            mat = jnp.moveaxis(wa, dim, 0).reshape(wa.shape[dim], -1)
            u_ = ua
            for _ in range(n_power_iterations):
                v_ = mat.T @ u_
                v_ = v_ / jnp.maximum(jnp.linalg.norm(v_), eps)
                u_ = mat @ v_
                u_ = u_ / jnp.maximum(jnp.linalg.norm(u_), eps)
            sigma = u_ @ (mat @ v_)
            return wa / jnp.maximum(sigma, eps), jax.lax.stop_gradient(u_)

        w_new = apply_op("spectral_norm",
                         lambda wa: _sn(wa, u_t._data)[0], orig)
        u_t._data = _sn(jax.lax.stop_gradient(orig._data), u_t._data)[1]
        cur = l._parameters.get(name)
        if cur is not None:
            cur._data = w_new._data
            cur._grad_node = w_new._grad_node
            cur._grad_out_idx = w_new._grad_out_idx
            cur.stop_gradient = w_new.stop_gradient
        return None

    layer.register_forward_pre_hook(recompute)
    return layer
