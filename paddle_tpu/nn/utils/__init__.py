"""paddle.nn.utils — gradient clipping, weight/spectral norm, param vecs.

Parity: reference `python/paddle/nn/utils/` — clip_grad_norm_ /
clip_grad_value_ (clip_grad.py), weight_norm / remove_weight_norm
(weight_norm_hook.py: reparameterize weight = g * v/||v||), spectral_norm
(spectral_norm_hook.py: power-iteration largest singular value),
parameters_to_vector / vector_to_parameters (transform_parameters.py).

Like the reference hooks, the reparameterized `weight` is REMOVED from the
parameter list (it becomes a non-persistable buffer recomputed by a
forward pre-hook), so optimizers and state_dicts see only weight_g /
weight_v (resp. weight_orig).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor
from ...ops.dispatch import apply_op

__all__ = ["clip_grad_norm_", "clip_grad_value_", "parameters_to_vector",
           "vector_to_parameters", "weight_norm", "remove_weight_norm",
           "spectral_norm"]


def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    """Scale grads in place so the global norm <= max_norm; returns the
    pre-clip total norm (parity: clip_grad.py clip_grad_norm_)."""
    params = [parameters] if isinstance(parameters, Tensor) else \
        [p for p in parameters]
    grads = [p._grad_buffer for p in params if p._grad_buffer is not None]
    if not grads:
        return Tensor(jnp.zeros(()))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(g)) for g in grads]))
    else:
        total = jnp.sum(jnp.stack(
            [jnp.sum(jnp.abs(g.astype(jnp.float32)) ** norm_type)
             for g in grads])) ** (1.0 / norm_type)
    if error_if_nonfinite and not bool(jnp.isfinite(total)):
        raise RuntimeError("non-finite total gradient norm")
    scale = jnp.minimum(max_norm / (total + 1e-6), 1.0)
    for p in params:
        if p._grad_buffer is not None:
            p._grad_buffer = (p._grad_buffer.astype(jnp.float32)
                              * scale).astype(p._grad_buffer.dtype)
    return Tensor(total)


def clip_grad_value_(parameters, clip_value):
    params = [parameters] if isinstance(parameters, Tensor) else \
        [p for p in parameters]
    v = float(clip_value)
    for p in params:
        if p._grad_buffer is not None:
            p._grad_buffer = jnp.clip(p._grad_buffer, -v, v)


def parameters_to_vector(parameters, name=None):
    """Concatenate flattened params — on the tape, so gradients flow back
    to the source parameters."""
    params = list(parameters)
    return apply_op(
        "parameters_to_vector",
        lambda xs: jnp.concatenate([x.reshape(-1) for x in xs]), params)


def vector_to_parameters(vec, parameters, name=None):
    data = vec._data if isinstance(vec, Tensor) else jnp.asarray(vec)
    off = 0
    for p in parameters:
        n = int(np.prod(p.shape))
        p._data = data[off:off + n].reshape(p._data.shape).astype(p.dtype)
        off += n


def _norm_except(v, dim):
    if dim is None:
        return jnp.sqrt(jnp.sum(jnp.square(v)))  # scalar g (whole tensor)
    axes = tuple(i for i in range(v.ndim) if i != dim)
    return jnp.sqrt(jnp.sum(jnp.square(v), axis=axes, keepdims=True))


def _demote_to_buffer(layer, name, value):
    """Drop `name` from the parameter list and keep it as a recomputed
    non-persistable buffer (the reference hooks delete the attribute)."""
    layer._parameters.pop(name, None)
    layer.register_buffer(name, value, persistable=False)
    return layer._buffers[name]


def weight_norm(layer, name="weight", dim=0):
    """Reparameterize `name` as g * v/||v|| recomputed every forward
    (parity: weight_norm_hook.py). Registers `{name}_g` / `{name}_v` and
    removes `name` from the parameter list. dim=None yields one scalar g
    over the whole tensor (reference semantics)."""
    w = getattr(layer, name)
    v0 = w._data
    g0 = _norm_except(v0, dim)
    layer.add_parameter(name + "_v", Tensor(v0, stop_gradient=False))
    layer.add_parameter(name + "_g", Tensor(g0, stop_gradient=False))
    buf = _demote_to_buffer(layer, name, Tensor(v0))

    def recompute(l, inputs):
        gv = l._parameters[name + "_g"]
        vv = l._parameters[name + "_v"]
        w_new = apply_op(
            "weight_norm",
            lambda g, v: g * v / jnp.maximum(_norm_except(v, dim), 1e-12),
            gv, vv)
        cur = l._buffers[name]
        cur._data = w_new._data
        cur._grad_node = w_new._grad_node
        cur._grad_out_idx = w_new._grad_out_idx
        cur.stop_gradient = w_new.stop_gradient
        return None

    handle = layer.register_forward_pre_hook(recompute)
    layer._weight_norm_handle = handle
    layer._weight_norm_dim = dim
    return layer


def remove_weight_norm(layer, name="weight"):
    """Bake the current g*v/||v|| back into `name` (as a parameter again)
    and drop the hook. Uses the dim weight_norm was created with."""
    dim = getattr(layer, "_weight_norm_dim", 0)
    gv = layer._parameters.pop(name + "_g")
    vv = layer._parameters.pop(name + "_v")
    w = gv._data * vv._data / jnp.maximum(_norm_except(vv._data, dim), 1e-12)
    layer._buffers.pop(name, None)
    layer.add_parameter(name, Tensor(w, stop_gradient=False))
    handle = getattr(layer, "_weight_norm_handle", None)
    if handle is not None:
        handle.remove()
    return layer


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12,
                  dim=0):
    """Divide `name` by its largest singular value, estimated by power
    iteration each forward (parity: spectral_norm_hook.py). `name` leaves
    the parameter list; `{name}_orig` is the trainable parameter."""
    w = getattr(layer, name)
    rows = w.shape[dim]
    rng = np.random.RandomState(0)
    u0 = rng.randn(rows).astype(np.float32)
    layer.register_buffer(name + "_u",
                          Tensor(jnp.asarray(u0 / np.linalg.norm(u0))),
                          persistable=False)
    layer.add_parameter(name + "_orig", Tensor(w._data, stop_gradient=False))
    _demote_to_buffer(layer, name, Tensor(w._data))
    iters = max(int(n_power_iterations), 1)  # 0 iterations: still one
    # matvec pair so v is defined (the buffers carry u across forwards)

    def recompute(l, inputs):
        orig = l._parameters[name + "_orig"]
        u_t = l._buffers[name + "_u"]
        # ONE power-iteration evaluation per forward: update u eagerly
        # (stop-gradient), then the taped op only normalizes by sigma
        mat = jnp.moveaxis(jax.lax.stop_gradient(orig._data),
                           dim, 0).reshape(rows, -1)
        u_ = u_t._data
        for _ in range(iters):
            v_ = mat.T @ u_
            v_ = v_ / jnp.maximum(jnp.linalg.norm(v_), eps)
            u_ = mat @ v_
            u_ = u_ / jnp.maximum(jnp.linalg.norm(u_), eps)
        u_t._data = u_

        def _normalize(wa):
            m = jnp.moveaxis(wa, dim, 0).reshape(rows, -1)
            sigma = u_ @ (m @ v_)
            return wa / jnp.maximum(sigma, eps)

        w_new = apply_op("spectral_norm", _normalize, orig)
        cur = l._buffers[name]
        cur._data = w_new._data
        cur._grad_node = w_new._grad_node
        cur._grad_out_idx = w_new._grad_out_idx
        cur.stop_gradient = w_new.stop_gradient
        return None

    layer.register_forward_pre_hook(recompute)
    return layer
