"""Fused weight-only int8 dequant-matmul (Pallas TPU) — the decode-path
GEMM (ISSUE 6).

Capability parity: the reference's `weight_only_linear` phi kernel
(`paddle/phi/kernels/weight_only_linear_kernel.h`, CUTLASS
mixed-dtype GEMM underneath); rebuilt as a native Pallas kernel that
streams int8 weight blocks into VMEM, converts to fp32 THERE, and
applies the per-output-channel scale once at the accumulator flush —
so the weight's HBM traffic is 1 byte/element instead of 2 (bf16),
which is the entire win in the decode regime where M is tiny and the
GEMM is weight-bandwidth-bound (bench_ops `weight_only_matmul` carries
the measured int8-vs-bf16 decision sweep; the serving engine's
`wq="int8"` config routes the LM head + MLP projections here).

Block discipline (the round-4 on-chip lessons, all statically checked
by tpu-lint):
  * block picks are sized against the A3 VMEM estimator
    (`analysis/vmem.py::estimate_vmem_bytes`) with the TRUE element
    widths — int8 weight blocks, fp32 x/scale blocks — instead of a
    hardcoded table (`pick_quant_blocks`; the rms block_rows=256 OOM
    is the cautionary tale);
  * index maps use pinned int32 (`_I0`), never bare literals (the
    package enables x64 — bare ints trace as i64 and fail Mosaic
    legalization on chip);
  * int8's (32, 128) minimum tile binds strict sub-blocks, so the K
    block is a multiple of 32 unless it spans the whole K dim (the
    whole-dim escape every Mosaic tiling rule grants);
  * anything the tiling cannot express falls back to the XLA
    dequant+matmul composition — same numerics, no Pallas.

`weight_only_linear` (nn/quant) routes its int8 fast path here; this
module keeps the raw-array kernel so the serving engine, bench_ops and
chip_parity can hit it without Tensor plumbing.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..analysis.vmem import estimate_vmem_bytes, VMEM_BUDGET_BYTES
from ..jax_compat import patch_pltpu
from .flash_attention import _interpret_mode

patch_pltpu()

__all__ = ["quant_matmul", "quant_matmul_supported", "pick_quant_blocks",
           "quant_matmul_blockspecs", "dequant_matmul_xla"]

_I0 = np.int32(0)

# Search ceilings: one (bm, bk) x block + (bk, bn) int8 block + (bm, bn)
# fp32 accumulator must fit scoped VMEM with double-buffered DMA; the
# estimator does the exact accounting below, these just bound the
# divisor search.
_BM_MAX = 256
_BK_MAX = 1024
_BN_MAX = 1024


def _blocks(bm, bk, bn, x_dtype):
    """(in_blocks, out_blocks, scratch) with TRUE dtypes for the A3
    estimator — int8 weight block, fp32 scale row, x in its own dtype,
    fp32 accumulator scratch."""
    xd = str(jnp.dtype(x_dtype))
    in_blocks = [((bm, bk), xd),           # x tile
                 ((bk, bn), "int8"),       # quantized weight tile
                 ((1, bn), "float32")]     # per-out-channel scales
    out_blocks = [((bm, bn), xd)]
    scratch = [((bm, bn), "float32")]      # accumulator
    return in_blocks, out_blocks, scratch


def _fits(bm, bk, bn, x_dtype):
    ib, ob, sc = _blocks(bm, bk, bn, x_dtype)
    # fp32_copies=2 models the int8->fp32 weight upcast + the fp32 x
    # copy the MXU path materializes per block (same accounting the
    # rms kernel's chip OOM validated)
    return estimate_vmem_bytes(ib, ob, sc) <= VMEM_BUDGET_BYTES


def _divisor_block(dim, cap, step):
    """Largest b <= cap with dim % b == 0 and b % step == 0; None when
    no such tiling exists (the whole-dim case is handled by callers)."""
    b = (min(dim, cap) // step) * step
    while b >= step:
        if dim % b == 0:
            return b
        b -= step
    return None


def pick_quant_blocks(M, K, N, x_dtype=jnp.float32):
    """VMEM-guarded (bm, bk, bn) for the dequant-matmul grid, or None
    when no legal tiling fits (callers take the XLA fallback).

    Discipline mirrors fused_norm.pick_block_rows: start from the
    bandwidth-friendly targets, shrink (halving via the divisor search)
    until the A3 estimate fits the scoped-VMEM budget. Legality per
    dim: whole-dim blocks are always legal; strict sub-blocks need
    bm%8==0 (sublanes), bn%128==0 (lanes), and bk%128==0 — bk is the
    LANE dim of the x block and the sublane dim of the int8 weight
    block at once, so it must satisfy both (128 covers int8's 32-row
    sublane tile)."""
    bm = M if M <= _BM_MAX else _divisor_block(M, _BM_MAX, 8)
    bk = K if K <= _BK_MAX else _divisor_block(K, _BK_MAX, 128)
    bn = N if N <= _BN_MAX else _divisor_block(N, _BN_MAX, 128)
    if bm is None or bk is None or bn is None:
        return None
    # strict sub-blocks must respect the dtype tiles even when the dim
    # itself is small but not tileable (e.g. K=48 with bk=48 is the
    # whole dim -> fine; K=1040 with bk=520 is not a 32-multiple -> the
    # divisor search above already guarantees it is)
    while not _fits(bm, bk, bn, x_dtype):
        # shrink K first (the weight-streaming dim), then N, then M,
        # staying on tile-aligned divisors throughout; a dim that has
        # no smaller legal divisor simply can't shrink further
        for dim, cur, floor, step in (("k", bk, 128, 128),
                                      ("n", bn, 128, 128),
                                      ("m", bm, 8, 8)):
            if cur <= floor:
                continue
            full = {"k": K, "n": N, "m": M}[dim]
            cand = _divisor_block(full, cur // 2, step)
            if cand is None:
                continue
            if dim == "k":
                bk = cand
            elif dim == "n":
                bn = cand
            else:
                bm = cand
            break
        else:
            return None            # nothing left to shrink: no legal pick
    return bm, bk, bn


def quant_matmul_supported(M, K, N, x_dtype=jnp.float32):
    """True when the Pallas path has a legal VMEM-sized tiling."""
    return pick_quant_blocks(M, K, N, x_dtype) is not None


def quant_matmul_blockspecs(M, K, N, x_dtype=jnp.float32):
    """The exact (block_shape, array_shape) pairs the pallas_call below
    constructs, enumerable for the static legality test (same contract
    as paged_attention.paged_blockspecs). None when unsupported."""
    picked = pick_quant_blocks(M, K, N, x_dtype)
    if picked is None:
        return None
    bm, bk, bn = picked
    return [((bm, bk), (M, K)),        # x
            ((bk, bn), (K, N)),        # int8 weight
            ((1, bn), (1, N)),         # scales
            ((bm, bn), (M, N))]        # out


def _kernel(x_ref, w_ref, s_ref, o_ref, acc_ref, *, nk):
    """acc[m, n] += x[m, k] @ f32(w_int8[k, n]); the per-out-channel
    scale multiplies ONCE at the flush — mathematically identical to
    scaling the dequantized weight (scales are per column), one fewer
    VMEM-wide multiply per K step."""
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    x = x_ref[...].astype(jnp.float32)
    w = w_ref[...].astype(jnp.float32)             # int8 -> f32 in VMEM
    acc_ref[...] += jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _flush():
        o_ref[...] = (acc_ref[...] * s_ref[0][None, :]).astype(o_ref.dtype)


def quant_matmul(x2d, qw, scale, blocks=None):
    """x2d (M, K) float @ dequant(qw (K, N) int8, scale (N,)) -> (M, N)
    in x2d's dtype, via the fused Pallas kernel. Callers must check
    `quant_matmul_supported` first (or pass pre-picked `blocks`);
    unsupported shapes raise — use `dequant_matmul_xla` for the
    fallback composition."""
    M, K = x2d.shape
    N = qw.shape[1]
    if blocks is None:
        blocks = pick_quant_blocks(M, K, N, x2d.dtype)
    if blocks is None:
        raise ValueError(
            f"no VMEM-legal tiling for ({M}, {K}) x ({K}, {N}) — route "
            "through dequant_matmul_xla")
    bm, bk, bn = blocks
    nk = K // bk
    grid = (M // bm, N // bn, nk)
    return pl.pallas_call(
        functools.partial(_kernel, nk=nk),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, k: (i, k)),
            pl.BlockSpec((bk, bn), lambda i, j, k: (k, j)),
            pl.BlockSpec((1, bn), lambda i, j, k: (_I0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, k: (i, j)),
        out_shape=jax.ShapeDtypeStruct((M, N), x2d.dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret_mode(),
        # tpu-lint-hint: vmem-dtypes=float32,int8,float32
    )(x2d, qw, scale[None, :].astype(jnp.float32))


def dequant_matmul_xla(x2d, qw, scale):
    """XLA fallback: materialize the fp32 weight and matmul — same
    numerics as the kernel (fp32 accumulate, scale per out channel),
    none of the bandwidth win. Used off-TPU-tiling shapes and as the
    parity reference in tests/chip_parity."""
    wf = qw.astype(jnp.float32) * scale[None, :].astype(jnp.float32)
    return (x2d.astype(jnp.float32) @ wf).astype(x2d.dtype)
