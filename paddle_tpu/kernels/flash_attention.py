"""Pallas flash attention (TPU) — fwd + bwd with online softmax.

Capability parity: reference flash-attention integration
(`paddle/phi/kernels/gpu/flash_attn_kernel.cu` dynloading FA2, python API
`python/paddle/nn/functional/flash_attention.py:242`). Rebuilt as a native
Pallas TPU kernel rather than a vendor-library binding.

Design (see /opt/skills/guides/pallas_guide.md):
  * layout (B, S, H, D) -> kernel works on (B*H, S, D);
  * grid over (batch*heads, q blocks); K/V stream through VMEM whole
    (fits comfortably for S <= ~8k at D=128 in bf16) while Q/O are blocked —
    the MXU sees (block_q, D) x (D, S) matmuls;
  * online softmax carries running max/denominator in fp32;
  * backward = custom_vjp with a dq kernel and a dkv kernel, recomputing
    probabilities from the saved logsumexp (no S^2 residuals).
Falls back to the XLA composition automatically when shapes don't fit
(caller: nn.functional.scaled_dot_product_attention).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

__all__ = ["flash_attention_bshd"]

_INTERPRET_CACHE = [None]


def _interpret_mode():
    """Pallas interpret=True off-TPU so the same kernel runs in CPU tests."""
    if _INTERPRET_CACHE[0] is None:
        _INTERPRET_CACHE[0] = jax.default_backend() not in ("tpu",)
    return _INTERPRET_CACHE[0]


NEG_INF = -1e30


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, sm_scale, causal,
                block_k, q_offset_blocks):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)          # (bq, D)
    bq = q.shape[0]
    S = k_ref.shape[1]
    nk = S // block_k

    def body(ki, carry):
        acc, m_prev, l_prev = carry
        k = k_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = s * sm_scale
        if causal:
            q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            # allow keys up to q_pos + key_offset (prefill-with-cache)
            s = jnp.where(k_pos <= q_pos + q_offset_blocks, s, NEG_INF)
        m_cur = jnp.max(s, axis=1)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=1)
        acc = acc * alpha[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        return acc, m_new, l_new

    acc0 = jnp.zeros((bq, q_ref.shape[2]), jnp.float32)
    m0 = jnp.full((bq,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, nk, body, (acc0, m0, l0))
    safe_l = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / safe_l[:, None]).astype(o_ref.dtype)
    lse_ref[0] = (m + jnp.log(safe_l)).astype(jnp.float32)


def _fwd(q, k, v, sm_scale, causal, block_q, block_k):
    BH, Sq, D = q.shape
    Sk = k.shape[1]
    grid = (BH, Sq // block_q)
    kernel = functools.partial(_fwd_kernel, sm_scale=sm_scale, causal=causal,
                               block_k=block_k,
                               q_offset_blocks=Sk - Sq)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, Sk, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, Sk, D), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q), lambda b, i: (b, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((BH, Sq), jnp.float32),
        ],
        interpret=_interpret_mode(),
    )(q, k, v)
    return out, lse


def _dq_kernel(q_ref, k_ref, v_ref, delta_ref, do_ref, lse_ref, dq_ref, *,
               sm_scale, causal, block_k, q_offset):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    delta = delta_ref[0]                       # (bq,) = sum(do*o) per row
    lse = lse_ref[0]
    bq = q.shape[0]
    S = k_ref.shape[1]
    nk = S // block_k

    def body(ki, dq):
        k = k_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if causal:
            q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            s = jnp.where(k_pos <= q_pos + q_offset, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * sm_scale
        return dq + jax.lax.dot_general(ds, k, (((1,), (0,)), ((), ())),
                                        preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, nk, body, jnp.zeros_like(q))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, delta_ref, do_ref, lse_ref, dk_ref,
                dv_ref, *, sm_scale, causal, block_q, q_offset):
    ki = pl.program_id(1)
    k = k_ref[0].astype(jnp.float32)          # (bk, D)
    v = v_ref[0].astype(jnp.float32)
    bk = k.shape[0]
    Sq = q_ref.shape[1]
    nq = Sq // block_q

    def body(qi, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(qi * block_q, block_q), :].astype(jnp.float32)
        do = do_ref[0, pl.ds(qi * block_q, block_q), :].astype(jnp.float32)
        delta = delta_ref[0, pl.ds(qi * block_q, block_q)]
        lse = lse_ref[0, pl.ds(qi * block_q, block_q)]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, bk), 0)
            k_pos = ki * bk + jax.lax.broadcasted_iota(jnp.int32, (block_q, bk), 1)
            s = jnp.where(k_pos <= q_pos + q_offset, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])          # (bq, bk)
        dv = dv + jax.lax.dot_general(p, do, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * sm_scale
        dk = dk + jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        return dk, dv

    dk0 = jnp.zeros_like(k)
    dv0 = jnp.zeros_like(v)
    dk, dv = jax.lax.fori_loop(0, nq, body, (dk0, dv0))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _bwd(sm_scale, causal, block_q, block_k, res, dout):
    q, k, v, out, lse = res
    delta = jnp.sum(dout.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)
    return _bwd_with_delta(sm_scale, causal, block_q, block_k,
                           q, k, v, delta, lse, dout)


def _bwd_with_delta(sm_scale, causal, block_q, block_k, q, k, v, delta, lse,
                    dout):
    """delta: (BH, Sq) f32 = sum(dout*out, -1) — precomputed so callers
    (e.g. ring attention) need not carry the full output tensor."""
    BH, Sq, D = q.shape
    Sk = k.shape[1]
    q_offset = Sk - Sq

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, sm_scale=sm_scale, causal=causal,
                          block_k=block_k, q_offset=q_offset),
        grid=(BH, Sq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, Sk, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, Sk, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, block_q), lambda b, i: (b, i)),
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q), lambda b, i: (b, i)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=_interpret_mode(),
    )(q, k, v, delta, dout, lse)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, q_offset=q_offset),
        grid=(BH, Sk // block_k),
        in_specs=[
            pl.BlockSpec((1, Sq, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, Sq), lambda b, i: (b, 0)),
            pl.BlockSpec((1, Sq, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, Sq), lambda b, i: (b, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        interpret=_interpret_mode(),
    )(q, k, v, delta, dout, lse)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_core(q, k, v, sm_scale, causal, block_q, block_k):
    out, _ = _fwd(q, k, v, sm_scale, causal, block_q, block_k)
    return out


def _flash_core_fwd(q, k, v, sm_scale, causal, block_q, block_k):
    out, lse = _fwd(q, k, v, sm_scale, causal, block_q, block_k)
    return out, (q, k, v, out, lse)


def _flash_core_bwd(sm_scale, causal, block_q, block_k, res, dout):
    return _bwd(sm_scale, causal, block_q, block_k, res, dout)


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


def _pick_block(n, target):
    b = min(target, n)
    while n % b != 0:
        b //= 2
    return max(b, 1)


def check_supported(q_shape, k_shape, dtype):
    """Raises ValueError for shapes the kernel doesn't support (caller falls
    back to the XLA composition)."""
    B, Sq, H, D = q_shape
    Sk = k_shape[1]
    if D > 256 or D % 8 != 0:
        raise ValueError(f"head_dim {D} unsupported")
    if Sq % 8 != 0 or Sk % 8 != 0:
        raise ValueError("seq len must be multiple of 8")
    # VMEM budget: whole K/V per (batch,head) must fit
    if Sk * D * max(jnp.dtype(dtype).itemsize, 2) > 8 * 1024 * 1024:
        raise ValueError("K/V too large for single-pass VMEM streaming")


def flash_attention_bshd(q, k, v, causal=False, sm_scale=None):
    """q,k,v: (B, S, H, D) -> out (B, Sq, H, D)."""
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    check_supported(tuple(q.shape), tuple(k.shape), q.dtype)
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(D)
    block_q = _pick_block(Sq, 256)
    block_k = _pick_block(Sk, 512)

    def to_bhsd(x):
        return jnp.swapaxes(x, 1, 2).reshape(x.shape[0] * x.shape[2],
                                             x.shape[1], x.shape[3])

    qf = to_bhsd(q)
    kf = to_bhsd(k)
    vf = to_bhsd(v)
    out = _flash_core(qf, kf, vf, float(sm_scale), bool(causal),
                      int(block_q), int(block_k))
    out = out.reshape(B, H, Sq, D)
    return jnp.swapaxes(out, 1, 2)
