"""Pallas flash attention (TPU) — fwd + bwd with online softmax.

Capability parity: reference flash-attention integration
(`paddle/phi/kernels/gpu/flash_attn_kernel.cu` dynloading FA2, python API
`python/paddle/nn/functional/flash_attention.py:242`). Rebuilt as a native
Pallas TPU kernel rather than a vendor-library binding.

Design (see /opt/skills/guides/pallas_guide.md):
  * layout (B, S, H, D) -> kernel works on (B*H, S, D);
  * grid (BH, q blocks, k blocks) with the k dimension innermost: K/V
    blocks stream through VMEM (Pallas double-buffers the fetches), Q and
    the fp32 accumulator stay resident in VMEM scratch across the k loop —
    no whole-K/V residency, so sequence length is HBM-bound, not VMEM-bound;
  * online softmax carries running max/denominator as (block_q, 128) fp32
    lane-broadcast scratch (TPU-legal stats layout);
  * logsumexp is emitted as (BH, 1, Sq) so its BlockSpec (1, 1, block_q)
    satisfies Mosaic's (8, 128) last-two-dims rule (second-to-last == array
    dim, last % 128 == 0 or == Sq) — validated on real v5e hardware;
  * causal runs skip fully-masked K/V blocks' compute via pl.when;
  * backward = custom_vjp with a dq kernel (grid (BH, nq, nk)) and a dkv
    kernel (grid (BH, nk, nq)), recomputing probabilities from the saved
    logsumexp (no S^2 residuals).
Falls back to the XLA composition automatically when shapes don't fit
(caller: nn.functional.scaled_dot_product_attention).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["flash_attention_bshd"]

_INTERPRET_CACHE = [None]


def _interpret_mode():
    """Pallas interpret=True off-TPU so the same kernel runs in CPU tests."""
    if _INTERPRET_CACHE[0] is None:
        _INTERPRET_CACHE[0] = jax.default_backend() not in ("tpu",)
    return _INTERPRET_CACHE[0]


NEG_INF = np.float32(-1e30)
_STATS_LANES = 128  # lane width for the m/l running-stat scratch
_I0 = np.int32(0)   # index-map zero: the package enables x64, and Mosaic
                    # rejects i64 index-map results, so pin literals to i32


def _causal_block_mask(s, qi, ki, block_q, block_k, q_offset):
    """In-block causal mask: key pos <= query pos + q_offset."""
    bq, bk = s.shape
    q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    k_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return jnp.where(k_pos <= q_pos + q_offset, s, NEG_INF)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
                *, sm_scale, causal, block_q, block_k, nk, q_offset):
    sm_scale = np.float32(sm_scale)  # strong f32: x64 mode makes bare
    # python/np floats f64, which Mosaic cannot store into f32 refs
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # A K/V block is entirely above the causal diagonal iff its first key
    # position exceeds the last query position (+offset): skip its compute.
    contributes = (ki * block_k <= qi * block_q + (block_q - 1) + q_offset) \
        if causal else (ki >= 0)

    @pl.when(contributes)
    def _step():
        q = q_ref[0].astype(jnp.float32)           # (bq, D)
        k = k_ref[0].astype(jnp.float32)           # (bk, D)
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = s * sm_scale
        if causal:
            s = _causal_block_mask(s, qi, ki, block_q, block_k, q_offset)
        m_prev = m_ref[:, :1]                      # (bq, 1), lanes equal
        l_prev = l_ref[:, :1]
        m_cur = jnp.max(s, axis=1, keepdims=True)  # (bq, 1)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                     # (bq, bk)
        alpha = jnp.exp(m_prev - m_new)            # (bq, 1)
        l_new = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _finalize():
        l = l_ref[:, :1]
        safe_l = jnp.maximum(l, np.float32(1e-30))
        o_ref[0] = (acc_ref[...] / safe_l).astype(o_ref.dtype)
        lse_ref[0, 0] = m_ref[:, 0] + jnp.log(safe_l[:, 0])


def _fwd(q, k, v, sm_scale, causal, block_q, block_k):
    """(BH, Sq, D) x (BH, Sk, D)^2 -> out (BH, Sq, D), lse (BH, Sq) f32."""
    BH, Sq, D = q.shape
    Sk = k.shape[1]
    nq = Sq // block_q
    nk = Sk // block_k
    grid = (BH, nq, nk)
    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal, block_q=block_q,
        block_k=block_k, nk=nk, q_offset=Sk - Sq)
    out, lse3 = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, _I0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, _I0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, _I0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, _I0)),
            pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, _I0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((BH, 1, Sq), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),
            pltpu.VMEM((block_q, _STATS_LANES), jnp.float32),
            pltpu.VMEM((block_q, _STATS_LANES), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret_mode(),
    )(q, k, v)
    return out, lse3[:, 0, :]


def _dq_kernel(q_ref, k_ref, v_ref, delta_ref, do_ref, lse_ref, dq_ref,
               dq_acc_ref, *, sm_scale, causal, block_q, block_k, nk,
               q_offset):
    sm_scale = np.float32(sm_scale)  # strong f32: x64 mode makes bare
    # python/np floats f64, which Mosaic cannot store into f32 refs
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_acc_ref[...] = jnp.zeros_like(dq_acc_ref)

    contributes = (ki * block_k <= qi * block_q + (block_q - 1) + q_offset) \
        if causal else (ki >= 0)

    @pl.when(contributes)
    def _step():
        q = q_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        delta = delta_ref[0, 0][:, None]           # (bq, 1)
        lse = lse_ref[0, 0][:, None]               # (bq, 1)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if causal:
            s = _causal_block_mask(s, qi, ki, block_q, block_k, q_offset)
        p = jnp.exp(s - lse)                       # (bq, bk)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        dq_acc_ref[...] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0] = dq_acc_ref[...].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, delta_ref, do_ref, lse_ref, dk_ref,
                dv_ref, dk_acc_ref, dv_acc_ref, *, sm_scale, causal, block_q,
                block_k, nq, q_offset):
    sm_scale = np.float32(sm_scale)  # strong f32: x64 mode makes bare
    # python/np floats f64, which Mosaic cannot store into f32 refs
    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc_ref[...] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[...] = jnp.zeros_like(dv_acc_ref)

    # A q block contributes to this k block iff its last query position
    # (+offset) reaches the k block's first key position.
    contributes = (qi * block_q + (block_q - 1) + q_offset >= ki * block_k) \
        if causal else (qi >= 0)

    @pl.when(contributes)
    def _step():
        k = k_ref[0].astype(jnp.float32)           # (bk, D)
        v = v_ref[0].astype(jnp.float32)
        q = q_ref[0].astype(jnp.float32)           # (bq, D)
        do = do_ref[0].astype(jnp.float32)
        delta = delta_ref[0, 0][:, None]
        lse = lse_ref[0, 0][:, None]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if causal:
            s = _causal_block_mask(s, qi, ki, block_q, block_k, q_offset)
        p = jnp.exp(s - lse)                       # (bq, bk)
        dv_acc_ref[...] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        dk_acc_ref[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0] = dk_acc_ref[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc_ref[...].astype(dv_ref.dtype)


def _bwd(sm_scale, causal, block_q, block_k, res, dout):
    q, k, v, out, lse = res
    delta = jnp.sum(dout.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)
    return _bwd_with_delta(sm_scale, causal, block_q, block_k,
                           q, k, v, delta, lse, dout)


def _bwd_with_delta(sm_scale, causal, block_q, block_k, q, k, v, delta, lse,
                    dout):
    """delta: (BH, Sq) f32 = sum(dout*out, -1) — precomputed so callers
    (e.g. ring attention) need not carry the full output tensor."""
    BH, Sq, D = q.shape
    Sk = k.shape[1]
    q_offset = Sk - Sq
    nq = Sq // block_q
    nk = Sk // block_k
    delta3 = delta[:, None, :]                     # (BH, 1, Sq)
    lse3 = lse[:, None, :]

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k, nk=nk,
                          q_offset=q_offset),
        grid=(BH, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, _I0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, _I0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, _I0)),
            pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, _I0, i)),
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, _I0)),
            pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, _I0, i)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, _I0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret_mode(),
    )(q, k, v, delta3, dout, lse3)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k, nq=nq,
                          q_offset=q_offset),
        grid=(BH, nk, nq),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, j, i: (b, i, _I0)),
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, _I0)),
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, _I0)),
            pl.BlockSpec((1, 1, block_q), lambda b, j, i: (b, _I0, i)),
            pl.BlockSpec((1, block_q, D), lambda b, j, i: (b, i, _I0)),
            pl.BlockSpec((1, 1, block_q), lambda b, j, i: (b, _I0, i)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, _I0)),
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, _I0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret_mode(),
    )(q, k, v, delta3, dout, lse3)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_core(q, k, v, sm_scale, causal, block_q, block_k):
    out, _ = _fwd(q, k, v, sm_scale, causal, block_q, block_k)
    return out


def _flash_core_fwd(q, k, v, sm_scale, causal, block_q, block_k):
    out, lse = _fwd(q, k, v, sm_scale, causal, block_q, block_k)
    return out, (q, k, v, out, lse)


def _flash_core_bwd(sm_scale, causal, block_q, block_k, res, dout):
    return _bwd(sm_scale, causal, block_q, block_k, res, dout)


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


def _pick_block(n, target):
    """Pick a block along a sequence axis: either the whole axis (always
    legal — BlockSpec dims equal to the array dims pass the Mosaic (8,128)
    rule) or a divisor that is a multiple of 128. The 128 constraint comes
    from the q axis, whose (1, 1, block_q) lse/delta specs put block_q in
    the lane dimension; k blocks share the same picker so both stay
    MXU-tile aligned."""
    if n <= target or n % 128 != 0:
        return n
    b = target
    while n % b != 0:
        b -= 128
    return max(b, 128)


def _pick_block_q(sq, target=256):
    return _pick_block(sq, target)


def _pick_block_k(sk, target=512):
    return _pick_block(sk, target)


def check_supported(q_shape, k_shape, dtype):
    """Raises ValueError for shapes the kernel doesn't support (caller falls
    back to the XLA composition). K/V stream through VMEM in blocks, so
    sequence length is not VMEM-bound; only tiling legality is checked."""
    B, Sq, H, D = q_shape
    Sk = k_shape[1]
    if D > 256 or D % 8 != 0:
        raise ValueError(f"head_dim {D} unsupported")
    if Sq % 8 != 0 or Sk % 8 != 0:
        raise ValueError("seq len must be multiple of 8")
    if Sq % 128 != 0 and Sq > 1024:
        raise ValueError("long Sq must be a multiple of 128")
    if Sk % 128 != 0 and Sk > 1024:
        raise ValueError("long Sk must be a multiple of 128")


def flash_attention_bshd(q, k, v, causal=False, sm_scale=None):
    """q,k,v: (B, S, H, D) -> out (B, Sq, H, D)."""
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    check_supported(tuple(q.shape), tuple(k.shape), q.dtype)
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(D)
    block_q = _pick_block_q(Sq)
    block_k = _pick_block_k(Sk)

    def to_bhsd(x):
        return jnp.swapaxes(x, 1, 2).reshape(x.shape[0] * x.shape[2],
                                             x.shape[1], x.shape[3])

    qf = to_bhsd(q)
    kf = to_bhsd(k)
    vf = to_bhsd(v)
    out = _flash_core(qf, kf, vf, float(sm_scale), bool(causal),
                      int(block_q), int(block_k))
    out = out.reshape(B, H, Sq, D)
    return jnp.swapaxes(out, 1, 2)
