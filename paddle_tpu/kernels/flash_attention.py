"""Pallas flash attention (TPU) — fwd + bwd with online softmax.

Capability parity: reference flash-attention integration
(`paddle/phi/kernels/gpu/flash_attn_kernel.cu` dynloading FA2, python API
`python/paddle/nn/functional/flash_attention.py:242` flash_attention,
`:1098` flashmask_attention, varlen `flash_attn_unpadded`). Rebuilt as a
native Pallas TPU kernel rather than a vendor-library binding.

Design (see /opt/skills/guides/pallas_guide.md):
  * layout (B, S, H, D) -> kernel works on (B*H, S, D);
  * grid (BH, q blocks, k blocks) with the k dimension innermost: K/V
    blocks stream through VMEM (Pallas double-buffers the fetches), Q and
    the fp32 accumulator stay resident in VMEM scratch across the k loop —
    no whole-K/V residency, so sequence length is HBM-bound, not VMEM-bound;
  * online softmax carries running max/denominator as (block_q, 128) fp32
    lane-broadcast scratch (TPU-legal stats layout);
  * logsumexp is emitted as (BH, 1, Sq) so its BlockSpec (1, 1, block_q)
    satisfies Mosaic's (8, 128) last-two-dims rule (second-to-last == array
    dim, last % 128 == 0 or == Sq) — validated on real v5e hardware;
  * causal runs skip fully-masked K/V blocks' compute via pl.when;
  * varlen (cu_seqlens) runs pass per-token segment ids as (B, 1, S) int32
    blocks; cross-segment scores are masked in-block and K/V blocks whose
    segment range doesn't overlap the q block's are skipped entirely;
  * flashmask runs pass the (B, Hm, Sk, C) startend_row_indices as
    (B*Hm, C, Sk) column-bound blocks — the mask is reconstructed per
    (q block, k block) tile from O(S*C) bounds, never materialized as a
    dense (B, H, Sq, Sk) tensor; for the causal C==1 (document-mask) case,
    K/V blocks that the bounds mask out completely are skipped;
  * backward = custom_vjp with a dq kernel (grid (BH, nq, nk)) and a dkv
    kernel (grid (BH, nk, nq)), recomputing probabilities from the saved
    logsumexp (no S^2 residuals).
Falls back to the XLA composition automatically when shapes don't fit
(caller: nn.functional.scaled_dot_product_attention).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..jax_compat import patch_pltpu

patch_pltpu()

__all__ = ["flash_attention_bshd", "flash_attention_varlen_bshd",
           "flashmask_attention_bshd"]

_INTERPRET_CACHE = [None]


def _interpret_mode():
    """Pallas interpret=True off-TPU so the same kernel runs in CPU tests."""
    if _INTERPRET_CACHE[0] is None:
        _INTERPRET_CACHE[0] = jax.default_backend() not in ("tpu",)
    return _INTERPRET_CACHE[0]


NEG_INF = np.float32(-1e30)
_STATS_LANES = 128  # lane width for the m/l running-stat scratch
_I0 = np.int32(0)   # index-map zero: the package enables x64, and Mosaic
                    # rejects i64 index-map results, so pin literals to i32


def _causal_block_mask(s, qi, ki, block_q, block_k, q_offset):
    """In-block causal mask: key pos <= query pos + q_offset."""
    bq, bk = s.shape
    q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    k_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return jnp.where(k_pos <= q_pos + q_offset, s, NEG_INF)


def _flashmask_block_mask(s, qi, ki, block_q, block_k, q_offset, fm_blk,
                          fm_causal, fm_cols):
    """Apply the flashmask column bounds to an in-block score tile.

    fm_blk: (C, block_k) int32 row bounds for this k block (reference
    startend_row_indices semantics, flash_attention.py:1098). Row indices
    are query positions; flashmask requires Sq == Sk (enforced by the
    wrapper) so the frame matches the XLA fallback exactly.
    """
    bq, bk = s.shape
    rows = (qi * block_q
            + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0))
    b = fm_blk.astype(jnp.int32)
    if fm_causal:
        if fm_cols == 1:
            masked = rows >= b[0][None, :]
        else:
            masked = (rows >= b[0][None, :]) & (rows < b[1][None, :])
    else:
        if fm_cols == 2:
            masked = (rows >= b[0][None, :]) | (rows < b[1][None, :])
        else:
            masked = (((rows >= b[0][None, :]) & (rows < b[1][None, :]))
                      | ((rows >= b[2][None, :]) & (rows < b[3][None, :])))
    return jnp.where(masked, NEG_INF, s)


def _apply_masks(s, qi, ki, *, block_q, block_k, q_offset, causal,
                 segq_blk=None, segk_blk=None, posq_blk=None, posk_blk=None,
                 fm_blk=None, fm_causal=True, fm_cols=0):
    if causal and segq_blk is None:
        s = _causal_block_mask(s, qi, ki, block_q, block_k, q_offset)
    if segq_blk is not None:
        allow = segq_blk[:, None] == segk_blk[None, :]
        if causal:
            # per-sequence causal: key's position within its sequence must
            # not exceed the query's (length-difference-adjusted) position —
            # a single packed-global offset would be wrong when per-sequence
            # q/k lengths differ
            allow = jnp.logical_and(allow,
                                    posk_blk[None, :] <= posq_blk[:, None])
        s = jnp.where(allow, s, NEG_INF)
    if fm_cols:
        s = _flashmask_block_mask(s, qi, ki, block_q, block_k, q_offset,
                                  fm_blk, fm_causal, fm_cols)
    return s


def _masked_exp(s, ref):
    """exp(s - ref) that yields exactly 0 for masked (-1e30) scores even
    when `ref` is itself -1e30 (row with no valid key seen yet)."""
    return jnp.where(s <= NEG_INF / 2, 0.0, jnp.exp(s - ref))


def _unpack_refs(refs, n_fixed, use_seg, fm_cols):
    """Split the variadic pallas ref list into (fixed inputs, segq, segk,
    fm, rest)."""
    fixed = refs[:n_fixed]
    idx = n_fixed
    segq_ref = segk_ref = fm_ref = None
    if use_seg:
        segq_ref, segk_ref = refs[idx], refs[idx + 1]
        idx += 2
    if fm_cols:
        fm_ref = refs[idx]
        idx += 1
    return fixed, segq_ref, segk_ref, fm_ref, refs[idx:]


def _block_contributes(qi, ki, *, block_q, block_k, q_offset, causal,
                       segq_blk, segk_blk, posq_blk=None, posk_blk=None,
                       fm_blk=None, fm_causal=True, fm_cols=0):
    """Whether this (q block, k block) tile can contain any unmasked score
    (cheap bound checks -> pl.when skips the matmuls entirely)."""
    if causal and segq_blk is None:
        contributes = ki * block_k <= qi * block_q + (block_q - 1) + q_offset
    else:
        contributes = ki >= 0
    if segq_blk is not None:
        # contiguous segment ids: ranges must overlap
        overlap = jnp.logical_and(jnp.min(segq_blk) <= jnp.max(segk_blk),
                                  jnp.max(segq_blk) >= jnp.min(segk_blk))
        contributes = jnp.logical_and(contributes, overlap)
        if causal:
            # the packed-global causal bound is invalid with per-sequence
            # alignment; skip instead when both blocks sit in one shared
            # sequence and every key position exceeds every query position
            one_seq = jnp.logical_and(
                jnp.min(segq_blk) == jnp.max(segk_blk),
                jnp.max(segq_blk) == jnp.min(segk_blk))
            all_future = jnp.min(posk_blk) > jnp.max(posq_blk)
            contributes = jnp.logical_and(
                contributes,
                jnp.logical_not(jnp.logical_and(one_seq, all_future)))
    if fm_cols == 1 and fm_causal and fm_blk is not None:
        # document mask: every row/col masked iff first q row >= max(start)
        q0 = qi * block_q
        any_open = q0 < jnp.max(fm_blk[0])
        contributes = jnp.logical_and(contributes, any_open)
    return contributes


def _fwd_kernel(*refs, sm_scale, causal, block_q, block_k, nk, q_offset,
                use_seg, fm_causal, fm_cols):
    sm_scale = np.float32(sm_scale)  # strong f32: x64 mode makes bare
    # python/np floats f64, which Mosaic cannot store into f32 refs
    (q_ref, k_ref, v_ref), segq_ref, segk_ref, fm_ref, rest = _unpack_refs(
        refs, 3, use_seg, fm_cols)
    o_ref, lse_ref, acc_ref, m_ref, l_ref = rest
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    masked_rows = use_seg or fm_cols  # rows may see no valid key yet

    @pl.when(ki == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    segq_blk = segq_ref[0, 0] if use_seg else None
    posq_blk = segq_ref[0, 1] if use_seg else None
    segk_blk = segk_ref[0, 0] if use_seg else None
    posk_blk = segk_ref[0, 1] if use_seg else None
    fm_blk = fm_ref[0] if fm_cols else None
    contributes = _block_contributes(
        qi, ki, block_q=block_q, block_k=block_k, q_offset=q_offset,
        causal=causal, segq_blk=segq_blk, segk_blk=segk_blk,
        posq_blk=posq_blk, posk_blk=posk_blk, fm_blk=fm_blk,
        fm_causal=fm_causal, fm_cols=fm_cols)

    @pl.when(contributes)
    def _step():
        q = q_ref[0].astype(jnp.float32)           # (bq, D)
        k = k_ref[0].astype(jnp.float32)           # (bk, D)
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = s * sm_scale
        s = _apply_masks(s, qi, ki, block_q=block_q, block_k=block_k,
                         q_offset=q_offset, causal=causal, segq_blk=segq_blk,
                         segk_blk=segk_blk, posq_blk=posq_blk,
                         posk_blk=posk_blk, fm_blk=fm_blk,
                         fm_causal=fm_causal, fm_cols=fm_cols)
        m_prev = m_ref[:, :1]                      # (bq, 1), lanes equal
        l_prev = l_ref[:, :1]
        m_cur = jnp.max(s, axis=1, keepdims=True)  # (bq, 1)
        m_new = jnp.maximum(m_prev, m_cur)
        p = _masked_exp(s, m_new) if masked_rows else jnp.exp(s - m_new)
        alpha = jnp.where(m_prev <= NEG_INF / 2, 0.0,
                          jnp.exp(m_prev - m_new)) if masked_rows else \
            jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _finalize():
        l = l_ref[:, :1]
        safe_l = jnp.maximum(l, np.float32(1e-30))
        o_ref[0] = (acc_ref[...] / safe_l).astype(o_ref.dtype)
        lse_ref[0, 0] = m_ref[:, 0] + jnp.log(safe_l[:, 0])


def _extra_in_specs(B, H, Sq, Sk, block_q, block_k, use_seg, fm_cols, fm_heads,
                    kmajor=False):
    """BlockSpecs for the optional segment-id / flashmask inputs.

    Grid order is (bh, i=q block, j=k block) — or (bh, j, i) for the dkv
    kernel (kmajor=True)."""
    specs = []
    if kmajor:
        def qmap(idx):
            return lambda b, j, i, _f=idx: _f(b, i, j)
    else:
        def qmap(idx):
            return idx

    def bdiv(b):
        # b // H via lax.div (b >= 0): jnp floor-division lowers through an
        # i64 convert under x64, which Mosaic cannot lower (infinite
        # recursion in its convert fallback — found on real v5e)
        return jax.lax.div(b, jnp.asarray(H, jnp.int32))

    if use_seg:
        # rows: [segment id, causal position-within-sequence]
        specs.append(pl.BlockSpec(
            (1, 2, block_q), qmap(lambda b, i, j: (bdiv(b), _I0, i))))
        specs.append(pl.BlockSpec(
            (1, 2, block_k), qmap(lambda b, i, j: (bdiv(b), _I0, j))))
    if fm_cols:
        if fm_heads == 1:
            specs.append(pl.BlockSpec(
                (1, fm_cols, block_k),
                qmap(lambda b, i, j: (bdiv(b), _I0, j))))
        else:
            specs.append(pl.BlockSpec(
                (1, fm_cols, block_k), qmap(lambda b, i, j: (b, _I0, j))))
    return specs


def _fwd(q, k, v, sm_scale, causal, block_q, block_k, seg=None, fm=None,
         fm_causal=True, H=1):
    """(BH, Sq, D) x (BH, Sk, D)^2 -> out (BH, Sq, D), lse (BH, Sq) f32.

    seg: optional (segq (B,2,Sq), segk (B,2,Sk)) int32 [segment id;
    causal position-within-sequence] rows.
    fm: optional (B*Hm, C, Sk) flashmask bounds."""
    BH, Sq, D = q.shape
    Sk = k.shape[1]
    nq = Sq // block_q
    nk = Sk // block_k
    grid = (BH, nq, nk)
    use_seg = seg is not None
    fm_cols = fm.shape[1] if fm is not None else 0
    fm_heads = (fm.shape[0] * H) // BH if fm is not None else 1
    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal, block_q=block_q,
        block_k=block_k, nk=nk, q_offset=Sk - Sq, use_seg=use_seg,
        fm_causal=fm_causal, fm_cols=fm_cols)
    in_specs = [
        pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, _I0)),
        pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, _I0)),
        pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, _I0)),
    ] + _extra_in_specs(BH // H, H, Sq, Sk, block_q, block_k, use_seg,
                        fm_cols, fm_heads)
    args = [q, k, v]
    if use_seg:
        args += [seg[0], seg[1]]
    if fm_cols:
        args.append(fm)
    out, lse3 = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, _I0)),
            pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, _I0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((BH, 1, Sq), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),
            pltpu.VMEM((block_q, _STATS_LANES), jnp.float32),
            pltpu.VMEM((block_q, _STATS_LANES), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret_mode(),
    )(*args)
    return out, lse3[:, 0, :]


def _dq_kernel(*refs, sm_scale, causal, block_q, block_k, nk, q_offset,
               use_seg, fm_causal, fm_cols):
    sm_scale = np.float32(sm_scale)  # strong f32: x64 mode makes bare
    # python/np floats f64, which Mosaic cannot store into f32 refs
    (q_ref, k_ref, v_ref, delta_ref, do_ref, lse_ref), segq_ref, segk_ref, \
        fm_ref, rest = _unpack_refs(refs, 6, use_seg, fm_cols)
    dq_ref, dq_acc_ref = rest
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    masked_rows = use_seg or fm_cols

    @pl.when(ki == 0)
    def _init():
        dq_acc_ref[...] = jnp.zeros_like(dq_acc_ref)

    segq_blk = segq_ref[0, 0] if use_seg else None
    posq_blk = segq_ref[0, 1] if use_seg else None
    segk_blk = segk_ref[0, 0] if use_seg else None
    posk_blk = segk_ref[0, 1] if use_seg else None
    fm_blk = fm_ref[0] if fm_cols else None
    contributes = _block_contributes(
        qi, ki, block_q=block_q, block_k=block_k, q_offset=q_offset,
        causal=causal, segq_blk=segq_blk, segk_blk=segk_blk,
        posq_blk=posq_blk, posk_blk=posk_blk, fm_blk=fm_blk,
        fm_causal=fm_causal, fm_cols=fm_cols)

    @pl.when(contributes)
    def _step():
        q = q_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        delta = delta_ref[0, 0][:, None]           # (bq, 1)
        lse = lse_ref[0, 0][:, None]               # (bq, 1)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        s = _apply_masks(s, qi, ki, block_q=block_q, block_k=block_k,
                         q_offset=q_offset, causal=causal, segq_blk=segq_blk,
                         segk_blk=segk_blk, posq_blk=posq_blk,
                         posk_blk=posk_blk, fm_blk=fm_blk,
                         fm_causal=fm_causal, fm_cols=fm_cols)
        p = _masked_exp(s, lse) if masked_rows else jnp.exp(s - lse)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        dq_acc_ref[...] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0] = dq_acc_ref[...].astype(dq_ref.dtype)


def _dkv_kernel(*refs, sm_scale, causal, block_q, block_k, nq, q_offset,
                use_seg, fm_causal, fm_cols):
    sm_scale = np.float32(sm_scale)  # strong f32: x64 mode makes bare
    # python/np floats f64, which Mosaic cannot store into f32 refs
    (q_ref, k_ref, v_ref, delta_ref, do_ref, lse_ref), segq_ref, segk_ref, \
        fm_ref, rest = _unpack_refs(refs, 6, use_seg, fm_cols)
    dk_ref, dv_ref, dk_acc_ref, dv_acc_ref = rest
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    masked_rows = use_seg or fm_cols

    @pl.when(qi == 0)
    def _init():
        dk_acc_ref[...] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[...] = jnp.zeros_like(dv_acc_ref)

    segq_blk = segq_ref[0, 0] if use_seg else None
    posq_blk = segq_ref[0, 1] if use_seg else None
    segk_blk = segk_ref[0, 0] if use_seg else None
    posk_blk = segk_ref[0, 1] if use_seg else None
    fm_blk = fm_ref[0] if fm_cols else None
    # same skip predicate as fwd/dq: the causal bound "k block start <= q
    # block end (+offset)" is symmetric in the two grid orders
    contributes = _block_contributes(
        qi, ki, block_q=block_q, block_k=block_k, q_offset=q_offset,
        causal=causal, segq_blk=segq_blk, segk_blk=segk_blk,
        posq_blk=posq_blk, posk_blk=posk_blk, fm_blk=fm_blk,
        fm_causal=fm_causal, fm_cols=fm_cols)

    @pl.when(contributes)
    def _step():
        k = k_ref[0].astype(jnp.float32)           # (bk, D)
        v = v_ref[0].astype(jnp.float32)
        q = q_ref[0].astype(jnp.float32)           # (bq, D)
        do = do_ref[0].astype(jnp.float32)
        delta = delta_ref[0, 0][:, None]
        lse = lse_ref[0, 0][:, None]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        s = _apply_masks(s, qi, ki, block_q=block_q, block_k=block_k,
                         q_offset=q_offset, causal=causal, segq_blk=segq_blk,
                         segk_blk=segk_blk, posq_blk=posq_blk,
                         posk_blk=posk_blk, fm_blk=fm_blk,
                         fm_causal=fm_causal, fm_cols=fm_cols)
        p = _masked_exp(s, lse) if masked_rows else jnp.exp(s - lse)
        dv_acc_ref[...] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        dk_acc_ref[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0] = dk_acc_ref[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc_ref[...].astype(dv_ref.dtype)


def _bwd(sm_scale, causal, block_q, block_k, res, dout, seg=None, fm=None,
         fm_causal=True, H=1):
    q, k, v, out, lse = res
    delta = jnp.sum(dout.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)
    return _bwd_with_delta(sm_scale, causal, block_q, block_k,
                           q, k, v, delta, lse, dout, seg=seg, fm=fm,
                           fm_causal=fm_causal, H=H)


def _bwd_with_delta(sm_scale, causal, block_q, block_k, q, k, v, delta, lse,
                    dout, seg=None, fm=None, fm_causal=True, H=1):
    """delta: (BH, Sq) f32 = sum(dout*out, -1) — precomputed so callers
    (e.g. ring attention) need not carry the full output tensor."""
    BH, Sq, D = q.shape
    Sk = k.shape[1]
    q_offset = Sk - Sq
    nq = Sq // block_q
    nk = Sk // block_k
    delta3 = delta[:, None, :]                     # (BH, 1, Sq)
    lse3 = lse[:, None, :]
    use_seg = seg is not None
    fm_cols = fm.shape[1] if fm is not None else 0
    fm_heads = (fm.shape[0] * H) // BH if fm is not None else 1
    B = BH // H

    extra_args = []
    if use_seg:
        extra_args += [seg[0], seg[1]]
    if fm_cols:
        extra_args.append(fm)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k, nk=nk,
                          q_offset=q_offset, use_seg=use_seg,
                          fm_causal=fm_causal, fm_cols=fm_cols),
        grid=(BH, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, _I0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, _I0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, _I0)),
            pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, _I0, i)),
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, _I0)),
            pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, _I0, i)),
        ] + _extra_in_specs(B, H, Sq, Sk, block_q, block_k, use_seg, fm_cols,
                            fm_heads),
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, _I0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret_mode(),
    )(q, k, v, delta3, dout, lse3, *extra_args)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k, nq=nq,
                          q_offset=q_offset, use_seg=use_seg,
                          fm_causal=fm_causal, fm_cols=fm_cols),
        grid=(BH, nk, nq),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, j, i: (b, i, _I0)),
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, _I0)),
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, _I0)),
            pl.BlockSpec((1, 1, block_q), lambda b, j, i: (b, _I0, i)),
            pl.BlockSpec((1, block_q, D), lambda b, j, i: (b, i, _I0)),
            pl.BlockSpec((1, 1, block_q), lambda b, j, i: (b, _I0, i)),
        ] + _extra_in_specs(B, H, Sq, Sk, block_q, block_k, use_seg, fm_cols,
                            fm_heads, kmajor=True),
        out_specs=[
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, _I0)),
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, _I0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret_mode(),
    )(q, k, v, delta3, dout, lse3, *extra_args)
    return dq, dk, dv


# ------------------------------------------------------------- plain core
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_core(q, k, v, sm_scale, causal, block_q, block_k):
    out, _ = _fwd(q, k, v, sm_scale, causal, block_q, block_k)
    return out


def _flash_core_fwd(q, k, v, sm_scale, causal, block_q, block_k):
    out, lse = _fwd(q, k, v, sm_scale, causal, block_q, block_k)
    return out, (q, k, v, out, lse)


def _flash_core_bwd(sm_scale, causal, block_q, block_k, res, dout):
    return _bwd(sm_scale, causal, block_q, block_k, res, dout)


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


def _int_zero(x):
    """float0 cotangent for integer primal inputs of custom_vjp rules."""
    return np.zeros(x.shape, jax.dtypes.float0)


# ----------------------------------------------------------- varlen core
@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _flash_core_seg(q, k, v, segq, segk, sm_scale, causal, block_q, block_k,
                    H):
    out, _ = _fwd(q, k, v, sm_scale, causal, block_q, block_k,
                  seg=(segq, segk), H=H)
    return out


def _flash_core_seg_fwd(q, k, v, segq, segk, sm_scale, causal, block_q,
                        block_k, H):
    out, lse = _fwd(q, k, v, sm_scale, causal, block_q, block_k,
                    seg=(segq, segk), H=H)
    return out, (q, k, v, out, lse, segq, segk)


def _flash_core_seg_bwd(sm_scale, causal, block_q, block_k, H, res, dout):
    q, k, v, out, lse, segq, segk = res
    dq, dk, dv = _bwd(sm_scale, causal, block_q, block_k,
                      (q, k, v, out, lse), dout, seg=(segq, segk), H=H)
    return dq, dk, dv, _int_zero(segq), _int_zero(segk)


_flash_core_seg.defvjp(_flash_core_seg_fwd, _flash_core_seg_bwd)


# -------------------------------------------------------- flashmask core
@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9))
def _flash_core_fm(q, k, v, fm, sm_scale, causal, block_q, block_k,
                   fm_causal, H):
    out, _ = _fwd(q, k, v, sm_scale, causal, block_q, block_k, fm=fm,
                  fm_causal=fm_causal, H=H)
    return out


def _flash_core_fm_fwd(q, k, v, fm, sm_scale, causal, block_q, block_k,
                       fm_causal, H):
    out, lse = _fwd(q, k, v, sm_scale, causal, block_q, block_k, fm=fm,
                    fm_causal=fm_causal, H=H)
    return out, (q, k, v, out, lse, fm)


def _flash_core_fm_bwd(sm_scale, causal, block_q, block_k, fm_causal, H,
                       res, dout):
    q, k, v, out, lse, fm = res
    dq, dk, dv = _bwd(sm_scale, causal, block_q, block_k,
                      (q, k, v, out, lse), dout, fm=fm, fm_causal=fm_causal,
                      H=H)
    return dq, dk, dv, _int_zero(fm)


_flash_core_fm.defvjp(_flash_core_fm_fwd, _flash_core_fm_bwd)


def _pick_block(n, target):
    """Pick a block along a sequence axis: either the whole axis (always
    legal — BlockSpec dims equal to the array dims pass the Mosaic (8,128)
    rule) or a divisor that is a multiple of 128. The 128 constraint comes
    from the q axis, whose (1, 1, block_q) lse/delta specs put block_q in
    the lane dimension; k blocks share the same picker so both stay
    MXU-tile aligned."""
    if n <= target or n % 128 != 0:
        return n
    b = target
    while n % b != 0:
        b -= 128
    return max(b, 128)


def _pick_block_q(sq, target=1024):
    """Default (1024, 1024): the on-chip block sweeps (v5e; S∈{2048,
    8192}, D∈{64, 128}, causal; fwd and fwd+bwd; device-side timing)
    found it fastest at every shape tried — 1.5-1.9× over the original
    (256, 512) defaults. Bigger tiles amortize the per-block
    online-softmax bookkeeping and keep the MXU fed; VMEM stays under
    budget (k+v tiles at 1024×128 bf16 = 512 KB, scores 1024×1024 fp32
    = 4 MB). (2048, 2048) fails to compile (VMEM); (1024, 2048)
    regresses fwd badly — don't chase full-axis K."""
    return _pick_block(sq, target)


def _pick_block_k(sk, target=1024):
    return _pick_block(sk, target)


def check_supported(q_shape, k_shape, dtype):
    """Raises ValueError for shapes the kernel doesn't support (caller falls
    back to the XLA composition). K/V stream through VMEM in blocks, so
    sequence length is not VMEM-bound; only tiling legality is checked."""
    B, Sq, H, D = q_shape
    Sk = k_shape[1]
    if D > 256 or D % 8 != 0:
        raise ValueError(f"head_dim {D} unsupported")
    if Sq % 8 != 0 or Sk % 8 != 0:
        raise ValueError("seq len must be multiple of 8")
    if Sq % 128 != 0 and Sq > 1024:
        raise ValueError("long Sq must be a multiple of 128")
    if Sk % 128 != 0 and Sk > 1024:
        raise ValueError("long Sk must be a multiple of 128")


def _to_bhsd(x):
    return jnp.swapaxes(x, 1, 2).reshape(x.shape[0] * x.shape[2],
                                         x.shape[1], x.shape[3])


def _from_bhsd(out, B, H, Sq, D):
    return jnp.swapaxes(out.reshape(B, H, Sq, D), 1, 2)


def flash_attention_bshd(q, k, v, causal=False, sm_scale=None):
    """q,k,v: (B, S, H, D) -> out (B, Sq, H, D)."""
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    check_supported(tuple(q.shape), tuple(k.shape), q.dtype)
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(D)
    block_q = _pick_block_q(Sq)
    block_k = _pick_block_k(Sk)
    qf, kf, vf = _to_bhsd(q), _to_bhsd(k), _to_bhsd(v)
    from .autotune import autotune_enabled, lookup
    sig = (B * H, Sq, Sk, D, str(q.dtype), bool(causal))
    if autotune_enabled() and not _interpret_mode() \
            and not isinstance(q, jax.core.Tracer):
        # eager concrete inputs on real TPU: search the legal block grid
        # once per (shape, device) and reuse the cached winner
        from .autotune import attention_block_candidates, autotune

        def run(cfg):
            bq, bk = int(cfg["block_q"]), int(cfg["block_k"])
            return (lambda a, b, c: _flash_core(
                a, b, c, float(sm_scale), bool(causal), bq, bk),
                (qf, kf, vf))

        best = autotune("flash_fwd", sig,
                        attention_block_candidates(Sq, Sk), run,
                        default={"block_q": block_q, "block_k": block_k})
        block_q, block_k = best["block_q"], best["block_k"]
    elif autotune_enabled():
        # trace time (jitted models) with the flag on: shapes are
        # static, so a previously persisted winner still applies.
        # Gated on the flag — with autotune off, heuristics stand (a
        # stale cache must not silently override retuned defaults).
        hit = lookup("flash_fwd", sig)
        if hit is not None:
            block_q, block_k = int(hit["block_q"]), int(hit["block_k"])
    out = _flash_core(qf, kf, vf, float(sm_scale),
                      bool(causal), int(block_q), int(block_k))
    return _from_bhsd(out, B, H, Sq, D)


def _positions_in_segments(seg):
    """Per-token position within its (contiguous) segment: (B, S) -> (B, S).
    pos[p] = p - start_of_segment(p), via a cumulative max over boundary
    indices."""
    B, S = seg.shape
    p = jnp.arange(S, dtype=jnp.int32)[None, :]
    boundary = jnp.where(seg != jnp.roll(seg, 1, axis=1), p, 0)
    boundary = boundary.at[:, 0].set(0)
    start = jax.lax.cummax(boundary, axis=1)
    return p - start


def flash_attention_varlen_bshd(q, k, v, q_segment_ids, kv_segment_ids,
                                causal=False, sm_scale=None,
                                q_positions=None, kv_positions=None):
    """Varlen (packed) flash attention via per-token segment ids.

    q,k,v: (B, S, H, D); segment ids: (B, Sq)/(B, Sk) int32 — tokens attend
    only within their segment (the cu_seqlens formulation of the reference's
    flash_attn_unpadded packs sequences along S; nn.functional converts
    cu_seqlens to segment ids). K/V blocks with no segment overlap are
    skipped.

    Causal masking is PER-SEQUENCE: key position-within-sequence <= query
    position-within-sequence (positions derived from the segment ids, or
    passed explicitly via q_positions/kv_positions — flash_attn_unpadded
    adjusts q positions by the per-sequence k/q length difference for
    cross-attention packing)."""
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    check_supported(tuple(q.shape), tuple(k.shape), q.dtype)
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(D)
    block_q = _pick_block_q(Sq)
    block_k = _pick_block_k(Sk)
    ids_q = q_segment_ids.astype(jnp.int32).reshape(B, Sq)
    ids_k = kv_segment_ids.astype(jnp.int32).reshape(B, Sk)
    if causal:
        pos_q = (q_positions.astype(jnp.int32).reshape(B, Sq)
                 if q_positions is not None else _positions_in_segments(ids_q))
        pos_k = (kv_positions.astype(jnp.int32).reshape(B, Sk)
                 if kv_positions is not None
                 else _positions_in_segments(ids_k))
    else:
        pos_q = jnp.zeros((B, Sq), jnp.int32)
        pos_k = jnp.zeros((B, Sk), jnp.int32)
    segq = jnp.stack([ids_q, pos_q], axis=1)       # (B, 2, Sq)
    segk = jnp.stack([ids_k, pos_k], axis=1)
    out = _flash_core_seg(_to_bhsd(q), _to_bhsd(k), _to_bhsd(v), segq, segk,
                          float(sm_scale), bool(causal), int(block_q),
                          int(block_k), int(H))
    return _from_bhsd(out, B, H, Sq, D)


def flashmask_attention_bshd(q, k, v, startend_row_indices, causal=True,
                             sm_scale=None):
    """Block-sparse flashmask attention (parity: flashmask_attention:1098).

    startend_row_indices: (B, 1|H, Sk, C) int32 with C in {1, 2} (causal)
    or {2, 4} (non-causal) — per-key-column masked row ranges. The mask is
    reconstructed tile-by-tile inside the kernel from O(S*C) bounds; no
    dense (B, H, Sq, Sk) tensor is ever built."""
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    check_supported(tuple(q.shape), tuple(k.shape), q.dtype)
    if Sq != Sk:
        # bounds are query-row indices in a square score matrix; the XLA
        # fallback defines the same frame, so reject rectangles identically
        raise ValueError("flashmask requires Sq == Sk")
    Hm = startend_row_indices.shape[1]
    C = startend_row_indices.shape[3]
    if Hm not in (1, H):
        raise ValueError(f"flashmask heads dim {Hm} must be 1 or {H}")
    if causal and C not in (1, 2):
        raise ValueError("causal flashmask needs 1 or 2 bound columns")
    if not causal and C not in (2, 4):
        raise ValueError("non-causal flashmask needs 2 or 4 bound columns")
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(D)
    block_q = _pick_block_q(Sq)
    block_k = _pick_block_k(Sk)
    # (B, Hm, Sk, C) -> (B*Hm, C, Sk)
    fm = jnp.swapaxes(startend_row_indices.astype(jnp.int32), 2, 3)
    fm = fm.reshape(B * Hm, C, Sk)
    out = _flash_core_fm(_to_bhsd(q), _to_bhsd(k), _to_bhsd(v), fm,
                         float(sm_scale), bool(causal), int(block_q),
                         int(block_k), bool(causal), int(H))
    return _from_bhsd(out, B, H, Sq, D)
