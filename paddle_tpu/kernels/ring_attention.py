"""Ring flash attention — context parallelism over a mesh axis.

Capability-parity-plus: the reference has no in-core ring attention (see
SURVEY.md §5 — its long-context story is Megatron-SP along TP
(`fleet/utils/sequence_parallel_utils.py`) and the `sep` topology axis
(`fleet/base/topology.py:70-90`, alltoall segment parallel); ring/blockwise
lives outside core in recipe repos). Here it is first-class and TPU-native:
K/V shards rotate around the `sep` ring with `lax.ppermute` (ICI neighbor
exchange), each hop's partial attention runs the Pallas flash kernel, and
partials merge with the standard log-sum-exp combine. The backward pass
rotates the (q, do, o, lse, dq) bundle the opposite way so dK/dV accumulate
at the K/V owner and dQ arrives home after a full loop — one ring, no
gather of the full sequence anywhere.

Causal masking is resolved at *block* granularity statically: at ring step
j, the visiting K/V block's owner is `(idx - j) mod P`, so each device picks
one of {full, diagonal, empty} via `lax.switch` — the Pallas kernels only
ever see static `causal` flags (empty blocks skip compute entirely, giving
the ~2x causal speedup ring attention is known for).

All shapes below are per-shard (inside `shard_map`): sequence length S is
the LOCAL sequence chunk.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from ..jax_compat import axis_size as _axis_size

from .flash_attention import _bwd_with_delta as _flash_step_bwd
from .flash_attention import _fwd as _flash_step_fwd
from .flash_attention import _pick_block_k, _pick_block_q, check_supported

__all__ = ["ring_flash_attention", "ulysses_attention"]


def _repeat_kv(x, rep):
    """(B*Hkv, S, D) -> (B*Hkv*rep, S, D) by repeating each head `rep`x."""
    if rep == 1:
        return x
    BH, S, D = x.shape
    return jnp.broadcast_to(x[:, None], (BH, rep, S, D)).reshape(BH * rep, S, D)


def _sum_over_rep(x, rep):
    """Inverse of _repeat_kv for gradients: sum the `rep` copies."""
    if rep == 1:
        return x
    BHr, S, D = x.shape
    return x.reshape(BHr // rep, rep, S, D).sum(axis=1)


def _combine(o_acc, l_acc, o_j, lse_j):
    """Merge a new attention partial (o_j, lse_j) into the running combined
    (o_acc f32, l_acc f32) using out = sum_j exp(lse_j - L) * o_j."""
    l_new = jnp.logaddexp(l_acc, lse_j)
    # guard exp(-inf - -inf) = nan when nothing has been visible yet
    w_prev = jnp.where(jnp.isneginf(l_new), 0.0, jnp.exp(l_acc - l_new))
    w_j = jnp.where(jnp.isneginf(l_new), 0.0, jnp.exp(lse_j - l_new))
    o_new = o_acc * w_prev[..., None] + o_j.astype(jnp.float32) * w_j[..., None]
    return o_new, l_new


def _ring_fwd(q, k, v, sm_scale, causal, axis_name, rep, block_q, block_k):
    """q: (B*H, S, D); k, v: (B*Hkv, S, D) local shards. Returns
    (out (B*H,S,D) in q.dtype, lse (B*H,S) f32)."""
    P_ = _axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % P_) for i in range(P_)]
    BH, S, D = q.shape

    o_acc = jnp.zeros((BH, S, D), jnp.float32)
    l_acc = jnp.full((BH, S), -jnp.inf, jnp.float32)
    kj, vj = k, v

    def step_full(q, kj, vj):
        o, lse = _flash_step_fwd(q, _repeat_kv(kj, rep), _repeat_kv(vj, rep),
                                 sm_scale, False, block_q, block_k)
        return o, lse

    def step_diag(q, kj, vj):
        o, lse = _flash_step_fwd(q, _repeat_kv(kj, rep), _repeat_kv(vj, rep),
                                 sm_scale, True, block_q, block_k)
        return o, lse

    def step_empty(q, kj, vj):
        return (jnp.zeros_like(q),
                jnp.full((BH, S), -jnp.inf, jnp.float32))

    for j in range(P_):
        if causal:
            src = (idx - j) % P_
            # keys from src visible to queries at idx: src<idx full,
            # src==idx diagonal, src>idx nothing
            rel = jnp.where(src == idx, 1, jnp.where(src < idx, 0, 2))
            o_j, lse_j = lax.switch(rel, [step_full, step_diag, step_empty],
                                    q, kj, vj)
        else:
            o_j, lse_j = step_full(q, kj, vj)
        o_acc, l_acc = _combine(o_acc, l_acc, o_j, lse_j)
        if j != P_ - 1:
            kj = lax.ppermute(kj, axis_name, perm)
            vj = lax.ppermute(vj, axis_name, perm)
    return o_acc.astype(q.dtype), l_acc


def _ring_bwd_loop(q, k, v, out, lse, dout, sm_scale, causal, axis_name, rep,
                   block_q, block_k):
    """Rotate the (q, do, delta, lse, dq) bundle around the ring; accumulate
    dk/dv at the local K/V owner; dq returns home after P hops. delta is
    precomputed at the query owner so the full output never travels."""
    P_ = _axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % P_) for i in range(P_)]
    BH, S, D = q.shape
    k_rep = _repeat_kv(k, rep)
    v_rep = _repeat_kv(v, rep)
    delta = jnp.sum(dout.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)

    dk_acc = jnp.zeros(k_rep.shape, jnp.float32)
    dv_acc = jnp.zeros(v_rep.shape, jnp.float32)

    def step_full(qv, deltav, dov, lsev):
        return _flash_step_bwd(sm_scale, False, block_q, block_k,
                               qv, k_rep, v_rep, deltav, lsev, dov)

    def step_diag(qv, deltav, dov, lsev):
        return _flash_step_bwd(sm_scale, True, block_q, block_k,
                               qv, k_rep, v_rep, deltav, lsev, dov)

    def step_empty(qv, deltav, dov, lsev):
        return (jnp.zeros_like(qv), jnp.zeros_like(k_rep),
                jnp.zeros_like(v_rep))

    bundle = (q, dout, delta, lse, jnp.zeros((BH, S, D), jnp.float32))
    for j in range(P_):
        qv, dov, deltav, lsev, dq_acc = bundle
        if causal:
            src_q = (idx - j) % P_   # owner of the visiting queries
            # local keys at idx visible to visiting queries from src_q:
            # idx<src_q full, idx==src_q diagonal, idx>src_q nothing
            rel = jnp.where(idx == src_q, 1, jnp.where(idx < src_q, 0, 2))
            dq_j, dk_j, dv_j = lax.switch(
                rel, [step_full, step_diag, step_empty], qv, deltav, dov,
                lsev)
        else:
            dq_j, dk_j, dv_j = step_full(qv, deltav, dov, lsev)
        dk_acc = dk_acc + dk_j.astype(jnp.float32)
        dv_acc = dv_acc + dv_j.astype(jnp.float32)
        bundle = (qv, dov, deltav, lsev, dq_acc + dq_j.astype(jnp.float32))
        bundle = jax.tree.map(lambda t: lax.ppermute(t, axis_name, perm),
                              bundle)
    dq = bundle[4]
    dk = _sum_over_rep(dk_acc, rep)
    dv = _sum_over_rep(dv_acc, rep)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _ring_core(q, k, v, sm_scale, causal, axis_name, rep, block_q, block_k):
    out, _ = _ring_fwd(q, k, v, sm_scale, causal, axis_name, rep,
                       block_q, block_k)
    return out


def _ring_core_fwd(q, k, v, sm_scale, causal, axis_name, rep, block_q,
                   block_k):
    out, lse = _ring_fwd(q, k, v, sm_scale, causal, axis_name, rep,
                         block_q, block_k)
    return out, (q, k, v, out, lse)


def _ring_core_bwd(sm_scale, causal, axis_name, rep, block_q, block_k, res,
                   dout):
    q, k, v, out, lse = res
    return _ring_bwd_loop(q, k, v, out, lse, dout, sm_scale, causal,
                          axis_name, rep, block_q, block_k)


_ring_core.defvjp(_ring_core_fwd, _ring_core_bwd)


def ring_flash_attention(q, k, v, axis_name="sep", causal=True, sm_scale=None):
    """Ring flash attention over mesh axis `axis_name` (call inside
    shard_map with q/k/v sequence-sharded on that axis).

    q: (B, S_local, H, D); k, v: (B, S_local, Hkv, D) with H % Hkv == 0.
    Global sequence order is the axis order: device i holds tokens
    [i*S_local, (i+1)*S_local). Returns (B, S_local, H, D).
    """
    B, S, H, D = q.shape
    Hkv = k.shape[2]
    if H % Hkv != 0:
        raise ValueError(f"H={H} not a multiple of Hkv={Hkv}")
    rep = H // Hkv
    check_supported((B, S, H, D), (B, S, H, D), q.dtype)
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(D)
    block_q = _pick_block_q(S)
    block_k = _pick_block_k(S)

    def to_flat(x):
        return jnp.swapaxes(x, 1, 2).reshape(x.shape[0] * x.shape[2],
                                             x.shape[1], x.shape[3])

    out = _ring_core(to_flat(q), to_flat(k), to_flat(v), float(sm_scale),
                     bool(causal), axis_name, int(rep), int(block_q),
                     int(block_k))
    return jnp.swapaxes(out.reshape(B, H, S, D), 1, 2)


def _local_attention(q, k, v, causal, sm_scale):
    """Single-device (B,S,H,D) attention: Pallas flash when shapes allow,
    else a jnp composition with fp32 softmax."""
    rep = q.shape[2] // k.shape[2]
    if rep > 1:
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    try:
        from .flash_attention import flash_attention_bshd
        check_supported(tuple(q.shape), tuple(k.shape), q.dtype)
        return flash_attention_bshd(q, k, v, causal=causal, sm_scale=sm_scale)
    except ValueError:
        pass
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * sm_scale
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        cm = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        s = jnp.where(cm, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def ulysses_attention(q, k, v, axis_name="sep", causal=True, sm_scale=None,
                      attn_fn=None):
    """DeepSpeed-Ulysses segment parallelism: all_to_all trades the
    sequence shard for a head shard, attention runs over the full sequence
    with H/P local heads, and a second all_to_all restores seq sharding.

    Parity: the reference's `sep` axis alltoall segment parallel
    (`fleet/meta_parallel/segment_parallel.py:26` + fused attention recipes).
    q: (B, S_local, H, D), k/v: (B, S_local, Hkv, D); H must be divisible by
    the axis size (Hkv is head-repeated if needed). Differentiable through
    all_to_all — no custom vjp required.
    """
    P_ = _axis_size(axis_name)
    B, S, H, D = q.shape
    Hkv = k.shape[2]
    if H % Hkv != 0:
        raise ValueError(f"H={H} not a multiple of Hkv={Hkv}")
    if H % P_ != 0:
        raise ValueError(f"H={H} not divisible by sep={P_}")
    if Hkv % P_ != 0:
        rep = P_ // math.gcd(P_, Hkv)
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)

    def seq_to_head(x):
        # (B, S/P, H, D) -> (B, S, H/P, D)
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    qf, kf, vf = seq_to_head(q), seq_to_head(k), seq_to_head(v)
    if attn_fn is None:
        out = _local_attention(qf, kf, vf, causal, sm_scale)
    else:
        out = attn_fn(qf, kf, vf, causal=causal, sm_scale=sm_scale)
    # (B, S, H/P, D) -> (B, S/P, H, D)
    return lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)
