"""Kernel autotune: block-size search + persistent cache.

Parity: reference `paddle/phi/kernels/autotune/` — `AutoTuneCache`
(cache.h: per-algo hashmaps keyed by shapes), `SwitchAutoTune`
(switch_autotune.h: tune for N steps then freeze), used for conv algos /
transpose tiling.

TPU-native: the tunable is the Pallas block geometry (block_q/block_k for
the attention kernels, block m/k/n for matmuls). `autotune()` times each
candidate on the live device, keeps the winner in a process cache, and
persists it as JSON keyed by (kernel, shape-signature, device kind) so
later processes skip the search. Off-TPU (interpret mode) the search is
skipped and heuristics stand."""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

__all__ = ["AutoTuneCache", "autotune", "lookup", "set_autotune_enabled",
           "autotune_enabled", "attention_block_candidates"]

from ..utils.flags import define_flag, flags

define_flag("use_autotune", False,
            "search Pallas block geometries at first use and cache winners")


def set_autotune_enabled(on: bool):
    """Parity: FLAGS_use_autotune / SwitchAutoTune (also settable via
    paddle.set_flags({'FLAGS_use_autotune': True}))."""
    from ..utils.flags import set_flags
    set_flags({"FLAGS_use_autotune": bool(on)})


def autotune_enabled() -> bool:
    return bool(flags("use_autotune", False))


class AutoTuneCache:
    """Process-wide winner cache with optional JSON persistence
    (parity: autotune/cache.h AutoTuneCache singleton)."""

    _instance = None
    _lock = threading.Lock()

    def __init__(self, path: Optional[str] = None):
        self._path = path or os.environ.get(
            "PADDLE_AUTOTUNE_CACHE", os.path.expanduser(
                "~/.cache/paddle_tpu_autotune.json"))
        self._mem: Dict[str, dict] = {}
        self._loaded = False
        self.hits = 0
        self.misses = 0

    @classmethod
    def instance(cls) -> "AutoTuneCache":
        with cls._lock:
            if cls._instance is None:
                cls._instance = AutoTuneCache()
            return cls._instance

    def _load(self):
        if self._loaded:
            return
        self._loaded = True
        try:
            with open(self._path) as f:
                self._mem.update(json.load(f))
        except Exception:
            pass

    def _save(self):
        try:
            os.makedirs(os.path.dirname(self._path), exist_ok=True)
            with open(self._path, "w") as f:
                json.dump(self._mem, f)
        except Exception:
            pass

    def get(self, key: str):
        self._load()
        got = self._mem.get(key)
        if got is None:
            self.misses += 1
        else:
            self.hits += 1
        return got

    def put(self, key: str, value: dict, persist=True):
        self._load()
        self._mem[key] = value
        if persist:
            self._save()

    def clear(self):
        self._mem.clear()
        self.hits = self.misses = 0


def _device_kind():
    import jax
    try:
        return jax.devices()[0].device_kind
    except Exception:
        return "cpu"


def lookup(kernel_name: str, shape_sig: Tuple) -> Optional[dict]:
    """Cached winner for (kernel, shape, device) or None.

    Pure host logic on static shapes — safe to call at TRACE time, so
    jitted models pick up winners a previous eager search persisted
    (the search itself cannot run under tracing)."""
    cache = AutoTuneCache.instance()
    key = json.dumps([kernel_name, list(shape_sig), _device_kind()])
    return cache.get(key)


def autotune(kernel_name: str, shape_sig: Tuple, candidates: List[dict],
             run_fn: Callable[[dict], Callable], warmup: int = 1,
             iters: int = 8, default: Optional[dict] = None):
    """Pick the fastest candidate config.

    run_fn(cfg) returns either a zero-arg callable (legacy; timed with
    host-fetch sync per call — coarse over the relay transport; runs
    max(1, warmup) un-timed calls first) or an (fn, args) tuple, timed
    with kernels/timing.py::device_time (the relay-proof path:
    device-side loop, fetch sync, 2N-N differencing; compiles are its
    warmup). Returns the best cfg, cached by (kernel, shape, device
    kind); if every candidate fails/can't be resolved, returns
    `default` when given (NOT cached) instead of raising."""
    cache = AutoTuneCache.instance()
    key = json.dumps([kernel_name, list(shape_sig), _device_kind()])
    hit = cache.get(key)
    if hit is not None:
        return hit
    if not candidates:
        raise ValueError("no candidates")
    from .timing import device_time
    import numpy as _np
    best_cfg, best_t = None, float("inf")
    for cfg in candidates:
        try:
            timed = run_fn(cfg)
            if isinstance(timed, tuple):
                fn, args = timed
                dt = device_time(fn, *args, iters=iters)
                if dt != dt:        # NaN: unresolvable — skip honestly
                    continue
            else:
                # legacy zero-arg form: fetch-sync each call
                # (block_until_ready does not block over the relay)
                for _ in range(max(1, warmup)):
                    _np.asarray(timed()).ravel()[:1]
                t0 = time.perf_counter()
                for _ in range(iters):
                    out = timed()
                _np.asarray(out).ravel()[:1]
                dt = (time.perf_counter() - t0) / iters
        except Exception:
            continue  # illegal tiling for this shape: skip the candidate
        if dt < best_t:
            best_cfg, best_t = cfg, dt
    if best_cfg is None:
        if default is not None:
            return dict(default)     # not cached: a later window can tune
        raise RuntimeError(f"all {len(candidates)} candidates failed for "
                           f"{kernel_name} {shape_sig}")
    best = dict(best_cfg)
    best["_time_s"] = best_t
    cache.put(key, best)
    return best


def attention_block_candidates(sq: int, sk: int) -> List[dict]:
    """Legal (block_q, block_k) grid for the flash kernels: full axis or a
    128-multiple divisor (the Mosaic tiling rule _pick_block enforces)."""
    def options(n):
        opts = {n}
        if n % 128 == 0:
            for b in (128, 256, 512, 1024):
                if b <= n and n % b == 0:
                    opts.add(b)
        return sorted(opts)

    return [{"block_q": bq, "block_k": bk}
            for bq in options(sq) for bk in options(sk)]
