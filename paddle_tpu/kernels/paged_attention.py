"""Pallas paged-KV-cache decode attention (TPU).

Capability parity: the reference serving kernel pack —
`block_multi_head_attention` (paged KV cache,
`paddle/phi/kernels/fusion/gpu/block_multi_head_attention.cu` via
`python/paddle/incubate/nn/functional/block_multihead_attention.py`) and
`masked_multihead_attention` (decode MHA,
`paddle/phi/kernels/fusion/gpu/masked_multihead_attention_kernel.cu`).
Rebuilt as a native Pallas TPU kernel over a TPU-friendly page layout
rather than a CUDA translation.

Design:
  * the KV cache lives in HBM as (num_pages, KVH, page_size, D) — page
    major, so one page (all kv heads' slices for page_size tokens) is a
    single contiguous DMA; pages are assigned to sequences through an
    int32 block table;
  * decode query (B, H, D) is viewed as (B, KVH, G, D) with G = H//KVH
    grouped-query heads sharing one KV head;
  * grid (B, max_pages) with the page dimension innermost: the block
    table and per-sequence lengths ride scalar prefetch, the page index
    map gathers `block_tables[b, i]` so Pallas streams exactly the
    pages this sequence owns (double-buffered HBM->VMEM), one whole
    page (all kv heads) per step;
  * online softmax over pages with (G, 128) lane-broadcast running
    stats; pages past ceil(len/page_size) skip all compute via pl.when;
  * positions >= seq_len inside the last page are masked in-block.

The kernel is bandwidth-bound (one pass over the live KV), which is the
same regime the reference's CUDA kernel targets; MXU utilisation is
irrelevant at decode G sizes.

Quantized KV pages (ISSUE 6): the cache may instead hold int8 values
with fp32 scales at PER-(slot, kv-head) granularity, stored page-major
in (num_pages, KVH, page_size) arrays addressed by the SAME page ids as
the values — so `BlockAllocator`/`RadixCache`/CoW-fork/truncate stay
byte-level and dtype-agnostic (a page id names a value page AND its
scale rows). Per-slot scales are the only granularity compatible with
quantize-ON-WRITE: a true per-page scale would need to re-quantize the
page's earlier tokens whenever a later token raised the absmax. Writes
quantize (absmax over D per token per head, symmetric, qmax 127);
the decode kernel and the gathered-prefix read paths dequantize in
fp32 before the softmax math, so accuracy loss is bounded by the
round-to-nearest step scale/2 (<= absmax/254 per element; the
quantize->dequantize bound test pins it). Capacity: a page costs
2*KVH*page*(D*width + 4) bytes (K+V + scales), so int8 halves the
payload exactly and the page count at fixed pool bytes grows by
2D/(D+4) (1.94x at D=128) — `paged_page_bytes` is the single source
for that math (engine, bench_ops and the capacity test all use it).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..jax_compat import patch_pltpu
from .flash_attention import _interpret_mode

patch_pltpu()

__all__ = ["paged_attention_decode", "paged_attention_decode_tp",
           "paged_cache_write",
           "paged_cache_write_range", "paged_cache_write_span",
           "alloc_paged_cache", "check_supported_paged", "paged_blockspecs",
           "quantize_kv", "paged_page_bytes", "KV_SCALE_DTYPE"]

NEG_INF = np.float32(-1e30)
_STATS_LANES = 128
_I0 = np.int32(0)
# int8 KV quantization constants: symmetric, qmax 127 (same convention
# as nn.quant.weight_quantize so the rel-err budgets compose), scales
# kept fp32 — the scale multiply happens in the kernel's fp32 softmax
# math anyway, and a bf16 scale would add ~0.4% relative error on top
# of the ~0.8% round-to-nearest step for a 2-bytes/slot-head saving.
KV_QMAX = np.float32(127.0)
KV_SCALE_DTYPE = jnp.float32


def quantize_kv(x):
    """Per-(token, head) symmetric int8 quantization over the head dim.

    x (..., D) float -> (int8 values (..., D), fp32 scales (...,)).
    dequant(q, s) = q * s reproduces x within scale/2 per element
    (absmax/254 — the bound tests/test_serving_quant_kv.py pins)."""
    xf = x.astype(jnp.float32)
    absmax = jnp.maximum(jnp.max(jnp.abs(xf), axis=-1), 1e-10)
    scale = absmax / KV_QMAX
    q = jnp.clip(jnp.round(xf / scale[..., None]), -KV_QMAX, KV_QMAX)
    return q.astype(jnp.int8), scale.astype(KV_SCALE_DTYPE)


def paged_page_bytes(num_kv_heads, page_size, head_dim, kv_dtype=None):
    """HBM bytes one page costs: K + V payload (+ per-slot fp32 scales
    for int8). The single source for the capacity math quoted in
    SERVING.md — the engine's kv_pool_bytes sizing, bench_ops'
    bytes/token rows and the doubling test all call this."""
    if kv_dtype in (None, "bf16", "bfloat16", "float16"):
        width, scale_b = 2, 0
    elif kv_dtype in ("float32", "fp32"):
        width, scale_b = 4, 0
    elif kv_dtype == "int8":
        width, scale_b = 1, 4
    else:
        raise ValueError(f"unknown kv_dtype {kv_dtype!r}")
    return 2 * num_kv_heads * page_size * (head_dim * width + scale_b)


def _decode_kernel(bt_ref, sl_ref, q_ref, *rest_refs, sm_scale, page_size,
                   nsteps, kvh, fold, quantized=False):
    """Grid (B, nsteps); one step streams `fold` gathered pages for ALL
    kv heads. Folding matters: with one 16-token page per step the DMAs
    are 64 KB and per-step overhead dominates (measured 78 GB/s on v5e;
    401 GB/s once ~128 tokens move per step), so small serving pages
    are batched until a step carries >= ~128 tokens' worth of KV.

    quantized=True streams int8 value pages plus their fp32 per-slot
    scale pages (same gathered page ids) and dequantizes on the VMEM
    side — K/V bytes moved drop ~2x, which is the whole win in this
    bandwidth-bound regime."""
    k_refs = rest_refs[:fold]
    v_refs = rest_refs[fold:2 * fold]
    if quantized:
        ks_refs = rest_refs[2 * fold:3 * fold]
        vs_refs = rest_refs[3 * fold:4 * fold]
        o_ref, acc_ref, m_ref, l_ref = rest_refs[4 * fold:]
    else:
        o_ref, acc_ref, m_ref, l_ref = rest_refs[2 * fold:]
    sm_scale = np.float32(sm_scale)
    b = pl.program_id(0)
    i = pl.program_id(1)
    sl = sl_ref[b]

    @pl.when(i == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    @pl.when(i * fold * page_size < sl)
    def _step():
        for f in range(fold):                          # static unroll
            for h in range(kvh):                       # static unroll
                q = q_ref[0, h].astype(jnp.float32)    # (G, D)
                k = k_refs[f][0, h].astype(jnp.float32)  # (page, D)
                v = v_refs[f][0, h].astype(jnp.float32)
                if quantized:
                    k = k * ks_refs[f][0, h][:, None]  # fp32 dequant
                    v = v * vs_refs[f][0, h][:, None]
                s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                        preferred_element_type=jnp.float32)
                s = s * sm_scale                       # (G, page)
                G, P = s.shape
                pos = ((i * fold + f) * page_size
                       + jax.lax.broadcasted_iota(jnp.int32, (G, P), 1))
                s = jnp.where(pos < sl, s, NEG_INF)
                m_prev = m_ref[h, :, :1]
                l_prev = l_ref[h, :, :1]
                m_cur = jnp.max(s, axis=1, keepdims=True)
                m_new = jnp.maximum(m_prev, m_cur)
                p = jnp.where(s <= NEG_INF / 2, 0.0, jnp.exp(s - m_new))
                alpha = jnp.where(m_prev <= NEG_INF / 2, 0.0,
                                  jnp.exp(m_prev - m_new))
                l_ref[h] = jnp.broadcast_to(
                    l_prev * alpha + jnp.sum(p, axis=1, keepdims=True),
                    l_ref.shape[1:])
                m_ref[h] = jnp.broadcast_to(m_new, m_ref.shape[1:])
                acc_ref[h] = acc_ref[h] * alpha + jax.lax.dot_general(
                    p, v, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)

    @pl.when(i == nsteps - 1)
    def _finalize():
        for h in range(kvh):
            l = jnp.maximum(l_ref[h, :, :1], np.float32(1e-30))
            o_ref[0, h] = (acc_ref[h] / l).astype(o_ref.dtype)


def check_supported_paged(q_shape, cache_shape, dtype, kv_dtype=None):
    """Static shape validation mirroring what Mosaic will accept — raise
    here (with a clear message) instead of deep inside lowering. Same
    role as flash_attention.check_supported; the legality test suite
    (tests/test_paged_blockspec_legality.py) sweeps this + the exact
    BlockSpecs below, because interpret=True on CPU hides all Mosaic
    tiling violations (round-1 lesson).

    `dtype` is the QUERY/compute dtype (always bf16/f32); `kv_dtype`
    optionally names a quantized cache storage ("int8" — per-slot-scale
    pages, legal because the value-page block spans the full page/head
    dims and int8's (32, 128) min tile only binds strict sub-blocks)."""
    B, H, D = q_shape
    num_pages, KVH, page_size, Dc = cache_shape
    if D != Dc:
        raise ValueError(f"q head_dim {D} != cache head_dim {Dc}")
    if H % KVH != 0:
        raise ValueError(f"H={H} not a multiple of KVH={KVH}")
    if D % 64 != 0 or D > 256:
        raise ValueError(f"head_dim {D} unsupported (need multiple of 64, "
                         "<= 256)")
    if page_size % 8 != 0:
        raise ValueError(f"page_size {page_size} must be a multiple of 8 "
                         "(sublane tiling)")
    if str(dtype) not in ("bfloat16", "float32"):
        # float16 is deliberately rejected: bf16/f32 are the TPU's native
        # compute dtypes; Mosaic fp16 support is not something we can
        # rely on unvalidated (ADVICE r3 asked to confirm on-chip — still
        # pending a live relay; loosen only after a real-chip run passes)
        raise ValueError(f"unsupported dtype {dtype} (TPU-native kernels "
                         "accept bfloat16/float32)")
    if kv_dtype not in (None, "int8"):
        raise ValueError(f"unsupported kv_dtype {kv_dtype!r} (None for "
                         "the compute dtype, or 'int8' per-slot-scale "
                         "pages)")


def _fold_pages(page_size, max_pages, fold_tokens=None):
    """Pages batched per grid step: max(128 tokens, 2 pages), clamped to
    the table width. Single source of truth for the kernel AND the
    static legality enumeration (they drifted once — don't re-fork)."""
    if fold_tokens is None:
        fold_tokens = max(128, 2 * page_size)
    return max(1, min(fold_tokens // page_size, max_pages))


def paged_blockspecs(B, H, KVH, D, page_size, num_pages, max_pages=None,
                     fold_tokens=None, quantized=False):
    """The exact (block_shape, array_shape) pairs the pallas_call below
    constructs — including the `fold` repetition of the k/v page specs
    the folded grid uses — plus the VMEM scratch shapes; enumerable for
    the static legality test without running the kernel. quantized=True
    appends the fp32 scale-page specs ((1, KVH, page_size) blocks over
    (num_pages, KVH, page_size) arrays — legal because both trailing
    block dims equal the array dims) the int8 path adds."""
    G = H // KVH
    if max_pages is None:
        max_pages = num_pages
    fold = _fold_pages(page_size, max_pages, fold_tokens)
    page = ((1, KVH, page_size, D), (num_pages, KVH, page_size, D))
    scale = ((1, KVH, page_size), (num_pages, KVH, page_size))
    specs = (
        [((1, KVH, G, D), (B, KVH, G, D))]                # q block
        + [page] * fold                                   # k pages
        + [page] * fold                                   # v pages
        + ([scale] * (2 * fold) if quantized else [])     # k/v scale pages
        + [((1, KVH, G, D), (B, KVH, G, D))]              # out block
    )
    scratch = [(KVH, G, D), (KVH, G, _STATS_LANES), (KVH, G, _STATS_LANES)]
    return specs, scratch


def paged_attention_decode(q, k_cache, v_cache, block_tables, seq_lens,
                           sm_scale=None, fold_tokens=None,
                           k_scale=None, v_scale=None):
    """One decode step of attention over a paged KV cache.

    q:            (B, H, D) — current-step queries.
    k/v_cache:    (num_pages, KVH, page_size, D).
    block_tables: (B, max_pages) int32 — page ids per sequence, position
                  j holds the page covering tokens [j*page_size,
                  (j+1)*page_size); unused slots must hold a valid page
                  id (0 is fine — masked out by seq_lens).
    seq_lens:     (B,) int32 — live tokens per sequence (including the
                  token being decoded).
    k/v_scale:    optional (num_pages, KVH, page_size) fp32 — per-slot
                  dequant scales for int8 caches (both or neither);
                  the kernel streams the scale pages alongside the
                  value pages and dequantizes in fp32.
    Returns (B, H, D).
    """
    B, H, D = q.shape
    num_pages, KVH, page_size, _ = k_cache.shape
    max_pages = block_tables.shape[1]
    quantized = k_scale is not None or v_scale is not None
    if quantized and (k_scale is None or v_scale is None):
        raise ValueError("pass both k_scale and v_scale or neither")
    if quantized and str(k_cache.dtype) != "int8":
        raise ValueError(f"scales given but cache dtype is "
                         f"{k_cache.dtype}, not int8")
    if not quantized and str(k_cache.dtype) == "int8":
        raise ValueError("int8 cache needs k_scale/v_scale")
    check_supported_paged(q.shape, k_cache.shape, q.dtype,
                          kv_dtype="int8" if quantized else None)
    G = H // KVH
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(D)
    qg = q.reshape(B, KVH, G, D)
    bt = block_tables.astype(jnp.int32)
    sl = seq_lens.astype(jnp.int32)

    # Fold pages so one grid step moves >= max(128 tokens, 2 pages) of
    # KV (swept on v5e at B16 KVH8 D128 S2048: 16-token steps ran at
    # 78 GB/s — DMA-latency-bound — vs 96/188/268 GB/s folded at
    # page 16/32/64, and 2-page folds at page 128 hit 472 GB/s vs 401
    # unfolded; folds deeper than this regressed every small-page
    # config). Pad the block table to a fold multiple; padded slots
    # reuse page 0 and are masked by seq_lens.
    fold = _fold_pages(page_size, max_pages, fold_tokens)
    if max_pages % fold != 0:
        pad = fold - max_pages % fold
        bt = jnp.pad(bt, ((0, 0), (0, pad)))
        max_pages += pad
    nsteps = max_pages // fold

    kernel = functools.partial(_decode_kernel, sm_scale=float(sm_scale),
                               page_size=page_size, nsteps=nsteps,
                               kvh=KVH, fold=fold, quantized=quantized)

    def page_spec(f):
        return pl.BlockSpec(
            (1, KVH, page_size, D),
            lambda b, i, bt, sl, f=f: (bt[b, i * fold + f],
                                       _I0, _I0, _I0))

    def scale_spec(f):
        # same gathered page id as the value page it scales
        return pl.BlockSpec(
            (1, KVH, page_size),
            lambda b, i, bt, sl, f=f: (bt[b, i * fold + f], _I0, _I0))

    scale_specs = ([scale_spec(f) for f in range(fold)] * 2
                   if quantized else [])
    scale_args = ([k_scale] * fold + [v_scale] * fold) if quantized else []
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, nsteps),
        in_specs=(
            [pl.BlockSpec((1, KVH, G, D),
                          lambda b, i, *_: (b, _I0, _I0, _I0))]
            + [page_spec(f) for f in range(fold)]      # k pages
            + [page_spec(f) for f in range(fold)]      # v pages
            + scale_specs                              # k/v scale pages
        ),
        out_specs=pl.BlockSpec((1, KVH, G, D),
                               lambda b, i, *_: (b, _I0, _I0, _I0)),
        scratch_shapes=[
            pltpu.VMEM((KVH, G, D), jnp.float32),
            pltpu.VMEM((KVH, G, _STATS_LANES), jnp.float32),
            pltpu.VMEM((KVH, G, _STATS_LANES), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, KVH, G, D), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=_interpret_mode(),
    )(bt, sl, qg, *([k_cache] * fold), *([v_cache] * fold), *scale_args)
    return out.reshape(B, H, D)


def paged_attention_decode_tp(q, k_cache, v_cache, block_tables, seq_lens,
                              mesh, axis="model", sm_scale=None,
                              fold_tokens=None, k_scale=None, v_scale=None,
                              manual=None):
    """Tensor-parallel decode attention: query heads and the KV pages'
    head dim sharded over mesh axis `axis` (ISSUE 8).

    Sharding layout — page IDS are global (the host-side
    BlockAllocator/RadixCache never see the mesh), page CONTENTS are
    head-sharded: q (B, H, D) splits H, the caches
    (num_pages, KVH, page, D) and int8 scale pages (num_pages, KVH,
    page) split KVH, block_tables/seq_lens are replicated. Each shard
    attends its own KVH/tp kv heads against its own H/tp query heads
    (G = H/KVH is shard-invariant), so NO collective is needed here —
    the psum lives in the row-parallel o_proj that consumes the output.

    Two lowerings, selected by `manual` (default: by backend):
    * manual=True (TPU default): shard_map manual on `axis` only — the
      partial-manual combination the pipeline already relies on
      (CLAUDE.md: traces only under jit); each shard runs the real
      Pallas kernel on its local head slice, so the kernel's measured
      GB/s applies per chip unchanged.
    * manual=False (CPU/test default): GSPMD sharding constraints
      around the plain kernel call — the interpret-mode kernel is
      ordinary traceable HLO, which this path partitions bit-exactly
      (tests/_env_probes.py::gspmd_tp_mesh probes it; the CPU backend
      rejects partial-manual shard_map outright, the same limitation
      the pipeline tests skip on).
    Both return (B, H, D) sharded on H over `axis`.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P
    B, H, D = q.shape
    KVH = k_cache.shape[1]
    tp = int(mesh.shape[axis])
    if H % tp:
        raise ValueError(f"H={H} not divisible by tp={tp}")
    if KVH % tp:
        raise ValueError(f"KVH={KVH} not divisible by tp={tp}")
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(D)
    if manual is None:
        manual = jax.default_backend() == "tpu"
    quantized = k_scale is not None

    def ns(spec):
        return NamedSharding(mesh, spec)

    q_spec = P(None, axis, None)
    page_spec = P(None, axis, None, None)
    scale_spec = P(None, axis, None)
    if not manual:
        cst = jax.lax.with_sharding_constraint
        q = cst(q, ns(q_spec))
        k_cache = cst(k_cache, ns(page_spec))
        v_cache = cst(v_cache, ns(page_spec))
        if quantized:
            k_scale = cst(k_scale, ns(scale_spec))
            v_scale = cst(v_scale, ns(scale_spec))
        out = paged_attention_decode(
            q, k_cache, v_cache, block_tables, seq_lens,
            sm_scale=sm_scale, fold_tokens=fold_tokens,
            k_scale=k_scale, v_scale=v_scale)
        return cst(out, ns(q_spec))

    try:
        from jax import shard_map
    except ImportError:
        from ..jax_compat import shard_map

    def local(qq, kc, vc, bt, sl, *scales):
        ks, vs = scales if scales else (None, None)
        return paged_attention_decode(
            qq, kc, vc, bt, sl, sm_scale=sm_scale,
            fold_tokens=fold_tokens, k_scale=ks, v_scale=vs)

    in_specs = (q_spec, page_spec, page_spec, P(), P())
    args = (q, k_cache, v_cache, block_tables, seq_lens)
    if quantized:
        in_specs = in_specs + (scale_spec, scale_spec)
        args = args + (k_scale, v_scale)
    f = shard_map(local, mesh=mesh, in_specs=in_specs, out_specs=q_spec,
                  axis_names={axis}, check_vma=False)
    return f(*args)


_SCALE_DNUMS = jax.lax.ScatterDimensionNumbers(
    update_window_dims=(),
    inserted_window_dims=(0, 1, 2),
    scatter_dims_to_operand_dims=(0, 1, 2))


def _scatter_scales(scale_buf, idx, scales):
    """Scatter per-(token, head) fp32 scales into the page-major scale
    array using the SAME (page, head, slot) indices as the value
    scatter — dead positions collide on page 0 exactly like the value
    writes (pad-page scale rows are never read un-masked)."""
    return jax.lax.scatter(
        scale_buf, idx, scales.reshape(-1).astype(scale_buf.dtype),
        _SCALE_DNUMS, indices_are_sorted=False, unique_indices=False)


def _maybe_quantize(k_cache, k_new, k_scale):
    """Route a write through quantize-on-write when the cache is int8.
    Returns (values to scatter, per-slot scales or None). Raises on a
    scale/dtype mismatch so a mis-threaded engine config fails loudly
    at trace time, not as silent garbage KV."""
    if k_scale is None:
        if str(k_cache.dtype) == "int8":
            raise ValueError("int8 cache write needs scale buffers")
        return k_new, None
    if str(k_cache.dtype) != "int8":
        raise ValueError(f"scale buffer given but cache dtype is "
                         f"{k_cache.dtype}, not int8")
    return quantize_kv(k_new)


def paged_cache_write_range(k_cache, v_cache, k_new, v_new, block_table,
                            length, start=0, k_scale=None, v_scale=None):
    """Scatter a prefill span's K/V (one sequence) into the paged cache.

    k_new/v_new:  (S, KVH, D) — keys/values for token positions
                  start..start+S-1 (S may exceed `length`: the tail is
                  prompt padding).
    block_table:  (max_pages,) int32 — the sequence's page ids; slot j
                  covers tokens [j*page_size, (j+1)*page_size).
    length:       () int32 — live tokens IN THIS SPAN; span positions
                  >= length are routed to page 0, the reserved pad page
                  the decode kernel never reads un-masked (same contract
                  as the padded block-table slots in
                  `paged_attention_decode`).
    start:        () int32 — absolute token position of k_new[0]
                  (chunked prefill writes a partial prompt at an
                  offset; whole-prompt callers keep the default 0).
    k/v_scale:    optional (num_pages, KVH, page_size) fp32 scale
                  arrays (int8 caches): the span is quantized on write
                  and its per-slot scales land at the same
                  (page, head, slot) addresses.
    Returns the updated (k_cache, v_cache) — plus (k_scale, v_scale)
    when scale buffers were passed.

    Serving prefill companion of `paged_cache_write`: one scatter moves
    a whole chunk instead of a token per step, so the engine's prefill
    program is a single fused write (the read path stays the Pallas
    kernel).
    """
    num_pages, KVH, page_size, D = k_cache.shape
    S = k_new.shape[0]
    k_new, k_sc = _maybe_quantize(k_cache, k_new, k_scale)
    v_new, v_sc = _maybe_quantize(v_cache, v_new, v_scale)
    t = jnp.arange(S, dtype=jnp.int32)
    live = t < jnp.asarray(length, jnp.int32)
    pos = t + jnp.asarray(start, jnp.int32)
    page_idx = jax.lax.div(pos, jnp.int32(page_size))
    page_off = jax.lax.rem(pos, jnp.int32(page_size))
    pages = jnp.where(live, block_table.astype(jnp.int32)[page_idx], 0)
    heads = jnp.arange(KVH, dtype=jnp.int32)
    idx = jnp.stack([
        jnp.broadcast_to(pages[:, None], (S, KVH)),
        jnp.broadcast_to(heads[None, :], (S, KVH)),
        jnp.broadcast_to(page_off[:, None], (S, KVH)),
    ], axis=-1)
    dnums = jax.lax.ScatterDimensionNumbers(
        update_window_dims=(1,),
        inserted_window_dims=(0, 1, 2),
        scatter_dims_to_operand_dims=(0, 1, 2))
    # padded positions collide on page 0 — duplicates allowed there (the
    # pad page's contents are never read un-masked)
    k_cache = jax.lax.scatter(
        k_cache, idx.reshape(S * KVH, 3),
        k_new.reshape(S * KVH, D).astype(k_cache.dtype), dnums,
        indices_are_sorted=False, unique_indices=False)
    v_cache = jax.lax.scatter(
        v_cache, idx.reshape(S * KVH, 3),
        v_new.reshape(S * KVH, D).astype(v_cache.dtype), dnums,
        indices_are_sorted=False, unique_indices=False)
    if k_sc is None:
        return k_cache, v_cache
    k_scale = _scatter_scales(k_scale, idx.reshape(S * KVH, 3), k_sc)
    v_scale = _scatter_scales(v_scale, idx.reshape(S * KVH, 3), v_sc)
    return k_cache, v_cache, k_scale, v_scale


def paged_cache_write_span(k_cache, v_cache, k_new, v_new, block_tables,
                           lengths, starts, k_scale=None, v_scale=None):
    """Scatter a BATCH of short spans' K/V into the paged cache — the
    speculative-decoding VERIFY write: every sequence lands its
    [last emitted token, draft_1..draft_K] K/V in one fused scatter.

    k_new/v_new:   (B, S, KVH, D) — row b holds keys/values for token
                   positions starts[b]..starts[b]+S-1 (positions past
                   lengths[b] are bucket padding).
    block_tables:  (B, max_pages) int32 — per-sequence page ids; slot j
                   covers tokens [j*page_size, (j+1)*page_size).
    lengths:       (B,) int32 — live tokens in each row's span (the
                   verify step's 1 + draft_len); span positions >=
                   lengths[b] route to page 0, the reserved pad page
                   (the `paged_attention_decode` padding contract).
    starts:        (B,) int32 — absolute position of k_new[b, 0]
                   (seq_len - 1: the first input token overwrites its
                   own slot idempotently, exactly like the decode-step
                   write — a supervisor retry re-runs bit-identically;
                   quantize-on-write keeps idempotence: the same fp
                   input always quantizes to the same (values, scale)).
    k/v_scale:     optional fp32 scale arrays for int8 caches.
    Returns the updated (k_cache, v_cache) (+ scales when given).

    Batched sibling of `paged_cache_write_range` (single-sequence
    prefill span) and `paged_cache_write` (one token per sequence);
    kept a pure-XLA scatter like both — a verify span moves at most
    (K+1) tokens per sequence, not a bandwidth problem; the read path
    stays the gathered-prefix attention / Pallas kernel.
    """
    num_pages, KVH, page_size, D = k_cache.shape
    B, S = k_new.shape[:2]
    k_new, k_sc = _maybe_quantize(k_cache, k_new, k_scale)
    v_new, v_sc = _maybe_quantize(v_cache, v_new, v_scale)
    P = block_tables.shape[1]
    t = jnp.arange(S, dtype=jnp.int32)[None, :]                   # (1, S)
    live = t < jnp.asarray(lengths, jnp.int32)[:, None]           # (B, S)
    pos = t + jnp.asarray(starts, jnp.int32)[:, None]             # (B, S)
    page_idx = jax.lax.div(pos, jnp.int32(page_size))
    page_off = jax.lax.rem(pos, jnp.int32(page_size))
    # dead positions may carry pos < 0 (padded batch rows start at -1)
    # or past-the-table pages: clamp the gather index — the page id is
    # forced to 0 by `live` anyway, and their offsets fall out of
    # bounds (FILL_OR_DROP discards them)
    safe_idx = jnp.clip(page_idx, 0, P - 1)
    pages = jnp.where(
        live,
        jnp.take_along_axis(block_tables.astype(jnp.int32), safe_idx,
                            axis=1),
        0)
    heads = jnp.arange(KVH, dtype=jnp.int32)
    idx = jnp.stack([
        jnp.broadcast_to(pages[:, :, None], (B, S, KVH)),
        jnp.broadcast_to(heads[None, None, :], (B, S, KVH)),
        jnp.broadcast_to(page_off[:, :, None], (B, S, KVH)),
    ], axis=-1)
    dnums = jax.lax.ScatterDimensionNumbers(
        update_window_dims=(1,),
        inserted_window_dims=(0, 1, 2),
        scatter_dims_to_operand_dims=(0, 1, 2))
    # dead positions collide on page 0 — duplicates allowed there (pad
    # page contents are never read un-masked), so uniqueness must NOT
    # be declared (same contract note as paged_cache_write)
    k_cache = jax.lax.scatter(
        k_cache, idx.reshape(B * S * KVH, 3),
        k_new.reshape(B * S * KVH, D).astype(k_cache.dtype), dnums,
        indices_are_sorted=False, unique_indices=False)
    v_cache = jax.lax.scatter(
        v_cache, idx.reshape(B * S * KVH, 3),
        v_new.reshape(B * S * KVH, D).astype(v_cache.dtype), dnums,
        indices_are_sorted=False, unique_indices=False)
    if k_sc is None:
        return k_cache, v_cache
    k_scale = _scatter_scales(k_scale, idx.reshape(B * S * KVH, 3), k_sc)
    v_scale = _scatter_scales(v_scale, idx.reshape(B * S * KVH, 3), v_sc)
    return k_cache, v_cache, k_scale, v_scale


def alloc_paged_cache(num_kv_heads, num_pages, page_size, head_dim,
                      dtype=jnp.bfloat16, kv_dtype=None):
    """Allocate an empty paged KV cache pair in the kernel's layout.

    kv_dtype="int8" returns (k, v, k_scale, v_scale): int8 value pages
    plus fp32 per-slot scale pages addressed by the same page ids
    (all-zero scales dequantize the pad page to exact zeros, matching
    the bf16 pad contract)."""
    shape = (num_pages, num_kv_heads, page_size, head_dim)
    if kv_dtype == "int8":
        sshape = (num_pages, num_kv_heads, page_size)
        return (jnp.zeros(shape, jnp.int8), jnp.zeros(shape, jnp.int8),
                jnp.zeros(sshape, KV_SCALE_DTYPE),
                jnp.zeros(sshape, KV_SCALE_DTYPE))
    if kv_dtype is not None:
        raise ValueError(f"unknown kv_dtype {kv_dtype!r}")
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)


def paged_cache_write(k_cache, v_cache, k_new, v_new, block_tables,
                      write_pos, k_scale=None, v_scale=None):
    """Scatter one step's K/V into the paged cache.

    k_new/v_new: (B, KVH, D) — the current token's key/value per head.
    write_pos:   (B,) int32 — token index being written (seq_len - 1).
    k/v_scale:   optional fp32 scale arrays for int8 caches
                 (quantize-on-write, same contract as the span writes).
    Returns the updated (k_cache, v_cache) (+ scales when given).

    The scatter is a pure-XLA dynamic update (one token per sequence per
    step — not a bandwidth problem); the read path is the Pallas kernel.
    """
    num_pages, KVH, page_size, D = k_cache.shape
    B = k_new.shape[0]
    k_new, k_sc = _maybe_quantize(k_cache, k_new, k_scale)
    v_new, v_sc = _maybe_quantize(v_cache, v_new, v_scale)
    pos = write_pos.astype(jnp.int32)
    page_idx = jax.lax.div(pos, jnp.int32(page_size))
    page_off = jax.lax.rem(pos, jnp.int32(page_size))
    pages = jnp.take_along_axis(block_tables.astype(jnp.int32),
                                page_idx[:, None], axis=1)[:, 0]   # (B,)
    heads = jnp.arange(KVH, dtype=jnp.int32)
    # scatter indices (B, KVH, 3) over cache dims (page, head, slot)
    idx = jnp.stack([
        jnp.broadcast_to(pages[:, None], (B, KVH)),
        jnp.broadcast_to(heads[None, :], (B, KVH)),
        jnp.broadcast_to(page_off[:, None], (B, KVH)),
    ], axis=-1)
    dnums = jax.lax.ScatterDimensionNumbers(
        update_window_dims=(1,),
        inserted_window_dims=(0, 1, 2),
        scatter_dims_to_operand_dims=(0, 1, 2))
    # NOT unique: a bucket-padded decode batch (serving engine) carries
    # pad rows with write_pos = -1 that all fold to the same (page 0,
    # head, -1) index — FILL_OR_DROP discards them (offset out of
    # bounds), but declaring uniqueness over duplicate indices is
    # undefined behavior, so don't
    k_cache = jax.lax.scatter(
        k_cache, idx.reshape(B * KVH, 3),
        k_new.reshape(B * KVH, D).astype(k_cache.dtype), dnums,
        indices_are_sorted=False, unique_indices=False)
    v_cache = jax.lax.scatter(
        v_cache, idx.reshape(B * KVH, 3),
        v_new.reshape(B * KVH, D).astype(v_cache.dtype), dnums,
        indices_are_sorted=False, unique_indices=False)
    if k_sc is None:
        return k_cache, v_cache
    k_scale = _scatter_scales(k_scale, idx.reshape(B * KVH, 3), k_sc)
    v_scale = _scatter_scales(v_scale, idx.reshape(B * KVH, 3), v_sc)
    return k_cache, v_cache, k_scale, v_scale
