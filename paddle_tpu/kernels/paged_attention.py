"""Pallas paged-KV-cache decode attention (TPU).

Capability parity: the reference serving kernel pack —
`block_multi_head_attention` (paged KV cache,
`paddle/phi/kernels/fusion/gpu/block_multi_head_attention.cu` via
`python/paddle/incubate/nn/functional/block_multihead_attention.py`) and
`masked_multihead_attention` (decode MHA,
`paddle/phi/kernels/fusion/gpu/masked_multihead_attention_kernel.cu`).
Rebuilt as a native Pallas TPU kernel over a TPU-friendly page layout
rather than a CUDA translation.

Design:
  * the KV cache lives in HBM as (num_pages, KVH, page_size, D) — page
    major, so one page (all kv heads' slices for page_size tokens) is a
    single contiguous DMA; pages are assigned to sequences through an
    int32 block table;
  * decode query (B, H, D) is viewed as (B, KVH, G, D) with G = H//KVH
    grouped-query heads sharing one KV head;
  * grid (B, max_pages) with the page dimension innermost: the block
    table and per-sequence lengths ride scalar prefetch, the page index
    map gathers `block_tables[b, i]` so Pallas streams exactly the
    pages this sequence owns (double-buffered HBM->VMEM), one whole
    page (all kv heads) per step;
  * online softmax over pages with (G, 128) lane-broadcast running
    stats; pages past ceil(len/page_size) skip all compute via pl.when;
  * positions >= seq_len inside the last page are masked in-block.

The kernel is bandwidth-bound (one pass over the live KV), which is the
same regime the reference's CUDA kernel targets; MXU utilisation is
irrelevant at decode G sizes.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..jax_compat import patch_pltpu
from .flash_attention import _interpret_mode

patch_pltpu()

__all__ = ["paged_attention_decode", "paged_cache_write",
           "paged_cache_write_range", "paged_cache_write_span",
           "alloc_paged_cache", "check_supported_paged", "paged_blockspecs"]

NEG_INF = np.float32(-1e30)
_STATS_LANES = 128
_I0 = np.int32(0)


def _decode_kernel(bt_ref, sl_ref, q_ref, *rest_refs, sm_scale, page_size,
                   nsteps, kvh, fold):
    """Grid (B, nsteps); one step streams `fold` gathered pages for ALL
    kv heads. Folding matters: with one 16-token page per step the DMAs
    are 64 KB and per-step overhead dominates (measured 78 GB/s on v5e;
    401 GB/s once ~128 tokens move per step), so small serving pages
    are batched until a step carries >= ~128 tokens' worth of KV."""
    k_refs = rest_refs[:fold]
    v_refs = rest_refs[fold:2 * fold]
    o_ref, acc_ref, m_ref, l_ref = rest_refs[2 * fold:]
    sm_scale = np.float32(sm_scale)
    b = pl.program_id(0)
    i = pl.program_id(1)
    sl = sl_ref[b]

    @pl.when(i == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    @pl.when(i * fold * page_size < sl)
    def _step():
        for f in range(fold):                          # static unroll
            for h in range(kvh):                       # static unroll
                q = q_ref[0, h].astype(jnp.float32)    # (G, D)
                k = k_refs[f][0, h].astype(jnp.float32)  # (page, D)
                v = v_refs[f][0, h].astype(jnp.float32)
                s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                        preferred_element_type=jnp.float32)
                s = s * sm_scale                       # (G, page)
                G, P = s.shape
                pos = ((i * fold + f) * page_size
                       + jax.lax.broadcasted_iota(jnp.int32, (G, P), 1))
                s = jnp.where(pos < sl, s, NEG_INF)
                m_prev = m_ref[h, :, :1]
                l_prev = l_ref[h, :, :1]
                m_cur = jnp.max(s, axis=1, keepdims=True)
                m_new = jnp.maximum(m_prev, m_cur)
                p = jnp.where(s <= NEG_INF / 2, 0.0, jnp.exp(s - m_new))
                alpha = jnp.where(m_prev <= NEG_INF / 2, 0.0,
                                  jnp.exp(m_prev - m_new))
                l_ref[h] = jnp.broadcast_to(
                    l_prev * alpha + jnp.sum(p, axis=1, keepdims=True),
                    l_ref.shape[1:])
                m_ref[h] = jnp.broadcast_to(m_new, m_ref.shape[1:])
                acc_ref[h] = acc_ref[h] * alpha + jax.lax.dot_general(
                    p, v, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)

    @pl.when(i == nsteps - 1)
    def _finalize():
        for h in range(kvh):
            l = jnp.maximum(l_ref[h, :, :1], np.float32(1e-30))
            o_ref[0, h] = (acc_ref[h] / l).astype(o_ref.dtype)


def check_supported_paged(q_shape, cache_shape, dtype):
    """Static shape validation mirroring what Mosaic will accept — raise
    here (with a clear message) instead of deep inside lowering. Same
    role as flash_attention.check_supported; the legality test suite
    (tests/test_paged_blockspec_legality.py) sweeps this + the exact
    BlockSpecs below, because interpret=True on CPU hides all Mosaic
    tiling violations (round-1 lesson)."""
    B, H, D = q_shape
    num_pages, KVH, page_size, Dc = cache_shape
    if D != Dc:
        raise ValueError(f"q head_dim {D} != cache head_dim {Dc}")
    if H % KVH != 0:
        raise ValueError(f"H={H} not a multiple of KVH={KVH}")
    if D % 64 != 0 or D > 256:
        raise ValueError(f"head_dim {D} unsupported (need multiple of 64, "
                         "<= 256)")
    if page_size % 8 != 0:
        raise ValueError(f"page_size {page_size} must be a multiple of 8 "
                         "(sublane tiling)")
    if str(dtype) not in ("bfloat16", "float32"):
        # float16 is deliberately rejected: bf16/f32 are the TPU's native
        # compute dtypes; Mosaic fp16 support is not something we can
        # rely on unvalidated (ADVICE r3 asked to confirm on-chip — still
        # pending a live relay; loosen only after a real-chip run passes)
        raise ValueError(f"unsupported dtype {dtype} (TPU-native kernels "
                         "accept bfloat16/float32)")


def _fold_pages(page_size, max_pages, fold_tokens=None):
    """Pages batched per grid step: max(128 tokens, 2 pages), clamped to
    the table width. Single source of truth for the kernel AND the
    static legality enumeration (they drifted once — don't re-fork)."""
    if fold_tokens is None:
        fold_tokens = max(128, 2 * page_size)
    return max(1, min(fold_tokens // page_size, max_pages))


def paged_blockspecs(B, H, KVH, D, page_size, num_pages, max_pages=None,
                     fold_tokens=None):
    """The exact (block_shape, array_shape) pairs the pallas_call below
    constructs — including the `fold` repetition of the k/v page specs
    the folded grid uses — plus the VMEM scratch shapes; enumerable for
    the static legality test without running the kernel."""
    G = H // KVH
    if max_pages is None:
        max_pages = num_pages
    fold = _fold_pages(page_size, max_pages, fold_tokens)
    page = ((1, KVH, page_size, D), (num_pages, KVH, page_size, D))
    specs = (
        [((1, KVH, G, D), (B, KVH, G, D))]                # q block
        + [page] * fold                                   # k pages
        + [page] * fold                                   # v pages
        + [((1, KVH, G, D), (B, KVH, G, D))]              # out block
    )
    scratch = [(KVH, G, D), (KVH, G, _STATS_LANES), (KVH, G, _STATS_LANES)]
    return specs, scratch


def paged_attention_decode(q, k_cache, v_cache, block_tables, seq_lens,
                           sm_scale=None, fold_tokens=None):
    """One decode step of attention over a paged KV cache.

    q:            (B, H, D) — current-step queries.
    k/v_cache:    (num_pages, KVH, page_size, D).
    block_tables: (B, max_pages) int32 — page ids per sequence, position
                  j holds the page covering tokens [j*page_size,
                  (j+1)*page_size); unused slots must hold a valid page
                  id (0 is fine — masked out by seq_lens).
    seq_lens:     (B,) int32 — live tokens per sequence (including the
                  token being decoded).
    Returns (B, H, D).
    """
    B, H, D = q.shape
    num_pages, KVH, page_size, _ = k_cache.shape
    max_pages = block_tables.shape[1]
    check_supported_paged(q.shape, k_cache.shape, q.dtype)
    G = H // KVH
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(D)
    qg = q.reshape(B, KVH, G, D)
    bt = block_tables.astype(jnp.int32)
    sl = seq_lens.astype(jnp.int32)

    # Fold pages so one grid step moves >= max(128 tokens, 2 pages) of
    # KV (swept on v5e at B16 KVH8 D128 S2048: 16-token steps ran at
    # 78 GB/s — DMA-latency-bound — vs 96/188/268 GB/s folded at
    # page 16/32/64, and 2-page folds at page 128 hit 472 GB/s vs 401
    # unfolded; folds deeper than this regressed every small-page
    # config). Pad the block table to a fold multiple; padded slots
    # reuse page 0 and are masked by seq_lens.
    fold = _fold_pages(page_size, max_pages, fold_tokens)
    if max_pages % fold != 0:
        pad = fold - max_pages % fold
        bt = jnp.pad(bt, ((0, 0), (0, pad)))
        max_pages += pad
    nsteps = max_pages // fold

    kernel = functools.partial(_decode_kernel, sm_scale=float(sm_scale),
                               page_size=page_size, nsteps=nsteps,
                               kvh=KVH, fold=fold)

    def page_spec(f):
        return pl.BlockSpec(
            (1, KVH, page_size, D),
            lambda b, i, bt, sl, f=f: (bt[b, i * fold + f],
                                       _I0, _I0, _I0))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, nsteps),
        in_specs=(
            [pl.BlockSpec((1, KVH, G, D),
                          lambda b, i, *_: (b, _I0, _I0, _I0))]
            + [page_spec(f) for f in range(fold)]      # k pages
            + [page_spec(f) for f in range(fold)]      # v pages
        ),
        out_specs=pl.BlockSpec((1, KVH, G, D),
                               lambda b, i, *_: (b, _I0, _I0, _I0)),
        scratch_shapes=[
            pltpu.VMEM((KVH, G, D), jnp.float32),
            pltpu.VMEM((KVH, G, _STATS_LANES), jnp.float32),
            pltpu.VMEM((KVH, G, _STATS_LANES), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, KVH, G, D), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=_interpret_mode(),
    )(bt, sl, qg, *([k_cache] * fold), *([v_cache] * fold))
    return out.reshape(B, H, D)


def paged_cache_write_range(k_cache, v_cache, k_new, v_new, block_table,
                            length, start=0):
    """Scatter a prefill span's K/V (one sequence) into the paged cache.

    k_new/v_new:  (S, KVH, D) — keys/values for token positions
                  start..start+S-1 (S may exceed `length`: the tail is
                  prompt padding).
    block_table:  (max_pages,) int32 — the sequence's page ids; slot j
                  covers tokens [j*page_size, (j+1)*page_size).
    length:       () int32 — live tokens IN THIS SPAN; span positions
                  >= length are routed to page 0, the reserved pad page
                  the decode kernel never reads un-masked (same contract
                  as the padded block-table slots in
                  `paged_attention_decode`).
    start:        () int32 — absolute token position of k_new[0]
                  (chunked prefill writes a partial prompt at an
                  offset; whole-prompt callers keep the default 0).
    Returns the updated (k_cache, v_cache).

    Serving prefill companion of `paged_cache_write`: one scatter moves
    a whole chunk instead of a token per step, so the engine's prefill
    program is a single fused write (the read path stays the Pallas
    kernel).
    """
    num_pages, KVH, page_size, D = k_cache.shape
    S = k_new.shape[0]
    t = jnp.arange(S, dtype=jnp.int32)
    live = t < jnp.asarray(length, jnp.int32)
    pos = t + jnp.asarray(start, jnp.int32)
    page_idx = jax.lax.div(pos, jnp.int32(page_size))
    page_off = jax.lax.rem(pos, jnp.int32(page_size))
    pages = jnp.where(live, block_table.astype(jnp.int32)[page_idx], 0)
    heads = jnp.arange(KVH, dtype=jnp.int32)
    idx = jnp.stack([
        jnp.broadcast_to(pages[:, None], (S, KVH)),
        jnp.broadcast_to(heads[None, :], (S, KVH)),
        jnp.broadcast_to(page_off[:, None], (S, KVH)),
    ], axis=-1)
    dnums = jax.lax.ScatterDimensionNumbers(
        update_window_dims=(1,),
        inserted_window_dims=(0, 1, 2),
        scatter_dims_to_operand_dims=(0, 1, 2))
    # padded positions collide on page 0 — duplicates allowed there (the
    # pad page's contents are never read un-masked)
    k_cache = jax.lax.scatter(
        k_cache, idx.reshape(S * KVH, 3),
        k_new.reshape(S * KVH, D).astype(k_cache.dtype), dnums,
        indices_are_sorted=False, unique_indices=False)
    v_cache = jax.lax.scatter(
        v_cache, idx.reshape(S * KVH, 3),
        v_new.reshape(S * KVH, D).astype(v_cache.dtype), dnums,
        indices_are_sorted=False, unique_indices=False)
    return k_cache, v_cache


def paged_cache_write_span(k_cache, v_cache, k_new, v_new, block_tables,
                           lengths, starts):
    """Scatter a BATCH of short spans' K/V into the paged cache — the
    speculative-decoding VERIFY write: every sequence lands its
    [last emitted token, draft_1..draft_K] K/V in one fused scatter.

    k_new/v_new:   (B, S, KVH, D) — row b holds keys/values for token
                   positions starts[b]..starts[b]+S-1 (positions past
                   lengths[b] are bucket padding).
    block_tables:  (B, max_pages) int32 — per-sequence page ids; slot j
                   covers tokens [j*page_size, (j+1)*page_size).
    lengths:       (B,) int32 — live tokens in each row's span (the
                   verify step's 1 + draft_len); span positions >=
                   lengths[b] route to page 0, the reserved pad page
                   (the `paged_attention_decode` padding contract).
    starts:        (B,) int32 — absolute position of k_new[b, 0]
                   (seq_len - 1: the first input token overwrites its
                   own slot idempotently, exactly like the decode-step
                   write — a supervisor retry re-runs bit-identically).
    Returns the updated (k_cache, v_cache).

    Batched sibling of `paged_cache_write_range` (single-sequence
    prefill span) and `paged_cache_write` (one token per sequence);
    kept a pure-XLA scatter like both — a verify span moves at most
    (K+1) tokens per sequence, not a bandwidth problem; the read path
    stays the gathered-prefix attention / Pallas kernel.
    """
    num_pages, KVH, page_size, D = k_cache.shape
    B, S = k_new.shape[:2]
    P = block_tables.shape[1]
    t = jnp.arange(S, dtype=jnp.int32)[None, :]                   # (1, S)
    live = t < jnp.asarray(lengths, jnp.int32)[:, None]           # (B, S)
    pos = t + jnp.asarray(starts, jnp.int32)[:, None]             # (B, S)
    page_idx = jax.lax.div(pos, jnp.int32(page_size))
    page_off = jax.lax.rem(pos, jnp.int32(page_size))
    # dead positions may carry pos < 0 (padded batch rows start at -1)
    # or past-the-table pages: clamp the gather index — the page id is
    # forced to 0 by `live` anyway, and their offsets fall out of
    # bounds (FILL_OR_DROP discards them)
    safe_idx = jnp.clip(page_idx, 0, P - 1)
    pages = jnp.where(
        live,
        jnp.take_along_axis(block_tables.astype(jnp.int32), safe_idx,
                            axis=1),
        0)
    heads = jnp.arange(KVH, dtype=jnp.int32)
    idx = jnp.stack([
        jnp.broadcast_to(pages[:, :, None], (B, S, KVH)),
        jnp.broadcast_to(heads[None, None, :], (B, S, KVH)),
        jnp.broadcast_to(page_off[:, :, None], (B, S, KVH)),
    ], axis=-1)
    dnums = jax.lax.ScatterDimensionNumbers(
        update_window_dims=(1,),
        inserted_window_dims=(0, 1, 2),
        scatter_dims_to_operand_dims=(0, 1, 2))
    # dead positions collide on page 0 — duplicates allowed there (pad
    # page contents are never read un-masked), so uniqueness must NOT
    # be declared (same contract note as paged_cache_write)
    k_cache = jax.lax.scatter(
        k_cache, idx.reshape(B * S * KVH, 3),
        k_new.reshape(B * S * KVH, D).astype(k_cache.dtype), dnums,
        indices_are_sorted=False, unique_indices=False)
    v_cache = jax.lax.scatter(
        v_cache, idx.reshape(B * S * KVH, 3),
        v_new.reshape(B * S * KVH, D).astype(v_cache.dtype), dnums,
        indices_are_sorted=False, unique_indices=False)
    return k_cache, v_cache


def alloc_paged_cache(num_kv_heads, num_pages, page_size, head_dim,
                      dtype=jnp.bfloat16):
    """Allocate an empty paged KV cache pair in the kernel's layout."""
    shape = (num_pages, num_kv_heads, page_size, head_dim)
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)


def paged_cache_write(k_cache, v_cache, k_new, v_new, block_tables,
                      write_pos):
    """Scatter one step's K/V into the paged cache.

    k_new/v_new: (B, KVH, D) — the current token's key/value per head.
    write_pos:   (B,) int32 — token index being written (seq_len - 1).
    Returns the updated (k_cache, v_cache).

    The scatter is a pure-XLA dynamic update (one token per sequence per
    step — not a bandwidth problem); the read path is the Pallas kernel.
    """
    num_pages, KVH, page_size, D = k_cache.shape
    B = k_new.shape[0]
    pos = write_pos.astype(jnp.int32)
    page_idx = jax.lax.div(pos, jnp.int32(page_size))
    page_off = jax.lax.rem(pos, jnp.int32(page_size))
    pages = jnp.take_along_axis(block_tables.astype(jnp.int32),
                                page_idx[:, None], axis=1)[:, 0]   # (B,)
    heads = jnp.arange(KVH, dtype=jnp.int32)
    # scatter indices (B, KVH, 3) over cache dims (page, head, slot)
    idx = jnp.stack([
        jnp.broadcast_to(pages[:, None], (B, KVH)),
        jnp.broadcast_to(heads[None, :], (B, KVH)),
        jnp.broadcast_to(page_off[:, None], (B, KVH)),
    ], axis=-1)
    dnums = jax.lax.ScatterDimensionNumbers(
        update_window_dims=(1,),
        inserted_window_dims=(0, 1, 2),
        scatter_dims_to_operand_dims=(0, 1, 2))
    # NOT unique: a bucket-padded decode batch (serving engine) carries
    # pad rows with write_pos = -1 that all fold to the same (page 0,
    # head, -1) index — FILL_OR_DROP discards them (offset out of
    # bounds), but declaring uniqueness over duplicate indices is
    # undefined behavior, so don't
    k_cache = jax.lax.scatter(
        k_cache, idx.reshape(B * KVH, 3),
        k_new.reshape(B * KVH, D).astype(k_cache.dtype), dnums,
        indices_are_sorted=False, unique_indices=False)
    v_cache = jax.lax.scatter(
        v_cache, idx.reshape(B * KVH, 3),
        v_new.reshape(B * KVH, D).astype(v_cache.dtype), dnums,
        indices_are_sorted=False, unique_indices=False)
    return k_cache, v_cache
