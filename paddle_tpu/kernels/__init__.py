"""Pallas TPU kernels (the phi/kernels/fusion equivalents, SURVEY.md A.2)."""
from . import flash_attention  # noqa: F401
