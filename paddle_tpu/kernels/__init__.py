"""Pallas TPU kernels (the phi/kernels/fusion equivalents, SURVEY.md A.2)."""
from . import flash_attention  # noqa: F401
from . import ring_attention  # noqa: F401
from .ring_attention import ring_flash_attention, ulysses_attention  # noqa: F401
