"""Fused multi-tensor AdamW update — one Pallas pass over flat buckets.

Round-4 measured the AdamW update AT the HBM roofline (~21 ms for 608M
fp32 states, RELAY_STATUS.md r4): the update is pure bytes, so the only
levers left are (a) narrower state bytes (bf16 moments, already
storable via `moment_dtype="bfloat16"`) and (b) ONE read and ONE write
per state byte instead of the per-parameter upcast/downcast round trips
XLA emits for the eager per-leaf update. This module is lever (b): the
TPU-native rebuild of Paddle's fused_adam multi-tensor kernel
(reference `paddle/phi/kernels/fusion/gpu/fused_adam_kernel.cu`, SURVEY
layer 2 — there a single CUDA kernel walks a chunked tensor list; here
the leaves are packed once into padded flat buckets and a single
`pallas_call` streams the bucket).

Geometry (single source: `build_bucket_layout`): every parameter leaf
flattens into one 1-D bucket per update group, zero-padded to a
(rows, 128) view whose rows are 64-aligned — 64 sublanes covers the
fp32(8)/bf16(16)/int8(32) minimum tiles, keeps every block
(8, 128)-legal, and is further aligned to the ZeRO sharding degree so
`P("sharding", None)` always divides. Zero padding is update-invariant:
g = m = v = w = 0 stays 0 through the AdamW expression.

The kernel reads (grad, master-or-param, m, v) blocks and writes
(param[, master], m, v) blocks — every state byte moves exactly once
each way; bias correction, lr, decoupled weight decay arrive via
SCALAR PREFETCH (an fp32 vector in SMEM) so a changing step count never
recompiles the kernel. Block rows are picked against the SAME A3 VMEM
estimator tpu-lint runs (`analysis/vmem.py::fits_vmem`,
`fp32_copies=5` for the g/w/m/v/update fp32 temporaries a block
materializes) — `pick_block_rows_fused` is the chip-blind cross-check
anchor for the lint fixtures. Untileable-or-tiny buckets and the
ZeRO-1 path use `_adamw_math` through XLA instead (`use_pallas=False`):
under GSPMD a pallas_call is an opaque custom call the partitioner can
only replicate, while the identical jnp expression partitions exactly —
each 'sharding' rank updates its bucket rows and the replication
constraint on the param output IS the ZeRO-1 all-gather (GSPMD
constraints outside shard_map, per the architecture invariants).

Numerics contract (tests/test_fused_optimizer.py): `_adamw_math` is the
ONLY update expression — the Pallas kernel body and the XLA fallback
both call it, with scalars rounded to fp32 exactly where the eager
per-parameter path's weak-typed python floats round, so fused-vs-eager
is bit-identical for the bf16-moment storage path and byte-exact for
fp32 state.
"""
from __future__ import annotations

import functools
import math
from typing import List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..jax_compat import patch_pltpu

patch_pltpu()

from .flash_attention import _I0, _interpret_mode  # noqa: E402
from ..analysis.vmem import fits_vmem  # noqa: E402

__all__ = ["BucketLayout", "build_bucket_layout", "pack_bucket",
           "unpack_bucket", "adamw_scalars", "adamw_update_bytes",
           "pick_block_rows_fused", "fused_adamw_bucket",
           "fused_adamw_zero1", "LANES", "ROW_ALIGN", "PALLAS_MIN_ROWS"]

LANES = 128          # lane width of the 2-D bucket view
ROW_ALIGN = 64       # sublane alignment: covers fp32/bf16/int8 min tiles
# below this many rows a kernel dispatch costs more than the fused read
# saves — the XLA composition (which fuses a small bucket into one
# loop anyway) takes over
PALLAS_MIN_ROWS = 1024
# half of Mosaic's ~16 MB scoped-vmem budget, same headroom policy as
# fused_norm.pick_block_rows
VMEM_TARGET_BYTES = 8 * 1024 * 1024
N_SCALARS = 9        # lr, wd_factor, b1, 1-b1, b2, 1-b2, bc1, bc2, eps


class BucketLayout(NamedTuple):
    """Geometry of one packed bucket — the single source every consumer
    (kernel, XLA fallback, state_dict slicing, bench bytes math) reads.

    entries: tuple of (param_index, flat_offset, size, shape) per leaf;
    rows:    padded row count of the (rows, LANES) bucket view.
    """
    entries: Tuple[Tuple[int, int, int, Tuple[int, ...]], ...]
    rows: int

    @property
    def padded_size(self) -> int:
        return self.rows * LANES

    @property
    def used_size(self) -> int:
        return sum(e[2] for e in self.entries)


def build_bucket_layout(shapes: Sequence[Tuple[int, Tuple[int, ...]]],
                        sharding_degree: int = 1) -> BucketLayout:
    """Layout for leaves [(param_index, shape), ...]: contiguous flat
    offsets, rows padded to lcm(ROW_ALIGN, sharding_degree) so blocks
    stay (8, 128)-legal AND P('sharding', None) divides the rows."""
    entries = []
    off = 0
    for idx, shape in shapes:
        size = int(math.prod(shape)) if shape else 1
        entries.append((int(idx), off, size, tuple(int(d) for d in shape)))
        off += size
    align = math.lcm(ROW_ALIGN, max(1, int(sharding_degree)))
    rows = -(-max(off, 1) // LANES)          # ceil div
    rows = -(-rows // align) * align
    return BucketLayout(tuple(entries), rows)


def pack_bucket(arrays: Sequence[jax.Array], layout: BucketLayout,
                dtype) -> jax.Array:
    """Concatenate leaves (layout order) + zero pad -> (rows, LANES)."""
    flat = [a.reshape(-1).astype(dtype) for a in arrays]
    pad = layout.padded_size - layout.used_size
    if pad:
        flat.append(jnp.zeros((pad,), dtype))
    return jnp.concatenate(flat).reshape(layout.rows, LANES)


def unpack_bucket(bucket: jax.Array, layout: BucketLayout) -> List[jax.Array]:
    """Slice a (rows, LANES) bucket back into leaves (layout order)."""
    flat = bucket.reshape(-1)
    return [flat[off:off + size].reshape(shape)
            for (_, off, size, shape) in layout.entries]


def adamw_scalars(lr: float, beta1: float, beta2: float, eps: float,
                  weight_decay: float, step: int) -> jax.Array:
    """The prefetched scalar vector. Every entry is rounded f64 -> f32
    exactly where the eager path's weak-typed python floats round when
    they meet an fp32 array, so fused and eager round identically."""
    lr = float(lr)
    return jnp.asarray(np.array([
        lr,
        1.0 - lr * float(weight_decay),      # decoupled-decay factor
        beta1, 1.0 - beta1,
        beta2, 1.0 - beta2,
        1.0 - beta1 ** int(step),            # bias correction 1
        1.0 - beta2 ** int(step),            # bias correction 2
        eps,
    ], np.float32))


def adamw_update_bytes(n_elems: int, param_width: int = 4,
                       moment_width: int = 4, has_master: bool = False,
                       grad_width: Optional[int] = None) -> int:
    """Bytes one fused update moves (single-read/single-write contract):
    read grad + (master | param) + m + v, write param (+ master) + m +
    v. The bench_ops optimizer rows and the BASELINE sizing math both
    use this so accounting can never drift from the kernel."""
    gw = param_width if grad_width is None else grad_width
    reads = gw + (4 if has_master else param_width) + 2 * moment_width
    writes = param_width + (4 if has_master else 0) + 2 * moment_width
    return int(n_elems) * (reads + writes)


def _adamw_math(g, w, m, v, lr, wdf, b1, omb1, b2, omb2, bc1, bc2, eps):
    """THE AdamW expression — written token-for-token like the eager
    `AdamW._apply_one` (same association order: `omb2 * g * g` is
    ((omb2*g)*g), `lr * mhat / (...)` is ((lr*mhat)/(...))) so the
    fused paths round bit-identically to the per-parameter path."""
    g = g.astype(jnp.float32)
    w = w.astype(jnp.float32) * wdf
    m = b1 * m.astype(jnp.float32) + omb1 * g
    v = b2 * v.astype(jnp.float32) + omb2 * g * g
    mhat = m / bc1
    vhat = v / bc2
    w = w - lr * mhat / (jnp.sqrt(vhat) + eps)
    return w, m, v


def _adamw_kernel(s_ref, g_ref, w_ref, m_ref, v_ref, *out_refs, has_master):
    w, m, v = _adamw_math(
        g_ref[...], w_ref[...], m_ref[...], v_ref[...],
        s_ref[0], s_ref[1], s_ref[2], s_ref[3], s_ref[4], s_ref[5],
        s_ref[6], s_ref[7], s_ref[8])
    if has_master:
        p_out, w_out, m_out, v_out = out_refs
        p_out[...] = w.astype(p_out.dtype)
    else:
        w_out, m_out, v_out = out_refs
    w_out[...] = w.astype(w_out.dtype)
    m_out[...] = m.astype(m_out.dtype)
    v_out[...] = v.astype(v_out.dtype)


def pick_block_rows_fused(rows: int, in_dtypes: Sequence[str],
                          out_dtypes: Sequence[str],
                          block_rows: int = 1024,
                          budget: int = VMEM_TARGET_BYTES) -> int:
    """Row-block pick validated against the SAME estimator tpu-lint's
    A3 rule runs: double-buffered (block_rows, LANES) blocks at their
    true widths plus fp32_copies=5 compute temporaries (g, w, m, v and
    the update quotient live as fp32 block-sized values). Halve until
    the estimate fits the budget AND the pick divides the padded rows
    (build_bucket_layout's 64-alignment guarantees a divisor >= 8
    exists for pow-2 candidates)."""
    while True:
        ins = [((block_rows, LANES), str(d)) for d in in_dtypes]
        outs = [((block_rows, LANES), str(d)) for d in out_dtypes]
        ok, _ = fits_vmem(ins, outs, fp32_copies=5, budget=budget)
        if ok:
            break
        if block_rows <= 8:
            raise ValueError(
                "fused optimizer: even an 8-row block exceeds the VMEM "
                "budget — use the XLA fallback for this bucket")
        block_rows //= 2
    while rows % block_rows != 0:
        block_rows //= 2
        if block_rows < 8:
            raise ValueError(
                f"fused optimizer: rows={rows} has no 8-aligned pow-2 "
                "divisor — pad the bucket with build_bucket_layout")
    return block_rows


def fused_adamw_bucket(grads, weights, m, v, scalars, param_dtype=None,
                       use_pallas: Optional[bool] = None,
                       block_rows: int = 1024):
    """One fused AdamW pass over a (rows, LANES) bucket.

    weights is the fp32 master bucket when `param_dtype` names a
    narrower parameter dtype (multi_precision), else the parameter
    bucket itself. Returns (param_new, weights_new, m_new, v_new) in
    their storage dtypes; param_new is weights_new when no master.

    use_pallas=None picks the kernel for buckets >= PALLAS_MIN_ROWS
    rows and the XLA composition below (a tiny bucket's dispatch costs
    more than the fusion saves); ZeRO-1 forces the XLA path (see
    module docstring).
    """
    rows, lanes = grads.shape
    if lanes != LANES:
        raise ValueError(f"bucket lane dim must be {LANES}, got {lanes}")
    has_master = (param_dtype is not None
                  and jnp.dtype(param_dtype) != weights.dtype)
    if use_pallas is None:
        use_pallas = rows >= PALLAS_MIN_ROWS and rows % 8 == 0

    if not use_pallas:
        w_new, m_new, v_new = _adamw_math(
            grads, weights, m, v, scalars[0], scalars[1], scalars[2],
            scalars[3], scalars[4], scalars[5], scalars[6], scalars[7],
            scalars[8])
        w_out = w_new.astype(weights.dtype)
        m_out = m_new.astype(m.dtype)
        v_out = v_new.astype(v.dtype)
        p_out = w_new.astype(param_dtype) if has_master else w_out
        return p_out, w_out, m_out, v_out

    in_dts = [str(a.dtype) for a in (grads, weights, m, v)]
    out_dts = ([str(jnp.dtype(param_dtype))] if has_master else []) + \
        [str(weights.dtype), str(m.dtype), str(v.dtype)]
    br = pick_block_rows_fused(rows, in_dts, out_dts, block_rows)
    spec = pl.BlockSpec((br, LANES), lambda i, s: (i, _I0))
    out_shapes = []
    if has_master:
        out_shapes.append(
            jax.ShapeDtypeStruct((rows, LANES), jnp.dtype(param_dtype)))
    out_shapes += [jax.ShapeDtypeStruct((rows, LANES), weights.dtype),
                   jax.ShapeDtypeStruct((rows, LANES), m.dtype),
                   jax.ShapeDtypeStruct((rows, LANES), v.dtype)]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(rows // br,),
        in_specs=[spec] * 4,
        out_specs=[spec] * len(out_shapes),
    )
    outs = pl.pallas_call(
        functools.partial(_adamw_kernel, has_master=has_master),
        grid_spec=grid_spec,
        out_shape=tuple(out_shapes),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=_interpret_mode(),
    )(scalars, grads, weights, m, v)
    if has_master:
        return outs
    w_out, m_out, v_out = outs
    return w_out, w_out, m_out, v_out


def fused_adamw_zero1(grads, weights, m, v, scalars, mesh,
                      param_dtype=None, axis: str = "sharding"):
    """ZeRO-1 over the SAME bucket layout: moments + master rows
    sharded over the mesh's 'sharding' axis, each rank updates its
    shard, and the replication constraint on the param output is the
    bf16-delta all-gather. GSPMD constraints only — no shard_map (the
    architecture invariant); the update itself is the XLA composition
    so the partitioner can actually split it (a pallas custom call it
    could only replicate)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    shard = NamedSharding(mesh, P(axis, None))
    repl = NamedSharding(mesh, P(None, None))

    def constrain(arr, s):
        # under tracing only with_sharding_constraint actually pins the
        # layout (an in-trace device_put is a no-op on this jax);
        # eagerly with_sharding_constraint is unavailable, so place
        # for real (same split as distributed/sharding.py's _place)
        if isinstance(arr, jax.core.Tracer):
            return jax.lax.with_sharding_constraint(arr, s)
        return jax.device_put(arr, s)

    grads = constrain(grads, shard)
    weights = constrain(weights, shard)
    m = constrain(m, shard)
    v = constrain(v, shard)
    p_new, w_new, m_new, v_new = fused_adamw_bucket(
        grads, weights, m, v, scalars, param_dtype=param_dtype,
        use_pallas=False)
    p_new = constrain(p_new, repl)
    # pin the state outputs too: under jit the replicated param output
    # would otherwise win sharding propagation and the compiled step
    # would silently re-replicate the very bytes ZeRO-1 shards
    w_new = constrain(w_new, shard)
    m_new = constrain(m_new, shard)
    v_new = constrain(v_new, shard)
    return p_new, w_new, m_new, v_new
