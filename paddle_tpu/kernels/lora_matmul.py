"""Batched heterogeneous-adapter LoRA matmul (Pallas TPU) — the
multi-LoRA serving delta GEMM (ISSUE 15).

Capability parity: Punica's BGMV / S-LoRA's batched heterogeneous
segment matmul — every row of one decode launch applies ITS OWN
adapter's low-rank delta, delta_b = (x_b @ A[id_b]) @ B[id_b], without
splitting the batch per adapter or recompiling per adapter set.

Shape contract: x (B, H) float rows; adapter_ids (B,) int32 SLOT ids
into the stacked adapter weights A (S, H, R) / B (S, R, N) fp32 (slot 0
is the reserved null adapter — all-zero matrices, so rows without an
adapter contribute an exact 0.0). Per-slot alpha/rank scaling is folded
into the B stack by the caller (serving/lora/runtime.py) BEFORE the
call, so both paths below compute the identical x@A@(B*scale) formula
— the bit-identity contract between the Pallas and XLA routes and
between engines with different loaded-adapter sets.

The Pallas kernel iterates the SLOT axis in the grid and masks rows
whose id differs — each adapter's weights stream through VMEM once per
OUTPUT-BLOCK COLUMN (N/bn of them; one column at the common decode
dims) regardless of how many rows use it, which is the bandwidth-right
shape for decode (B rows, tiny R): a gather-based bmv would re-read a
popular adapter's A/B once per ROW. Masked
accumulation is exact: non-matching slots contribute literal 0.0, and
float addition with 0.0 is the identity, so a row's delta is
bit-identical whatever the other slots hold (the solo-vs-mixed engine
acceptance rests on this).

Block discipline (the round-4 chip lessons, statically checked by
tpu-lint):
  * block picks sized against the A3 VMEM estimator
    (`analysis/vmem.py`) with the true element widths
    (`pick_lora_blocks`);
  * index maps on pinned int32 (`_I0`), never bare literals;
  * bk (the H reduction block) is the LANE dim of the x block and the
    sublane dim of the A block at once -> 128-multiple unless whole-dim;
    bn (the out block) is a lane dim -> 128-multiple unless whole-dim;
  * R and B ride whole-dim blocks (ranks are tiny; the batch is the
    sublane dim of x/out and stays whole);
  * anything the tiling cannot express falls back to the XLA gathered
    bmv composition (`lora_matmul_xla`) — same numerics by the folded-
    scale contract above, none of the weight-stream dedup.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..analysis.vmem import estimate_vmem_bytes, VMEM_BUDGET_BYTES
from ..jax_compat import patch_pltpu
from .flash_attention import _interpret_mode

patch_pltpu()

__all__ = ["lora_matmul", "lora_matmul_xla", "lora_matmul_supported",
           "pick_lora_blocks", "lora_blockspecs", "lora_delta_bytes"]

_I0 = np.int32(0)

# Search ceilings for the divisor search (the estimator does the exact
# accounting; these just bound the candidates).
_BK_MAX = 2048
_BN_MAX = 2048
# Ranks past this have left "low-rank" territory — the (B, R) scratch
# and (bk, R) A blocks stop being small, and the masked full-stack
# sweep stops being the right shape. Callers fall back to XLA.
MAX_KERNEL_RANK = 256


def _blocks(b, bk, r, bn, x_dtype):
    """(in_blocks, out_blocks, scratch) with TRUE dtypes for the A3
    estimator — x in its own dtype, fp32 A/B stacks, int32 id row,
    fp32 accumulator scratch."""
    xd = str(jnp.dtype(x_dtype))
    in_blocks = [((b, bk), xd),              # x tile
                 ((1, bk, r), "float32"),    # one slot's A tile
                 ((1, r, bn), "float32"),    # one slot's (scaled) B tile
                 ((1, b), "int32")]          # per-row slot ids
    out_blocks = [((b, bn), "float32")]
    scratch = [((b, r), "float32")]          # x @ A[s] accumulator
    return in_blocks, out_blocks, scratch


def _fits(b, bk, r, bn, x_dtype):
    ib, ob, sc = _blocks(b, bk, r, bn, x_dtype)
    return estimate_vmem_bytes(ib, ob, sc) <= VMEM_BUDGET_BYTES


def _divisor_block(dim, cap, step):
    """Largest blk <= cap with dim % blk == 0 and blk % step == 0;
    None when no such tiling exists (whole-dim handled by callers)."""
    blk = (min(dim, cap) // step) * step
    while blk >= step:
        if dim % blk == 0:
            return blk
        blk -= step
    return None


def pick_lora_blocks(B, H, R, N, x_dtype=jnp.float32):
    """VMEM-guarded (bk, bn) for the masked segment-bmm grid, or None
    when no legal tiling fits (callers take the XLA fallback).

    B (batch) and R (rank bucket) always ride whole-dim blocks; only
    the H reduction and the N output dim tile. Same
    shrink-until-it-fits discipline as quant_matmul.pick_quant_blocks."""
    if R > MAX_KERNEL_RANK:
        return None
    bk = H if H <= _BK_MAX else _divisor_block(H, _BK_MAX, 128)
    bn = N if N <= _BN_MAX else _divisor_block(N, _BN_MAX, 128)
    if bk is None or bn is None:
        return None
    while not _fits(B, bk, R, bn, x_dtype):
        # shrink H first (the A-streaming dim), then N, staying on
        # tile-aligned divisors; a dim with no smaller legal divisor
        # cannot shrink further
        for dim, cur in (("k", bk), ("n", bn)):
            if cur <= 128:
                continue
            full = H if dim == "k" else N
            cand = _divisor_block(full, cur // 2, 128)
            if cand is None:
                continue
            if dim == "k":
                bk = cand
            else:
                bn = cand
            break
        else:
            return None            # nothing left to shrink
    return bk, bn


def lora_matmul_supported(B, H, R, N, x_dtype=jnp.float32):
    """True when the Pallas path has a legal VMEM-sized tiling."""
    return pick_lora_blocks(B, H, R, N, x_dtype) is not None


def lora_blockspecs(B, S, H, R, N, x_dtype=jnp.float32):
    """The exact (block_shape, array_shape) pairs the pallas_call below
    constructs, enumerable for the static legality test (same contract
    as paged_attention.paged_blockspecs). None when unsupported."""
    picked = pick_lora_blocks(B, H, R, N, x_dtype)
    if picked is None:
        return None
    bk, bn = picked
    return [((B, bk), (B, H)),            # x
            ((1, bk, R), (S, H, R)),      # A stack
            ((1, R, bn), (S, R, N)),      # (scaled) B stack
            ((1, B), (1, B)),             # slot ids
            ((B, bn), (B, N))]            # out


def lora_delta_bytes(B, H, R, N, S_streamed, x_width=4, bn=None):
    """HBM bytes one launch of the masked kernel streams, per the
    ACTUAL grid iteration order (j outermost, then s, then k — Mosaic
    revisit caching only collapses CONSECUTIVE identical block
    indices): every A tile and the x block re-stream once per output
    block column (nj = N/bn of them), each slot's B column tile and
    the output block stream once per column, plus the delta write.
    The null slot counts — the kernel sweeps every slot in the stack.
    The bench's bytes-true accounting source; with `bn=None` (or a
    single column) this reduces to one pass over everything."""
    nj = 1 if bn is None else max(1, -(-N // bn))
    a_bytes = nj * S_streamed * H * R * 4
    b_bytes = S_streamed * R * N * 4
    x_bytes = nj * S_streamed * B * H * x_width
    return int(a_bytes + b_bytes + x_bytes + B * N * 4)


def _kernel(x_ref, a_ref, b_ref, ids_ref, o_ref, acc_ref, *, nk):
    """acc (B, R) accumulates x @ A[s] over the H blocks; at the last H
    block the slot's delta (acc @ B[s]) lands on the rows whose id
    matches s (others add an exact 0.0). The output block is revisited
    across (s, k) and written first at s == 0, accumulated after."""
    si = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    x = x_ref[...].astype(jnp.float32)
    acc_ref[...] += jax.lax.dot_general(
        x, a_ref[0], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _flush():
        mask = (ids_ref[0] == si).astype(jnp.float32)       # (B,)
        contrib = jax.lax.dot_general(
            acc_ref[...], b_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * mask[:, None]

        @pl.when(si == 0)
        def _first():
            o_ref[...] = contrib

        @pl.when(si > 0)
        def _rest():
            o_ref[...] += contrib


def lora_matmul(x2d, adapter_ids, a_stack, b_stack, blocks=None):
    """x2d (B, H) float; adapter_ids (B,) int32 slots; a_stack
    (S, H, R) fp32; b_stack (S, R, N) fp32 with per-slot scaling
    pre-folded -> (B, N) fp32 delta via the masked segment-bmm kernel.
    Callers must check `lora_matmul_supported` first (or pass
    pre-picked `blocks`); unsupported shapes raise — use
    `lora_matmul_xla` for the fallback composition."""
    B, H = x2d.shape
    S, _, R = a_stack.shape
    N = b_stack.shape[2]
    if blocks is None:
        blocks = pick_lora_blocks(B, H, R, N, x2d.dtype)
    if blocks is None:
        raise ValueError(
            f"no VMEM-legal tiling for B={B} H={H} R={R} N={N} — route "
            "through lora_matmul_xla")
    bk, bn = blocks
    nk = H // bk
    grid = (N // bn, S, nk)
    ids_row = adapter_ids.astype(jnp.int32)[None, :]
    return pl.pallas_call(
        functools.partial(_kernel, nk=nk),
        grid=grid,
        in_specs=[
            pl.BlockSpec((B, bk), lambda j, s, k: (_I0, k)),
            pl.BlockSpec((1, bk, R), lambda j, s, k: (s, k, _I0)),
            pl.BlockSpec((1, R, bn), lambda j, s, k: (s, _I0, j)),
            # block dims equal the (1, B) array dims (the documented
            # whole-array-dim case A2 cannot see)
            pl.BlockSpec((1, B),  # tpu-lint: blockspec-ok
                         lambda j, s, k: (_I0, _I0)),
        ],
        out_specs=pl.BlockSpec((B, bn), lambda j, s, k: (_I0, j)),
        out_shape=jax.ShapeDtypeStruct((B, N), jnp.float32),
        scratch_shapes=[pltpu.VMEM((B, R), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary")),
        interpret=_interpret_mode(),
        # tpu-lint-hint: vmem-dtypes=float32,float32,float32,int32
    )(x2d, a_stack, b_stack, ids_row)


def lora_matmul_xla(x2d, adapter_ids, a_stack, b_stack):
    """XLA fallback: gather each row's A/B and bmv — the same
    x @ A[id] @ (B*scale)[id] contraction per row (fp32 accumulate,
    row-independent), none of the weight-stream dedup. Used for
    untileable shapes, ranks past MAX_KERNEL_RANK, and multi-token
    rows (prefill chunks)."""
    ids = adapter_ids.astype(jnp.int32)
    a_g = jnp.take(a_stack, ids, axis=0)          # (B, H, R)
    b_g = jnp.take(b_stack, ids, axis=0)          # (B, R, N)
    xa = jnp.einsum("bh,bhr->br", x2d.astype(jnp.float32), a_g)
    return jnp.einsum("br,brn->bn", xa, b_g)
