"""Relay-proof on-device kernel timing.

Over the axon relay (the TPU transport in this environment) two things
break naive timing: every dispatch pays a multi-ms host round-trip, and
`jax.block_until_ready` does not actually block — only a host fetch
synchronizes. So timing loops of independent host-side calls measures
the transport, not the op.

`device_time` instead runs the iterations ON DEVICE in one dispatch:
a `lax.fori_loop` whose loop-carried scalar feeds an
iteration-dependent, value-preserving epsilon into the first float arg
(defeats loop-invariant hoisting and any result caching), an
`optimization_barrier` forces each iteration's output to materialize
(keeps memory-bound ops honest), and a 1-element slice of the output
becomes the next carry (serializes iterations at ~zero extra HBM
traffic). The loop result is fetched to host (`float(...)`) — the only
reliable sync — and loops of N and 2N iterations are differenced to
cancel the round-trip + fetch overhead (measured ~66 ms, stable ±1 ms).

Used by bench_ops.py and kernels/autotune.py. No reference analog —
this is infrastructure for honest measurement on this transport.
"""
from __future__ import annotations

import time

__all__ = ["device_time"]


def device_time(fn, *args, iters=10, signal_floor_s=0.02, loop_cap=512):
    """Seconds per call of fn(*args), timed device-side.

    Returns NaN when the op is too fast to resolve over the transport
    (non-positive 2N-N delta at the loop cap) — callers must not treat
    NaN as a time.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    def _bumpable(a):
        d = jnp.asarray(a).dtype
        return (jnp.issubdtype(d, jnp.floating)
                or jnp.issubdtype(d, jnp.integer))

    # prefer a float arg (epsilon is value-preserving but nonzero in
    # the IR); fall back to an int arg, where casting the traced tiny
    # float yields a runtime 0 that XLA cannot constant-fold — without
    # ANY bump the body is loop-invariant and hoistable
    bump_idx = next((j for j, a in enumerate(args)
                     if jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating)),
                    next((j for j, a in enumerate(args) if _bumpable(a)),
                         None))

    def make(n):
        @jax.jit
        def run(*a):
            def body(i, dep):
                aa = list(a)
                if bump_idx is not None:
                    eps = ((i.astype(jnp.float32) + dep) * 1e-38)
                    x = aa[bump_idx]
                    aa[bump_idx] = x + eps.astype(x.dtype)
                out = fn(*aa)
                tok = lax.optimization_barrier(out)
                leaf = jax.tree_util.tree_leaves(tok)[0]
                return jnp.ravel(leaf)[0].astype(jnp.float32)
            return lax.fori_loop(0, n, body, jnp.float32(0.0))
        return run

    def best_of(run, reps=3):
        float(run(*args))                    # compile / warm
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            float(run(*args))                # host fetch = real sync
            best = min(best, time.perf_counter() - t0)
        return best

    n = max(1, min(iters, loop_cap // 2))   # first dispatch respects the cap
    while True:
        run_long, run_short = make(2 * n), make(n)
        delta = best_of(run_long) - best_of(run_short)
        at_cap = 2 * (4 * n) > loop_cap
        if delta > signal_floor_s or at_cap:
            if delta <= 0:
                # noise inversion at the cap: one retry (reusing the
                # compiled loops), then refuse to fabricate a time
                delta = best_of(run_long) - best_of(run_short)
                if delta <= 0:
                    return float("nan")
            return delta / n
        n *= 4
