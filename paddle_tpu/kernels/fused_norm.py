"""Pallas rms_norm (+ optional residual) — the measurement counterpart.

bench_ops.py measures the XLA-fused rms_norm composition against the
HBM roofline; this kernel exists so the chip run can ALSO compare
hand-Pallas vs XLA directly (VERDICT r2 #2: add Pallas only where XLA
measurably loses >10%). Reference analog:
`paddle/phi/kernels/fusion/gpu/rms_norm_kernel.cu` (SURVEY A.2).

Layout: x (R, H) — callers flatten leading dims. Grid over row blocks;
each step streams a (block_rows, H) tile, computes the row rms in fp32,
scales by the replicated weight. BlockSpec legality: H must be
128-divisible (or equal the array dim — always true here since blocks
span the full H); block_rows is 8-divisible.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .flash_attention import _I0, _interpret_mode

__all__ = ["rms_norm_rows", "check_supported_rms", "pick_block_rows"]


def check_supported_rms(shape, dtype):
    r, h = shape
    if h % 128 != 0:
        raise ValueError(f"pallas rms_norm needs H % 128 == 0, got {h}")
    if str(dtype) not in ("bfloat16", "float32"):
        raise ValueError(f"unsupported dtype {dtype}")


def _kernel(x_ref, w_ref, o_ref, *, eps, has_res, res_ref=None):
    x = x_ref[...].astype(jnp.float32)
    if has_res:
        x = x + res_ref[...].astype(jnp.float32)
    var = jnp.mean(x * x, axis=-1, keepdims=True)
    y = x * jax.lax.rsqrt(var + eps)
    o_ref[...] = (y * w_ref[...].astype(jnp.float32)).astype(o_ref.dtype)


def _kernel_res(x_ref, res_ref, w_ref, o_ref, *, eps):
    _kernel(x_ref, w_ref, o_ref, eps=eps, has_res=True, res_ref=res_ref)


def _kernel_plain(x_ref, w_ref, o_ref, *, eps):
    _kernel(x_ref, w_ref, o_ref, eps=eps, has_res=False)


def pick_block_rows(r, h, has_residual=False, block_rows=256):
    """The kernel's VMEM-guarded row-block pick (found on chip): the
    kernel computes in fp32, so a block holds ~4 f32 copies (x, x*x, y,
    out) plus Mosaic's double-buffered bf16 in/out tiles —
    block_rows=256 at H=4096 hits "scoped vmem 24.2M > 16M". Shrink
    until the per-element estimate fits in half of VMEM; a residual
    adds its own double-buffered tile + fp32 upcast (~8 B/element
    more). Exposed standalone so tests/test_tpu_lint.py can cross-check
    the tpu-lint A3 estimator against this chip-validated rule."""
    bytes_per_elem = 24 + (8 if has_residual else 0)
    while block_rows > 8 and block_rows * h * bytes_per_elem > 8 * 1024 * 1024:
        block_rows //= 2
    if block_rows * h * bytes_per_elem > 8 * 1024 * 1024:
        raise ValueError(
            f"pallas rms_norm: even an 8-row block at H={h} exceeds the "
            "VMEM budget — use the XLA composition for this shape")
    while r % block_rows != 0:
        block_rows //= 2
        if block_rows < 8:
            # whole-array block (legal: equals array dim) — but only if
            # it also fits VMEM, else the fallback would reintroduce
            # the scoped-vmem OOM the guard above prevents
            if r * h * bytes_per_elem > 8 * 1024 * 1024:
                raise ValueError(
                    f"pallas rms_norm: rows={r} not tileable (no "
                    f"divisor >= 8) and too large for a single VMEM "
                    f"block at H={h}")
            return r
    return block_rows


def rms_norm_rows(x, weight, residual=None, eps=1e-6, block_rows=256):
    """rms_norm over the last dim of a 2-D (rows, H) array."""
    r, h = x.shape
    check_supported_rms(x.shape, x.dtype)
    block_rows = pick_block_rows(r, h, has_residual=residual is not None,
                                 block_rows=block_rows)
    grid = (r // block_rows,) if r % block_rows == 0 else (1,)

    # _I0, not a bare 0: the package enables x64, so literal ints in
    # index maps trace as i64 and Mosaic's func.return fails to
    # legalize (found on chip; interpret=True hides it).
    row_spec = pl.BlockSpec((block_rows, h), lambda i: (i, _I0))
    w_spec = pl.BlockSpec((h,), lambda i: (_I0,))
    if residual is not None:
        kernel = functools.partial(_kernel_res, eps=eps)
        in_specs = [row_spec, row_spec, w_spec]
        args = (x, residual, weight)
    else:
        kernel = functools.partial(_kernel_plain, eps=eps)
        in_specs = [row_spec, w_spec]
        args = (x, weight)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=row_spec,
        out_shape=jax.ShapeDtypeStruct((r, h), x.dtype),
        interpret=_interpret_mode(),
    )(*args)
