"""Shims for older jax releases.

The package is written against newer jax (`jax.shard_map` with
`check_vma=`); some environments pin an older jax where shard_map lives
under `jax.experimental` and the kwarg is named `check_rep`. Import
sites fall back here when the top-level import is missing:

    try:
        from jax import shard_map
    except ImportError:  # older jax: experimental
        from paddle_tpu.jax_compat import shard_map
"""
from __future__ import annotations

__all__ = ["shard_map", "axis_size", "patch_pltpu"]


def patch_pltpu():
    """Alias pltpu.CompilerParams on older jax (named TPUCompilerParams
    there) so kernel modules can use the new name uniformly. Idempotent;
    every module that touches pltpu.CompilerParams calls this at import
    instead of relying on another kernel module having patched first."""
    from jax.experimental.pallas import tpu as pltpu
    if not hasattr(pltpu, "CompilerParams"):
        pltpu.CompilerParams = pltpu.TPUCompilerParams


def shard_map(f, **kwargs):
    # imported lazily: on jax new enough to have dropped
    # jax.experimental.shard_map this fallback is never reached, and a
    # top-level import would break modules that import this shim only
    # for patch_pltpu
    from jax.experimental.shard_map import shard_map as _shard_map
    if "check_vma" in kwargs:          # renamed from check_rep in newer jax
        kwargs["check_rep"] = kwargs.pop("check_vma")
    if "axis_names" in kwargs:
        # newer jax: axis_names = the MANUAL axes; older jax expresses the
        # same partial-manual lowering as auto = mesh axes - manual axes
        manual = set(kwargs.pop("axis_names"))
        mesh_axes = set(kwargs["mesh"].axis_names)
        kwargs["auto"] = frozenset(mesh_axes - manual)
    return _shard_map(f, **kwargs)


def axis_size(axis_name):
    """jax.lax.axis_size fallback: older jax resolves the size through the
    bound axis env (jax.core.axis_frame returns the size directly)."""
    import jax
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.core.axis_frame(axis_name)
