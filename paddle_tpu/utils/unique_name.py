"""paddle.utils.unique_name — process-wide unique name generator.

Parity: reference `python/paddle/utils/unique_name.py` (generate/guard/
switch over a prefix-counter UniqueNameGenerator).
"""
from __future__ import annotations

import contextlib

__all__ = ["generate", "switch", "guard"]


class UniqueNameGenerator:
    def __init__(self, prefix=""):
        self.prefix = prefix
        self.ids = {}

    def __call__(self, key):
        n = self.ids.get(key, 0)
        self.ids[key] = n + 1
        return "_".join(filter(None, [self.prefix, key, str(n)]))


generator = UniqueNameGenerator()


def generate(key: str) -> str:
    return generator(key)


def switch(new_generator=None):
    """Swap the active generator; returns the previous one."""
    global generator
    old = generator
    generator = new_generator or UniqueNameGenerator()
    return old


@contextlib.contextmanager
def guard(new_generator=None):
    if isinstance(new_generator, str):
        new_generator = UniqueNameGenerator(new_generator)
    old = switch(new_generator)
    try:
        yield
    finally:
        switch(old)
