"""NaN/Inf debugging.

Parity: reference `FLAGS_check_nan_inf` + per-op scan
(`fluid/eager/nan_inf_utils.cc`, `phi/kernels/check_numerics_kernel.h`).
When enabled, the op-dispatch funnel checks every float output eagerly and
raises with the op name — the same observability point as the reference's
eager hook.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .flags import flags, set_flags

__all__ = ["check_numerics", "enable_check_nan_inf", "check_nan_inf_enabled",
           "maybe_check"]


def enable_check_nan_inf(enable=True, level=0):
    set_flags({"check_nan_inf": bool(enable), "check_nan_inf_level": level})


def check_nan_inf_enabled():
    return bool(flags("check_nan_inf", False))


def check_numerics(x, op_name="tensor", action="raise"):
    arr = x._data if hasattr(x, "_data") else x
    if not jnp.issubdtype(arr.dtype, jnp.floating):
        return x
    bad = bool(jnp.any(~jnp.isfinite(arr)))
    if bad:
        n_nan = int(jnp.sum(jnp.isnan(arr)))
        n_inf = int(jnp.sum(jnp.isinf(arr)))
        msg = (f"[check_nan_inf] op `{op_name}` produced {n_nan} NaN / "
               f"{n_inf} Inf values (shape={tuple(arr.shape)}, dtype={arr.dtype})")
        if action == "raise" and int(flags("check_nan_inf_level", 0)) == 0:
            raise FloatingPointError(msg)
        print("WARNING:", msg)
    return x


def maybe_check(op_name, out_arrays):
    """Hook used by ops.dispatch when FLAGS_check_nan_inf is on (eager only —
    inside jit, tracing skips the host check, same as the reference's static
    mode needing the interpreter-level hook)."""
    for a in out_arrays:
        if isinstance(a, jax.Array) and not isinstance(
                a, jax.core.Tracer):
            check_numerics(a, op_name)
