"""NaN/Inf debugging.

Parity: reference `FLAGS_check_nan_inf` + per-op scan
(`fluid/eager/nan_inf_utils.cc`, `phi/kernels/check_numerics_kernel.h`).
When enabled, the op-dispatch funnel checks every float output eagerly and
raises with the op name — the same observability point as the reference's
eager hook.

Poison attribution (ISSUE 3): `poison_scope(label)` pushes a label onto
a scope stack that every raised FloatingPointError message carries —
the serving engine wraps each compiled launch in a scope naming the
request(s) in flight, so a NaN caught by a dispatch hook is attributed
to the batch that produced it (the supervisor classifies any
FloatingPointError as deterministic poison, never retried).
"""
from __future__ import annotations

from contextlib import contextmanager
from typing import List

import jax
import jax.numpy as jnp
import numpy as np

from .flags import flags, set_flags

__all__ = ["check_numerics", "enable_check_nan_inf", "check_nan_inf_enabled",
           "maybe_check", "poison_scope", "current_poison_scope",
           "nan_stats", "reset_nan_stats", "nan_stats_generation"]

_SCOPES: List[str] = []

# Dispatch NaN-hook accounting (ISSUE 11): `checks` counts every tensor
# the hook evaluated, `hits` every NaN/Inf detection (incremented BEFORE
# the raise, so the count survives the exception). The TrainingMonitor
# records per-step deltas; only touched when FLAGS_check_nan_inf is on,
# so the default hot path stays untouched.
_STATS = {"checks": 0, "hits": 0}
_STATS_GEN = [0]


def nan_stats():
    """{checks, hits} since process start (or the last reset)."""
    return dict(_STATS)


def nan_stats_generation():
    """Bumped by every reset — delta consumers (TrainingMonitor)
    re-baseline on a generation change."""
    return _STATS_GEN[0]


def reset_nan_stats():
    _STATS["checks"] = 0
    _STATS["hits"] = 0
    _STATS_GEN[0] += 1


@contextmanager
def poison_scope(label: str):
    """Attribute any NaN-check failure raised in the body to `label`."""
    _SCOPES.append(str(label))
    try:
        yield
    finally:
        _SCOPES.pop()


def current_poison_scope():
    """The active attribution path, or None outside every scope."""
    return "/".join(_SCOPES) if _SCOPES else None


def enable_check_nan_inf(enable=True, level=0):
    set_flags({"check_nan_inf": bool(enable), "check_nan_inf_level": level})


def check_nan_inf_enabled():
    return bool(flags("check_nan_inf", False))


def check_numerics(x, op_name="tensor", action="raise"):
    arr = x._data if hasattr(x, "_data") else x
    if not jnp.issubdtype(arr.dtype, jnp.floating):
        return x
    _STATS["checks"] += 1
    bad = bool(jnp.any(~jnp.isfinite(arr)))
    if bad:
        _STATS["hits"] += 1
        n_nan = int(jnp.sum(jnp.isnan(arr)))
        n_inf = int(jnp.sum(jnp.isinf(arr)))
        scope = current_poison_scope()
        where = f" in scope `{scope}`" if scope else ""
        msg = (f"[check_nan_inf] op `{op_name}`{where} produced {n_nan} NaN / "
               f"{n_inf} Inf values (shape={tuple(arr.shape)}, dtype={arr.dtype})")
        if action == "raise" and int(flags("check_nan_inf_level", 0)) == 0:
            raise FloatingPointError(msg)
        print("WARNING:", msg)
    return x


def maybe_check(op_name, out_arrays):
    """Hook used by ops.dispatch when FLAGS_check_nan_inf is on (eager only —
    inside jit, tracing skips the host check, same as the reference's static
    mode needing the interpreter-level hook)."""
    for a in out_arrays:
        if isinstance(a, jax.Array) and not isinstance(
                a, jax.core.Tracer):
            check_numerics(a, op_name)
