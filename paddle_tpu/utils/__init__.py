"""Utilities: flags registry, nan/inf debugging, misc.

Parity: reference flag system (`paddle/common/flags.h` + `flags.cc`, 179
flags; settable via FLAGS_* env or paddle.set_flags) and nan/inf checking
(`FLAGS_check_nan_inf`, fluid/eager/nan_inf_utils.cc).
"""
from .flags import set_flags, get_flags, flags  # noqa: F401
from .nan_inf import check_numerics, enable_check_nan_inf  # noqa: F401

try:  # optional alias paddle.utils.unique_name
    from . import unique_name  # noqa: F401
except ImportError:
    pass

__all__ = ["set_flags", "get_flags", "flags", "check_numerics",
           "enable_check_nan_inf"]

from . import cpp_extension  # noqa: F401
