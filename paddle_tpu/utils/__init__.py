"""Utilities: flags registry, nan/inf debugging, misc.

Parity: reference flag system (`paddle/common/flags.h` + `flags.cc`, 179
flags; settable via FLAGS_* env or paddle.set_flags) and nan/inf checking
(`FLAGS_check_nan_inf`, fluid/eager/nan_inf_utils.cc).
"""
from .flags import set_flags, get_flags, flags  # noqa: F401
from .nan_inf import check_numerics, enable_check_nan_inf  # noqa: F401
from . import unique_name  # noqa: F401
from . import download  # noqa: F401
from . import dlpack  # noqa: F401

__all__ = ["set_flags", "get_flags", "flags", "check_numerics",
           "enable_check_nan_inf", "deprecated", "run_check",
           "require_version", "try_import", "unique_name", "download",
           "dlpack"]

from . import cpp_extension  # noqa: F401


def deprecated(update_to="", since="", reason="", level=0):
    """Parity: paddle.utils.deprecated — warn-once decorator."""
    import functools
    import warnings

    def deco(fn):
        warned = []

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if not warned:
                warned.append(1)
                msg = f"API {fn.__module__}.{fn.__qualname__} is deprecated"
                if since:
                    msg += f" since {since}"
                if update_to:
                    msg += f", use {update_to} instead"
                if reason:
                    msg += f" ({reason})"
                warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*args, **kwargs)
        wrapper.__deprecated__ = True
        return wrapper
    return deco


def run_check():
    """Parity: paddle.utils.run_check — smoke-test the install: one
    matmul on the available device(s), a multi-device mesh if present."""
    import jax
    import jax.numpy as jnp
    devs = jax.devices()
    x = jnp.ones((128, 128), jnp.float32)
    (x @ x).block_until_ready()
    print(f"PaddlePaddle-TPU works on {devs[0].platform} "
          f"({len(devs)} device(s)).")
    if len(devs) > 1:
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        mesh = Mesh(__import__("numpy").asarray(devs), ("x",))
        xs = jax.device_put(x, NamedSharding(mesh, P("x")))
        jnp.sum(xs).block_until_ready()
        print(f"PaddlePaddle-TPU works on {len(devs)} devices.")
    print("PaddlePaddle-TPU is installed successfully!")


def require_version(min_version, max_version=None):
    """Parity: paddle.utils.require_version — check the installed
    version lies in [min_version, max_version]."""
    from .. import __version__

    def parse(v):
        return tuple(int(p) for p in str(v).split(".")[:3] if p.isdigit())

    cur = parse(__version__)
    if parse(min_version) > cur:
        raise Exception(
            f"installed version {__version__} < required {min_version}")
    if max_version is not None and parse(max_version) < cur:
        raise Exception(
            f"installed version {__version__} > allowed {max_version}")
    return True


def try_import(module_name, err_msg=None):
    """Parity: paddle.utils.try_import — import or raise a helpful
    ImportError."""
    import importlib
    try:
        return importlib.import_module(module_name)
    except ImportError as e:
        raise ImportError(
            err_msg or f"{module_name} is required but not installed; "
            f"pip install {module_name}") from e
