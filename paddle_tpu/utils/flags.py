"""Runtime flag registry.

Parity: reference `paddle/common/flags.h` / `flags_native.cc`: named flags
with defaults, env-var override (FLAGS_<name>=...), paddle.set_flags /
get_flags API. Flags whose semantics carry to TPU keep their reference
names (check_nan_inf, benchmark, ...); CUDA-specific ones are registered
as inert for script compatibility.
"""
from __future__ import annotations

import os
from typing import Any, Dict

__all__ = ["define_flag", "set_flags", "get_flags", "flags"]

_REGISTRY: Dict[str, Any] = {}


def _env_cast(raw: str, default):
    if isinstance(default, bool):
        return raw.lower() in ("1", "true", "yes", "on")
    if isinstance(default, int):
        return int(raw)
    if isinstance(default, float):
        return float(raw)
    return raw


def define_flag(name: str, default, help_str: str = ""):
    env = os.environ.get("FLAGS_" + name)
    if env is not None:
        value = _env_cast(env, default)
    elif name in _REGISTRY:
        # a set_flags() issued before the defining module was lazily
        # imported must not be clobbered by the definition's default
        return _REGISTRY[name]
    else:
        value = default
    _REGISTRY[name] = value
    return value


def set_flags(flags_dict: Dict[str, Any]):
    for k, v in flags_dict.items():
        key = k[6:] if k.startswith("FLAGS_") else k
        _REGISTRY[key] = v


def get_flags(names):
    if isinstance(names, str):
        names = [names]
    out = {}
    for n in names:
        key = n[6:] if n.startswith("FLAGS_") else n
        out["FLAGS_" + key] = _REGISTRY.get(key)
    return out


def flags(name: str, default=None):
    if name not in _REGISTRY and default is not None:
        define_flag(name, default)
    return _REGISTRY.get(name, default)


# ---- the reference's flag surface that carries over to TPU (A.6) ----
define_flag("check_nan_inf", False, "scan op outputs for NaN/Inf")
define_flag("check_nan_inf_level", 0, "0: error, 1: warn, 3: collect")
define_flag("benchmark", False, "sync-and-time every op")
define_flag("low_precision_op_list", 0, "collect AMP op statistics")
define_flag("call_stack_level", 1, "error report verbosity")
define_flag("deterministic", False, "force deterministic lowering (XLA)")
define_flag("embedding_deterministic", 0, "deterministic embedding grads")
define_flag("allocator_strategy", "auto_growth", "inert on TPU (XLA BFC)")
define_flag("fraction_of_gpu_memory_to_use", 0.92,
            "maps to XLA_PYTHON_CLIENT_MEM_FRACTION")
define_flag("new_executor_serial_run", False, "debug: disable async dispatch")
define_flag("use_stride_kernel", True, "inert: XLA has no stride kernels")
define_flag("cudnn_deterministic", False, "alias of deterministic")
define_flag("sync_nccl_allreduce", False, "inert: XLA collectives are in-graph")
define_flag("tpu_matmul_precision", "default",
            "jax default_matmul_precision for fp32 matmuls")
define_flag("shm_ring_bytes", 128 << 20,
            "capacity of the DataLoader shared-memory ring transport")
