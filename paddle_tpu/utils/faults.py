"""Named fault-injection points for resilience testing.

The serving stack (and anything else that wants failure-path coverage)
declares *injection points* — `faults.register_point(name)` at import,
`faults.fire(name)` at the site. A test or the soak harness *arms* a
point with `faults.inject(name, ...)`; an armed point either raises a
chosen exception or hands a payload back to the site. Disarmed, `fire`
is a single module-flag check, so production code pays nothing.

Design rules (they make the soak harness deterministic):

* Triggers are counted/seeded, never wall-clock: `after` skips the
  first k hits, `times` bounds how often the spec fires, `prob` draws
  from the spec's own `random.Random(seed)` stream — same seed, same
  firing schedule.
* `fire` consumes specs in arm order; every actual firing is counted in
  `fired_counts()` so a soak run can assert its faults really landed.
* `injected(...)` is the context-manager form tests use; it disarms on
  exit even when the body raises.

Registered points (grep for `faults.register_point` /
`faults.fire`; full table with trigger semantics in SERVING.md "Fault
injection points"): serving KV allocator OOM, engine
prefill/decode/verify step exceptions, NaN-logits poisoning, deadline
storms, draft storms, radix donation failure, the fleet points
(replica crash, stream stall, route race), and the cross-process tier
(ISSUE 14): `transport.drop` / `transport.duplicate` /
`transport.stall` on the mailbox channel, `worker.kill9` (SIGKILL of
the worker's own process; armed INSIDE the worker via its spec — the
registry is per-process), and `cache.corrupt_entry` on the persistent
compile cache's read path. The disaggregated prefill/decode tier
(ISSUE 18) adds `fleet.handoff_partial` (donor SIGKILLs itself after
each armed kv_page send — mid-stream death), `fleet.handoff_stall`
(the supervisor's kv frame relay eats the frame — phase-deadline
trigger; host-armed) and `fleet.decode_reject` (the adopt handler
refuses the batch with a typed reject). `bench.py` uses the
BENCH_FAULT_INJECT env var instead — its supervisor must stay
importable without this package.
"""
from __future__ import annotations

import random
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

__all__ = ["register_point", "points", "inject", "injected", "clear",
           "fire", "fired_counts", "active", "FaultSpec"]

_POINTS: set = set()
_SPECS: Dict[str, List["FaultSpec"]] = {}
_FIRED: Dict[str, int] = {}
_ARMED = False          # fast-path flag: fire() is one check when clear


class FaultSpec:
    """One armed fault: what happens (`exc` to raise, or `payload` to
    hand the site) and when (`after` skipped hits, then up to `times`
    firings, each gated by `prob` on the spec's seeded stream)."""

    __slots__ = ("exc", "payload", "times", "after", "prob", "_rng",
                 "hits", "fired")

    def __init__(self, exc: Optional[BaseException] = None,
                 payload: Any = None, times: int = 1, after: int = 0,
                 prob: Optional[float] = None, seed: int = 0):
        if exc is not None and payload is not None:
            raise ValueError("a FaultSpec raises OR yields a payload")
        self.exc = exc
        self.payload = payload
        self.times = int(times)
        self.after = int(after)
        self.prob = prob
        self._rng = random.Random(seed)
        self.hits = 0
        self.fired = 0

    def exhausted(self) -> bool:
        return self.times >= 0 and self.fired >= self.times

    def should_fire(self) -> bool:
        """Advance this spec's trigger state by one site hit."""
        if self.exhausted():
            return False
        self.hits += 1
        if self.hits <= self.after:
            return False
        if self.prob is not None and self._rng.random() >= self.prob:
            return False
        self.fired += 1
        return True


def register_point(name: str) -> str:
    """Declare an injection point (idempotent; import-time)."""
    _POINTS.add(name)
    return name


def points() -> List[str]:
    """Every declared injection point, sorted."""
    return sorted(_POINTS)


def inject(name: str, *, exc: Optional[BaseException] = None,
           payload: Any = None, times: int = 1, after: int = 0,
           prob: Optional[float] = None, seed: int = 0) -> FaultSpec:
    """Arm `name`. Unknown points are an error — a typo'd name would
    otherwise silently never fire. `times=-1` means unbounded."""
    global _ARMED
    if name not in _POINTS:
        raise KeyError(f"unknown fault point {name!r}; registered: "
                       f"{points()}")
    spec = FaultSpec(exc=exc, payload=payload, times=times, after=after,
                     prob=prob, seed=seed)
    _SPECS.setdefault(name, []).append(spec)
    _ARMED = True
    return spec


@contextmanager
def injected(name: str, **kw):
    """Scoped arming for tests: disarms this spec on exit."""
    spec = inject(name, **kw)
    try:
        yield spec
    finally:
        _remove(name, spec)


def _remove(name: str, spec: FaultSpec):
    global _ARMED
    lst = _SPECS.get(name, [])
    if spec in lst:
        lst.remove(spec)
    if not lst:
        _SPECS.pop(name, None)
    _ARMED = bool(_SPECS)


def clear(name: Optional[str] = None):
    """Disarm one point (or all); firing counts survive for assertions
    until cleared with `reset_counts`."""
    global _ARMED
    if name is None:
        _SPECS.clear()
    else:
        _SPECS.pop(name, None)
    _ARMED = bool(_SPECS)


def reset_counts():
    _FIRED.clear()


def fired_counts() -> Dict[str, int]:
    """{point: times it actually fired} since the last reset_counts."""
    return dict(_FIRED)


def active() -> Dict[str, int]:
    """{point: number of live (non-exhausted) specs}."""
    return {k: sum(1 for s in v if not s.exhausted())
            for k, v in _SPECS.items() if v}


def fire(name: str, default: Any = None) -> Any:
    """Injection site. Raises the armed exception, or returns the armed
    payload, or `default` when nothing fires. Call sites must have
    registered `name` (checked when armed, free when not)."""
    if not _ARMED:
        return default
    specs = _SPECS.get(name)
    if not specs:
        return default
    for spec in specs:
        if spec.should_fire():
            _FIRED[name] = _FIRED.get(name, 0) + 1
            if spec.exc is not None:
                raise spec.exc
            return spec.payload
    return default
