"""paddle.utils.download — cached artifact fetcher.

Parity: reference `python/paddle/utils/download.py` (get_weights_path_
from_url / get_path_from_url with md5 check). This build runs in
zero-egress environments: a file:// URL or an existing local path is
served from/copied into the cache; a remote URL raises a clear error
unless the artifact is already cached.
"""
from __future__ import annotations

import hashlib
import os
import shutil

__all__ = ["get_weights_path_from_url", "get_path_from_url"]

WEIGHTS_HOME = os.path.expanduser("~/.cache/paddle_tpu/weights")


def _md5check(path, md5sum=None):
    if md5sum is None:
        return True
    h = hashlib.md5()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest() == md5sum


def get_path_from_url(url, root_dir=None, md5sum=None, check_exist=True):
    root_dir = root_dir or WEIGHTS_HOME
    os.makedirs(root_dir, exist_ok=True)
    fname = os.path.basename(url.rstrip("/")) or "artifact"
    cached = os.path.join(root_dir, fname)
    if check_exist and os.path.exists(cached) and _md5check(cached, md5sum):
        return cached
    if url.startswith("file://"):
        src = url[len("file://"):]
    elif os.path.exists(url):
        src = url
    else:
        raise RuntimeError(
            f"cannot fetch {url!r}: this build has no network egress and "
            f"the artifact is not cached at {cached}. Place the file there "
            "or pass a local/file:// path.")
    shutil.copyfile(src, cached)
    if not _md5check(cached, md5sum):
        raise RuntimeError(f"md5 mismatch for {cached}")
    return cached


def get_weights_path_from_url(url, md5sum=None):
    return get_path_from_url(url, WEIGHTS_HOME, md5sum)
