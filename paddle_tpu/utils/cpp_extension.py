"""Custom-op registration — the out-of-tree op ABI.

Parity: reference custom-op stack — C++ `PD_BUILD_OP` + `paddle.utils.
cpp_extension` (builds a shared object against `paddle/phi/api/ext/
op_meta_info.h`, loaded via `load()`/`CustomOpKernelContext`) and the C
plugin ABI (`paddle/phi/capi/`).

TPU-native: a custom op is a jax-traceable callable (jnp composition or a
Pallas kernel) registered under a name — it rides the same dispatch
funnel as built-in ops (AMP hooks, profiler spans, NaN checks, tape
autograd via jax.vjp, or an explicit custom vjp). The C++-compilation
path of the reference collapses: XLA/Mosaic compile the kernel; there is
no ABI boundary to build against. `load()` is kept for source-compat and
returns the registered-op namespace.
"""
from __future__ import annotations

from typing import Callable, Dict, Optional

import jax

__all__ = ["CustomOpRegistry", "register_op", "get_op", "custom_ops",
           "load"]


class _OpNamespace:
    """Attribute access to registered ops (the `module.op_name` surface the
    reference's load() returns)."""

    def __init__(self, registry):
        object.__setattr__(self, "_registry", registry)

    def __getattr__(self, name):
        try:
            return self._registry[name]
        except KeyError:
            raise AttributeError(f"no custom op {name!r} registered")


_REGISTRY: Dict[str, Callable] = {}
custom_ops = _OpNamespace(_REGISTRY)


def register_op(name: str, fn: Optional[Callable] = None, *,
                vjp: Optional[Callable] = None,
                infer_shape: Optional[Callable] = None,
                infer_dtype: Optional[Callable] = None):
    """Register `fn(*arrays) -> array(s)` as op `name`.

    Usable as a decorator::

        @register_op("fused_tanh_scale")
        def fused_tanh_scale(x, scale=1.0):
            return jnp.tanh(x) * scale

    The returned callable takes/returns Tensors through the dispatch
    funnel. `vjp(primals, cotangents) -> input cotangents` installs a
    custom gradient (the custom-op backward of PD_BUILD_GRAD_OP);
    without it jax.vjp differentiates the forward automatically.
    infer_shape/infer_dtype are accepted for API parity (jax infers both).
    """
    def deco(f):
        from ..ops.dispatch import apply_op

        raw = f
        if vjp is not None:
            @jax.custom_vjp
            def cored(*arrays):
                return raw(*arrays)

            def fwd(*arrays):
                return raw(*arrays), arrays

            def bwd(res, g):
                return tuple(vjp(res, g))

            cored.defvjp(fwd, bwd)
            call_target = cored
        else:
            call_target = raw

        def wrapper(*args, **kwargs):
            return apply_op(name, call_target, *args, **kwargs)

        wrapper.raw = raw
        wrapper.op_name = name
        _REGISTRY[name] = wrapper
        return wrapper

    if fn is not None:
        return deco(fn)
    return deco


def get_op(name: str) -> Callable:
    return _REGISTRY[name]


def load(name=None, sources=None, **kwargs):
    """Source-compat with paddle.utils.cpp_extension.load: the reference
    compiles C++ sources against the custom-op ABI; here kernels are
    jax/Pallas callables registered with `register_op`, so load() returns
    the live op namespace (and ignores `sources`)."""
    return custom_ops


class CppExtension:
    """Parity: cpp_extension.CppExtension — a setuptools Extension spec
    for a custom-op shared library. In this build the native toolchain
    compiles plain C extensions (see _native/); kwargs are carried for
    the setup() below."""

    def __init__(self, sources=None, *args, **kwargs):
        self.sources = list(sources or [])
        self.kwargs = kwargs
        self.name = kwargs.get("name")


class CUDAExtension(CppExtension):
    """Accepted for source compatibility; CUDA sources cannot build in
    the TPU image — setup() raises if any .cu file is listed."""


def get_build_directory(verbose=False):
    import os
    d = os.environ.get("PADDLE_EXTENSION_DIR",
                       os.path.expanduser("~/.cache/paddle_tpu/extensions"))
    os.makedirs(d, exist_ok=True)
    return d


def setup(name=None, ext_modules=None, **kwargs):
    """Parity: cpp_extension.setup — build custom-op extensions with
    setuptools. C++ sources build as plain C extensions (the custom-op
    ABI here is the python register_op registry + ctypes, no pybind11);
    .cu sources are rejected with a clear error."""
    exts = ext_modules if isinstance(ext_modules, (list, tuple)) else \
        ([ext_modules] if ext_modules else [])
    for e in exts:
        srcs = getattr(e, "sources", [])
        if any(str(s).endswith((".cu", ".cuh")) for s in srcs):
            raise RuntimeError(
                "CUDA sources cannot be built in the TPU image; implement "
                "the kernel in Pallas (jax.experimental.pallas) and attach "
                "it with register_op instead")
    import setuptools
    from setuptools import Extension
    st_exts = [Extension(getattr(e, "name", None) or name,
                         sources=getattr(e, "sources", []))
               for e in exts]
    return setuptools.setup(name=name, ext_modules=st_exts,
                            script_args=kwargs.pop("script_args",
                                                   ["build_ext", "--inplace"]),
                            **kwargs)


__all__ += ["CppExtension", "CUDAExtension", "setup",
            "get_build_directory"]
