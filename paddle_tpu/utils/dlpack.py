"""paddle.utils.dlpack — zero-copy tensor exchange.

Parity: reference `python/paddle/utils/dlpack.py` (to_dlpack /
from_dlpack over the DLPack protocol). TPU-native: jax arrays implement
`__dlpack__`; host-side interop (numpy/torch-cpu) goes through the
standard capsule protocol.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["to_dlpack", "from_dlpack"]


def to_dlpack(x):
    """Tensor -> DLPack capsule (via the array's __dlpack__)."""
    d = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    return d.__dlpack__()


def from_dlpack(capsule_or_array):
    """DLPack capsule / any __dlpack__-bearing object -> Tensor."""
    arr = jax.numpy.from_dlpack(capsule_or_array)
    return Tensor(arr)
