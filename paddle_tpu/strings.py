"""String tensor ops — the `phi/kernels/strings/` analog.

Parity: reference StringTensor (`paddle/phi/core/string_tensor.h`) with
its kernel set `strings_empty/strings_lower/strings_upper`
(`paddle/phi/kernels/strings/strings_lower_upper_kernel.h`, unicode-aware
case conversion in `strings/unicode.h`). The reference exposes these to
serving preprocessing (faster_tokenizer); here the same surface is a
host-side object array — string data never belongs on the TPU, and the
reference's CPU kernels are host-side too.
"""
from __future__ import annotations

import numpy as np

__all__ = ["StringTensor", "empty", "lower", "upper"]


class StringTensor:
    """A dense tensor of variable-length unicode strings."""

    def __init__(self, data, name=None):
        if isinstance(data, StringTensor):
            data = data._data
        self._data = np.asarray(data, dtype=object)
        self.name = name

    @property
    def shape(self):
        return list(self._data.shape)

    def numpy(self):
        return self._data

    def tolist(self):
        return self._data.tolist()

    def __getitem__(self, idx):
        out = self._data[idx]
        return out if isinstance(out, str) else StringTensor(out)

    def __repr__(self):
        return f"StringTensor(shape={self.shape}, data={self._data!r})"


def empty(shape, name=None):
    """strings_empty kernel: a StringTensor of empty strings."""
    arr = np.full(tuple(shape), "", dtype=object)
    return StringTensor(arr)


def _case_map(x, fn, use_utf8_encoding):
    arr = x._data if isinstance(x, StringTensor) else \
        np.asarray(x, dtype=object)
    if use_utf8_encoding:
        # ASCII-only conversion (the reference's utf8 byte fast path):
        # only code points < 128 change case, multibyte chars pass through
        delta = -32 if fn == "upper" else 32
        lo, hi = ("a", "z") if fn == "upper" else ("A", "Z")
        table = {c: c + delta for c in range(ord(lo), ord(hi) + 1)}
        out = np.frompyfunc(lambda s: s.translate(table), 1, 1)(arr)
    else:
        out = np.frompyfunc(lambda s: getattr(s, fn)(), 1, 1)(arr)
    return StringTensor(out)


def lower(x, use_utf8_encoding=False, name=None):
    """strings_lower kernel (unicode-aware by default)."""
    return _case_map(x, "lower", use_utf8_encoding)


def upper(x, use_utf8_encoding=False, name=None):
    """strings_upper kernel."""
    return _case_map(x, "upper", use_utf8_encoding)
