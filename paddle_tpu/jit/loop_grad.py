"""Reverse-mode AD through converted loops: the lax.scan lowering.

Parity: the reference trains through converted loops — `WhileGradOp`
(/root/reference/paddle/fluid/operators/controlflow/while_op.cc:319, grad
maker :612) plus `append_backward` over `static.nn.while_loop`
(/root/reference/python/paddle/static/nn/control_flow.py:682) push each
iteration's activations on a stack and replay them backwards. The
TPU-native counterpart: a converted loop whose trip count is STATIC at
trace time lowers to `jax.lax.scan` — which has reverse-mode AD built in
(XLA stacks the residuals; `jax.checkpoint` composes for memory) —
recorded as ONE op on the eager tape, so `.backward()` differentiates
through the whole loop instead of falling back to eager (VERDICT r4
missing #2). In JAX every shape-derived bound is a concrete int at trace
time, so the loops that matter in training (decoder blocks over
positions/layers/rows) scan; a bound carried in tensor DATA has no
static trip count and keeps the counted eager fallback.

Two structural problems and their solutions:

* The loop body closes over parameters (`self.w`) and pre-loop
  activations. Wrapped naively into one op, those become CONSTANTS of
  the scan closure and silently receive no gradient. Solution: a
  dispatch-level capture hook (`ops.dispatch._loop_capture`) observes
  every op's input tensors while iteration 0 runs as the probe;
  grad-requiring tensors the probe did not itself produce are EXTERNALS,
  threaded as differentiable inputs of the scan op recompute-style
  (fleet.utils.recompute swaps `_data` the same way). A second capture
  stays active during the scan trace itself: an external that only
  appears in a branch the probe did not take (concrete predicate at
  iteration 0) is detected LATE and the lowering is abandoned for the
  host loop — a declined lowering is never a silently-wrong gradient.
* `break` cannot stop a scan, so it lowers to masked early exit: the
  flag rides the carry, and once set every later iteration selects the
  pre-break values through `jnp.where` — reverse AD flows only through
  the iterations that actually ran.

The probe IS iteration 0 (its python-level side effects run exactly
once, like eager — the same probe-as-iteration-0 contract as
dy2static._run_for_iter); the scan covers iterations 1..n-1.
"""
from __future__ import annotations

import contextlib

import jax
import jax.numpy as jnp

__all__ = ["try_scan_range", "try_scan_iter"]


class _Capture:
    """Dispatch-hook observer: collects grad-requiring op-input Tensors
    that the observed region did not itself produce (= the loop body's
    external inputs: parameters, pre-loop activations)."""

    def __init__(self, exclude_ids=()):
        self.exclude = set(exclude_ids)
        self.produced = set()
        self.externals = []
        self._seen = set()
        self._pinned = []

    def pin(self, objs):
        """Exclude `objs` AND hold strong references to them: an id() in
        `exclude` is only meaningful while its object is alive — if a
        wrapper Tensor were GC'd mid-trace, CPython could hand its id to
        a genuinely-late grad-requiring tensor, which would then be
        silently excluded from the late-external check (ADVICE r5 #2)."""
        self._pinned.extend(objs)
        self.exclude.update(id(o) for o in objs)

    def observe(self, in_tensors, out_tensors):
        for t in in_tensors:
            i = id(t)
            if (not t.stop_gradient and i not in self.produced
                    and i not in self.exclude and i not in self._seen):
                self._seen.add(i)
                self.externals.append(t)
        for t in out_tensors:
            self.produced.add(id(t))


@contextlib.contextmanager
def _capturing(cap):
    """Install `cap` as the dispatch capture hook. A grad-mode nested
    probe deliberately MASKS an outer capture: the outer loop
    re-discovers anything it misses through its own late-capture check,
    trading a possible outer decline for never observing doubly.

    cap=None (probe under no_grad) must NOT clear an outer hook: an
    inner loop attempted inside an outer scan step (which runs under
    no_grad) is the outer capture's only window onto the inner body's
    parameter reads — masking it would bake those parameters into the
    outer scan as constants with silently-zero gradients."""
    from ..ops import dispatch
    prev = dispatch._loop_capture
    if cap is not None:
        dispatch._loop_capture = cap
    try:
        yield
    finally:
        dispatch._loop_capture = prev


def _rng_snapshot():
    """(stream, key-object) pairs for every live RNG stream — draws
    REBIND the key object (see dy2static._rng_fingerprint), so identity
    comparison detects a draw even for traced keys, and keeping the
    object allows restoration after an abandoned scan trace (a draw
    inside the trace would otherwise leak a TRACER into live RNG
    state). The tracker object + its CURRENT substream names ride along:
    a substream first registered inside a trace is invisible to the
    pairs, yet a draw from it leaves a tracer-valued key too (ADVICE
    r5 #4) — so new names count as an RNG effect and are dropped on
    restore."""
    from ..framework import random as _random
    pairs = [(_random._global, _random._global._key)]
    tracker, names = None, frozenset()
    try:
        from ..distributed.fleet.mpu import get_rng_state_tracker
        tracker = get_rng_state_tracker()
        names = frozenset(tracker.states_)
        for _name, st in sorted(tracker.states_.items()):
            pairs.append((st, st._key))
    except Exception:
        pass
    return {"pairs": pairs, "tracker": tracker, "names": names}


def _rng_changed(snap):
    if any(st._key is not key for st, key in snap["pairs"]):
        return True
    tracker = snap["tracker"]
    # a substream registered since the snapshot is an RNG effect of the
    # observed region (its draws don't rebind any snapshotted key)
    return tracker is not None and \
        frozenset(tracker.states_) != snap["names"]


def _rng_restore(snap):
    for st, key in snap["pairs"]:
        st._key = key
    tracker = snap["tracker"]
    if tracker is not None:
        for name in list(tracker.states_):
            if name not in snap["names"]:
                # registered inside the abandoned trace: its key may be a
                # tracer — keeping it would poison every later draw
                del tracker.states_[name]


def _normalize_carry(vals):
    """Probe outputs (tgt, *carried) -> list of Tensors, or None when a
    value cannot enter a scan carry (lists, None, _Undefined objects).
    Python scalars (incl. the False of a never-tripped break flag) become
    0-d arrays; a body that then needs them as PYTHON values fails the
    scan trace and falls back to the host loop."""
    from ..core.tensor import Tensor
    out = []
    for v in vals:
        if isinstance(v, Tensor):
            out.append(v)
        elif isinstance(v, (bool, int, float)) or (
                hasattr(v, "dtype") and hasattr(v, "shape")):
            out.append(Tensor(jnp.asarray(v)))
        else:
            return None
    return out


def _as_array(x):
    from ..core.tensor import Tensor
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


@contextlib.contextmanager
def _lowering_scope(externals, ext_arrays, late, check_late, extra=None):
    """Inside the scan closure: swap the externals' `_data` for the
    trace's input arrays (recompute-style; `extra` = (tensor, array)
    for a scanned sequence), and install `late` as the capture hook —
    but ONLY when its verdict will be read (check_late is grad mode at
    attempt time): under no_grad an OUTER loop's capture must keep
    observing this body, or a nested lowering would hide parameter
    reads from the outer late-external check (silent zero grads)."""
    from ..ops import dispatch
    saved = [p._data for p in externals]
    extra_saved = extra[0]._data if extra is not None else None
    prev_cap = dispatch._loop_capture
    if check_late:
        dispatch._loop_capture = late
    try:
        if extra is not None:
            extra[0]._data = extra[1]
        for p, a in zip(externals, ext_arrays):
            p._data = a
        yield
    finally:
        if extra is not None:
            extra[0]._data = extra_saved
        for p, s in zip(externals, saved):
            p._data = s
        dispatch._loop_capture = prev_cap


def _step_body(body_fn, late, first_arg, carry_vals, brk_idx):
    """One scan-step body invocation over arrays, shared by both loop
    families: wrap the carries (registering the wrappers with the late
    capture's exclude set), run the body under no_grad (the OUTER scan
    op owns the tape node), normalize outputs to arrays, and apply the
    break mask — the flag is read at iteration ENTRY, matching the host
    loop's check-before-body semantics. Returns (new_vals, done_flag)
    with done_flag None when no break flag rides the carry."""
    from ..core import autograd
    from ..core.tensor import Tensor
    wraps = [Tensor(a) for a in carry_vals]
    fw = Tensor(first_arg)
    late.pin(wraps + [fw])
    with autograd.no_grad():
        o = body_fn(fw, *wraps[1:])
    o = tuple(o) if isinstance(o, (list, tuple)) else (o,)
    new = [_as_array(x) for x in o]
    done = None
    if brk_idx is not None:
        done = jnp.asarray(carry_vals[1 + brk_idx]).astype(bool) \
            .reshape(())
        new = [jnp.where(done, c, n_) for c, n_ in zip(carry_vals, new)]
    return new, done


def _record_scan(name, scan_closed, inputs, snap, late, check_late):
    """Run the taped scan op; decline (return a reason string) on any
    trace failure, on an RNG draw inside the trace (a branch the probe
    did not take — the traced key is rolled back), or on a late
    external. `check_late` is False under no_grad: with no tape there is
    no gradient to get wrong, so a param read inside the trace must not
    veto the lowering. Returns (results_tuple, None) or (None, reason).

    In EAGER mode a shape-only pre-trace runs first so a decline costs
    one abstract trace, not a full discarded execution of the loop
    (under an outer jit everything is abstract anyway — and eval_shape
    of a closure over the outer trace's tracers would not be safe)."""
    import jax as _jax
    from ..ops.dispatch import apply_op

    eager = not any(isinstance(t._data, _jax.core.Tracer) for t in inputs)
    if eager:
        try:
            _jax.eval_shape(scan_closed, *[
                _jax.ShapeDtypeStruct(tuple(t._data.shape), t._data.dtype)
                for t in inputs])
        except Exception:
            _rng_restore(snap)
            return None, "trace-failed"
        if _rng_changed(snap):
            _rng_restore(snap)
            return None, "rng-draw"
        if check_late and late.externals:
            _rng_restore(snap)
            return None, "late-external"
    try:
        res = apply_op(name, scan_closed, *inputs)
    except Exception:
        _rng_restore(snap)
        return None, "trace-failed"
    if _rng_changed(snap):
        _rng_restore(snap)
        return None, "rng-draw"
    if check_late and late.externals:
        _rng_restore(snap)
        return None, "late-external"
    res = tuple(res) if isinstance(res, (list, tuple)) else (res,)
    return res, None


def try_scan_range(i0, stop, sp, body_fn, carried, brk_idx=None):
    """Scan-lower a CONCRETE-bound `for k in range(i0, stop, sp)` whose
    trip count exceeds the unroll limit.

    Protocol (consumed by dy2static._run_for_range):
      ("done", results)          — fully lowered; results = (tgt, *carried)
      ("probed", reason, i, vals) — iteration 0 ran as the probe; the
                                 caller continues its host loop from i
                                 with vals (no body re-run). `reason`
                                 names why (rng-draw / carry-type /
                                 late-external / trace-failed), or None
                                 when nothing declined (a concrete break
                                 simply ended the loop at iteration 0).
    """
    from ..core import autograd
    from ..core.tensor import Tensor

    grad_on = autograd.is_grad_enabled()
    # NOTE: carry-init tensors are deliberately NOT excluded from the
    # capture — the body may read the same object through a closure name
    # too, and only the external `_data` swap makes that read traced. A
    # tensor that is both carry and external costs one redundant input
    # (its external slot gets a zero cotangent when the closure read
    # does not exist); excluding it would silently drop the closure
    # path's gradient.
    cap = _Capture()
    snap = _rng_snapshot()
    with _capturing(cap if grad_on else None):
        out = body_fn(i0, *carried)
    vals = tuple(out) if isinstance(out, (list, tuple)) else (out,)
    i_next = i0 + sp

    def probed(reason):
        return ("probed", reason, i_next, vals)

    if _rng_changed(snap):
        return probed("rng-draw")  # per-iteration draws: host loop keeps them
    remaining = len(range(i_next, stop, sp))
    if remaining == 0:
        return ("done", vals)
    if brk_idx is not None:
        flag = vals[1 + brk_idx]
        if not isinstance(flag, Tensor) and flag:
            return probed(None)  # concrete break: host check stops the loop
    init = _normalize_carry(vals)
    if init is None:
        return probed("carry-type")
    externals = cap.externals
    n_c = len(init)
    late = _Capture(exclude_ids=[id(p) for p in externals])
    k1 = jnp.asarray(i_next)

    def scan_closed(*arrs):
        with _lowering_scope(externals, arrs[n_c:], late, grad_on):
            def step(carry, _):
                k, cur = carry[0], carry[1:]
                new, done = _step_body(body_fn, late, k, cur, brk_idx)
                k_next = k + sp if done is None \
                    else jnp.where(done, k, k + sp)
                return (k_next,) + tuple(new), None

            carry0 = (k1,) + tuple(arrs[:n_c])
            final, _ = jax.lax.scan(step, carry0, None, length=remaining)
            return final[1:]                      # drop the counter

    res, reason = _record_scan("dy2static_scan_for", scan_closed,
                               list(init) + list(externals), snap, late,
                               check_late=grad_on)
    return ("done", res) if res is not None else probed(reason)


def try_scan_iter(seq, body_fn, vals, externals, brk_idx=None):
    """Scan-lower `for x in seq` over rows 1..n-1, after the caller's
    probe consumed row 0 (vals = its outputs (tgt, *carried)). `seq`
    itself is a differentiable input — cotangents flow into the rows
    through the scan's xs. Returns the final (tgt, *carried) tuple of
    Tensors paired with None, or (None, reason) — the caller continues
    unrolling from row 1."""
    from ..core import autograd
    from ..core.tensor import Tensor

    grad_on = autograd.is_grad_enabled()
    init = _normalize_carry(vals)
    if init is None:
        return None, "carry-type"
    if brk_idx is not None:
        flag = vals[1 + brk_idx]
        if not isinstance(flag, Tensor) and flag:
            return None, None  # concrete break after row 0: host handles it
    n_c = len(init)
    snap = _rng_snapshot()
    late = _Capture(exclude_ids=[id(p) for p in externals] + [id(seq)])

    def scan_closed(seq_a, *arrs):
        # seq swaps too: a closure read of the sequence (`xs[0]` inside
        # `for x in xs`) must trace through the same input the scan's
        # xs come from
        with _lowering_scope(externals, arrs[n_c:], late, grad_on,
                             extra=(seq, seq_a)):
            def step(carry, row):
                new, _done = _step_body(body_fn, late, row, carry,
                                        brk_idx)
                return tuple(new), None

            final, _ = jax.lax.scan(step, tuple(arrs[:n_c]), seq_a[1:])
            return final

    return _record_scan("dy2static_scan_iter", scan_closed,
                        [seq] + list(init) + list(externals), snap, late,
                        check_late=grad_on)
