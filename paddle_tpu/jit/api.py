"""to_static: stateful eager code -> one compiled XLA program.

Parity: reference `python/paddle/jit/` — `to_static`
(dy2static/program_translator.py:377) and the SOT bytecode tracer
(jit/sot/). The reference captures python bytecode into StatementIR and
replays it as a static program; here the eager tape is already
jax-traceable, so to_static only has to *functionalize state*:

  1. collect state (model params/buffers via `raw_state()`, optimizer
     accumulators, the global RNG key) into a pytree,
  2. jax.jit a wrapper that loads the state, runs the python function
     (tape records ops on tracers; `.backward()` unrolls into the trace),
     and returns (outputs, new_state),
  3. write the new state back into the live objects after each call.

Guards (SOT's graph-break keys) = the hash of all non-Tensor arguments +
pytree structure; a new combination triggers a retrace, same as the
reference's guard-failure recompilation.

Graph breaks (SOT-lite, VERDICT r2 missing #1): the reference's SOT
bytecode VM falls back to eager execution when it meets untraceable
python (jit/sot/, eval_frame.c:442 hooks CPython's frame evaluation);
its AST mode (full_graph=True) errors instead. Here the same contract
rides the guard cache: a call whose trace dies on data-dependent python
control flow (jax ConcretizationTypeError family) restores the concrete
state the aborted trace clobbered, stores an eager-fallback marker under
that guard key, warns once, and runs the original function eagerly —
to_static never breaks a model that runs in eager. full_graph=True
keeps the hard error.
"""
from __future__ import annotations

import functools
import os
import pickle
import time
import warnings
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..framework import random as _random
from .. import profiler as _profiler
from ..profiler import compile_log as _compile_log

__all__ = ["to_static", "not_to_static", "TracedFunction", "save", "load",
           "functional_call", "ignore_module", "to_static_report"]

# Every function-level eager fallback lands here (VERDICT r4 item 9):
# the observable inventory of what did NOT compile and why. Capped so a
# long-lived serving process whose traffic keeps hitting graph breaks
# cannot grow it unboundedly (ADVICE r5 #3): the most recent
# _FALLBACK_REGISTRY_MAX entries are kept, older ones are dropped and
# counted.
_fallback_registry: List[dict] = []
_FALLBACK_REGISTRY_MAX = 256
_fallback_dropped = [0]


def _record_fallback(entry: dict):
    _fallback_registry.append(entry)
    overflow = len(_fallback_registry) - _FALLBACK_REGISTRY_MAX
    if overflow > 0:
        del _fallback_registry[:overflow]
        _fallback_dropped[0] += overflow


def to_static_report(reset=False):
    """Fallback observability: which functions fell back to eager (with
    the error that broke them) plus dy2static's per-reason break/decline
    counters. The report is the SOT-gap inventory — it measures how much
    of a workload runs eager before deciding whether a bytecode tracer
    (reference jit/sot/, ~35k LoC) would ever pay for itself.
    `eager_fallbacks` holds the most recent entries (bounded);
    `eager_fallbacks_dropped` counts what aged out of the window."""
    from . import dy2static
    from ..analysis import purity
    rep = {
        "eager_fallbacks": list(_fallback_registry),
        "eager_fallbacks_dropped": _fallback_dropped[0],
        "break_counters": dy2static.fallback_counters(),
        # tpu-lint A5 runtime promotions (shared Diagnostic dicts):
        # scan/while bodies that printed at trace time, loops kept eager
        # because their bodies mutate non-carried state, out-of-trace
        # collective rejections — see ANALYSIS.md
        "purity_diagnostics": [d.to_dict() for d in purity.snapshot()],
        # compile-event timeline (ISSUE 11): every trace/retrace/AST
        # rescue/eager fallback + serving ProgramCache compile, with
        # durations — a compile storm is a counter, not a debugger hunt
        "compile_events": _compile_log.events(),
        "compile_counters": _compile_log.counters(),
        "compile_seconds": _compile_log.duration_totals_s(),
        "compile_events_dropped": _compile_log.dropped(),
    }
    if reset:
        _fallback_registry.clear()
        _fallback_dropped[0] = 0
        dy2static.reset_fallback_counters()
        purity.reset()
        _compile_log.reset()
    return rep


def _is_tensor(x):
    return isinstance(x, Tensor)


def _hashable(x):
    try:
        hash(x)
        return x
    except TypeError:
        return repr(x)


class _StateBundle:
    """Collects/loads the mutable state of a set of stateful objects
    (Layers, Optimizers — anything with raw_state/load_raw_state)."""

    def __init__(self, objects):
        self.objects = [o for o in objects if o is not None]

    def collect(self):
        state = {}
        for i, obj in enumerate(self.objects):
            state[str(i)] = obj.raw_state()
        state["__rng__"] = _random.get_rng_state()
        return state

    def load(self, state):
        for i, obj in enumerate(self.objects):
            if str(i) in state:
                obj.load_raw_state(state[str(i)])
        if "__rng__" in state:
            _random.set_rng_state(state["__rng__"])


class _EagerFallbackType:
    def __repr__(self):
        return "<EAGER-FALLBACK>"


_EAGER_FALLBACK = _EagerFallbackType()

class _CacheEntry:
    """One guard key's compiled program + its accounting hooks:
    `avals` (ShapeDtypeStructs of the LAST-compiled call's (state,
    tensor) pytrees) lets `cost_report()` re-lower the program without
    holding data; `sg_flags`/`grad_mode` pin the trace-time inputs the
    closure reads off the instance and the ambient grad state (both are
    guard key axes — re-lowering under the LAST call's values would
    account a different program); `compile_ms` is the compiling call's
    trace+compile+execute wall (logged to the compile-event ring).

    One guard key can hold MORE than one XLA program: an optimizer that
    creates accumulators lazily (AdamW moments on the first step) grows
    the donated state pytree between call 1 and call 2, and jax.jit
    recompiles underneath the guard cache. Calls keep being timed until
    the jax-side program count stops growing (`stable`); each growth is
    logged as a `retrace` (jax_internal) and refreshes `avals`, so
    cost_report()/bench account the STEADY-STATE program, not the
    run-once cold-start one, and the compile-event counters see every
    real compile. After stabilization the hot path is back to two
    attribute checks."""

    __slots__ = ("jitted", "out_box", "avals", "fresh", "compile_ms",
                 "sg_flags", "grad_mode", "stable", "n_programs")

    def __init__(self, jitted, out_box):
        self.jitted = jitted
        self.out_box = out_box
        self.avals = None
        self.fresh = True
        self.compile_ms = None
        self.sg_flags = None
        self.grad_mode = True
        self.stable = False
        self.n_programs = None

    def jax_cache_size(self):
        """jax-side compiled-program count for this jit wrapper (None
        when the private probe is unavailable — accounting then
        degrades to first-call-only, never breaks the call)."""
        try:
            return int(self.jitted._cache_size())
        except Exception:
            return None


def _graph_break_errors():
    """Exception types that mean 'this python needs a value a tracer
    cannot provide' — the same class of failures SOT graph-breaks on
    (data-dependent if/while, int()/bool()/np.asarray() on a tracer,
    tensor-dependent shapes)."""
    import jax.errors as je
    from .dy2static import DygraphToStaticBreak
    # note: in this jax only TracerBoolConversionError subclasses
    # ConcretizationTypeError; the int/array variants are siblings
    return (je.ConcretizationTypeError,
            je.TracerIntegerConversionError,
            je.TracerArrayConversionError,
            je.NonConcreteBooleanIndexError,
            je.UnexpectedTracerError,     # side-effect leaks out of the trace
            DygraphToStaticBreak)         # rewritten construct won't lower


class TracedFunction:
    """The compiled callable returned by to_static."""

    def __init__(self, fn, state_objects=None, donate_state=True,
                 input_spec=None, full_graph=False):
        from ..nn.layer.layers import Layer
        self._orig_fn = fn
        if isinstance(fn, Layer):
            self._callable = fn.forward
            state_objects = [fn] + list(state_objects or [])
        else:
            self._callable = fn
            state_objects = list(state_objects or [])
        self._bundle = _StateBundle(state_objects)
        self._cache: Dict[Any, Any] = {}
        self._donate = donate_state
        self._input_spec = list(input_spec) if input_spec else None
        self._full_graph = bool(full_graph)
        self._fallback_count = 0   # observability: how many guard keys broke
        self._compiled_count = 0   # programs ever compiled (trace + retraces)
        self.__wrapped__ = fn
        functools.update_wrapper(self, self._callable)

    def _check_spec(self, tensor_arrays):
        """input_spec-driven guard (parity: the reference's
        StaticFunction input_spec contract): every call's tensor args must
        match the declared dtypes and static dims (-1/None = dynamic)."""
        spec = self._input_spec
        if len(tensor_arrays) < len(spec):
            raise TypeError(
                f"to_static(input_spec=...) declared {len(spec)} tensor "
                f"inputs, call passed {len(tensor_arrays)}")
        for i, (s, a) in enumerate(zip(spec, tensor_arrays)):
            want = tuple(getattr(s, "shape", ()))
            if len(want) != a.ndim:
                raise TypeError(
                    f"input {i} ({getattr(s, 'name', None) or i}): rank "
                    f"{a.ndim} does not match input_spec rank {len(want)}")
            for d, (w, g) in enumerate(zip(want, a.shape)):
                if w not in (-1, None) and w != g:
                    raise TypeError(
                        f"input {i} dim {d}: got {g}, input_spec demands "
                        f"{w}")
            sd = getattr(s, "dtype", None)
            if sd is not None and str(a.dtype) != str(sd):
                raise TypeError(
                    f"input {i}: dtype {a.dtype} != input_spec {sd}")

    def warmup(self):
        """Ahead-of-time compile from a fully static input_spec (the
        reference's declarative-tracing mode: no example call needed)."""
        import jax.numpy as jnp
        if not self._input_spec:
            raise ValueError("warmup() needs to_static(input_spec=[...])")
        args = []
        for s in self._input_spec:
            shape = tuple(getattr(s, "shape", ()))
            if any(d in (-1, None) for d in shape):
                raise ValueError(
                    "warmup() needs fully static input_spec shapes")
            args.append(Tensor(jnp.zeros(shape,
                                         jnp.dtype(s.dtype or "float32"))))
        self(*args)
        return self

    # -- internals ---------------------------------------------------------
    def _make_jitted(self, treedef, static_leaves, n_tensors):
        bundle = self._bundle
        call = self._callable

        def functional(state, tensor_arrays):
            bundle.load(state)
            leaves = list(static_leaves)
            it = iter(tensor_arrays)
            full = [next(it) if l is _TENSOR_SLOT else l for l in leaves]
            # Tensor args enter as fresh leaf Tensors (stop_gradient like orig)
            args, kwargs = jax.tree_util.tree_unflatten(
                treedef, [Tensor(v, stop_gradient=sg) if isinstance(v, jax.Array) or
                          hasattr(v, "dtype") else v
                          for v, sg in zip(full, self._sg_flags)])
            out = call(*args, **kwargs)
            out_leaves, out_treedef = jax.tree_util.tree_flatten(
                out, is_leaf=_is_tensor)
            out_arrays = [o._data if isinstance(o, Tensor) else o for o in out_leaves]
            new_state = bundle.collect()
            return out_arrays, new_state, out_treedef

        # out_treedef is static per cache entry; capture via closure cell
        out_treedef_box = []

        def jittable(state, tensor_arrays):
            out_arrays, new_state, out_treedef = functional(state, tensor_arrays)
            if not out_treedef_box:
                out_treedef_box.append(out_treedef)
            return out_arrays, new_state

        # Donating the state pytree lets XLA update params/optimizer
        # accumulators in place — without it a training step holds two full
        # copies of the optimizer state (OOM for ~1B params on one chip).
        jitted = jax.jit(jittable, donate_argnums=(0,) if self._donate else ())
        return _CacheEntry(jitted, out_treedef_box)

    def __call__(self, *args, **kwargs):
        if not _to_static_enabled:
            return self._orig_fn(*args, **kwargs)   # jit globally disabled
        if getattr(self._callable, "_not_to_static", False) or \
                getattr(self._orig_fn, "_not_to_static", False):
            # @not_to_static: the function opted out of capture — run it
            # eagerly (the whole-function subset of the reference's
            # call-site graph break, jit/api.py not_to_static)
            return self._callable(*args, **kwargs)
        leaves, treedef = jax.tree_util.tree_flatten((args, kwargs),
                                                     is_leaf=_is_tensor)
        tensor_arrays = []
        static_leaves = []
        sg_flags = []
        for l in leaves:
            if isinstance(l, Tensor):
                tensor_arrays.append(l._data)
                static_leaves.append(_TENSOR_SLOT)
                sg_flags.append(l.stop_gradient)
            else:
                static_leaves.append(l)
                sg_flags.append(True)
        self._sg_flags = sg_flags
        if self._input_spec is not None:
            self._check_spec(tensor_arrays)
        # Guard evaluation: when a Profiler is recording, the key build
        # (closure/global fingerprints + the re-conversion check) gets
        # its own host span (ISSUE 11) — guard time is real per-call
        # work in closure-heavy loops and was invisible before.
        prof = _profiler
        if prof._tracer.enabled:
            with prof.RecordEvent("to_static.guard"):
                key = self._guard_key(treedef, static_leaves,
                                      tensor_arrays, sg_flags)
        else:
            key = self._guard_key(treedef, static_leaves, tensor_arrays,
                                  sg_flags)
        entry = self._cache.get(key)
        if entry is _EAGER_FALLBACK:       # guard hit on a broken graph
            return self._callable(*args, **kwargs)
        if entry is None:
            entry = self._make_jitted(treedef, static_leaves, len(tensor_arrays))
            self._cache[key] = entry
        jitted, out_box = entry.jitted, entry.out_box
        state = self._bundle.collect()
        # time every call until the entry stabilizes: the first call is
        # the trace+compile (a guard miss is only alertable if it
        # carries its cost), and the next call(s) may recompile inside
        # jax when lazily created optimizer state grows the pytree —
        # see _CacheEntry. Steady state pays one attribute check.
        t0 = None if entry.stable else time.perf_counter()
        try:
            out_arrays, new_state = jitted(state, tensor_arrays)
        except _graph_break_errors() as e:
            if self._full_graph:
                raise RuntimeError(
                    "to_static(full_graph=True): tracing hit data-dependent "
                    "python control flow and graph-break fallback is "
                    "disabled. Rewrite with lax.cond/where, or use "
                    "full_graph=False to run this call eagerly. (parity: "
                    "the reference AST dy2static mode errors here too)"
                ) from e
            return self._graph_break(key, state, e, args, kwargs)
        if t0 is not None:
            self._note_compiled(entry, state, tensor_arrays,
                                time.perf_counter() - t0)
        self._bundle.load(new_state)
        self._clear_tracer_grads()
        out_treedef = out_box[0]
        out_leaves = [Tensor(a) if hasattr(a, "dtype") else a for a in out_arrays]
        return jax.tree_util.tree_unflatten(out_treedef, out_leaves)

    def _guard_key(self, treedef, static_leaves, tensor_arrays, sg_flags):
        # sg_flags is read by the traced closure, so it MUST be part of the
        # guard key: two calls with identical shapes but different
        # stop_gradient patterns need distinct compiled programs.
        # The closure signature guards cell CONTENTS (VERDICT r3 weak #8:
        # a closed-over tensor mutated after the first call must retrace,
        # not replay the baked-in constant — the reference's SOT guards on
        # cells the same way).
        closure_sig = self._closure_sig()
        self._refresh_conversion(closure_sig)
        # ambient grad mode is part of the key: the dy2static loop
        # lowerings choose forward-only structures under no_grad, so a
        # trace built in no_grad must not replay for a grad-enabled call
        from ..core import autograd as _autograd
        return (treedef, tuple(_hashable(l) for l in static_leaves),
                tuple((tuple(a.shape), str(a.dtype)) for a in tensor_arrays),
                tuple(sg_flags), closure_sig, self._globals_sig(),
                _autograd.is_grad_enabled())

    def _fn_name(self):
        return getattr(self._callable, "__qualname__",
                       getattr(self._callable, "__name__", "<fn>"))

    def _note_compiled(self, entry, state, tensor_arrays, dt):
        """A still-watched (fresh or not-yet-stable) call just
        finished. Fresh: stamp the entry and log the trace/retrace.
        Warm: if jax recompiled underneath the guard entry (lazily
        created optimizer state grew the donated pytree — see
        _CacheEntry), log it and refresh the entry to the NEW program;
        otherwise mark the entry stable and stop timing calls."""
        if not entry.fresh:
            size = entry.jax_cache_size()
            if size is None or size == entry.n_programs:
                entry.stable = True       # steady state: stop timing
                return
            entry.n_programs = size
            self._stamp_entry(entry, state, tensor_arrays, dt)
            self._compiled_count += 1
            _compile_log.log_event(
                "retrace", name=self._fn_name(), duration_s=dt,
                detail={"jax_internal": True,
                        "programs": self._compiled_count,
                        "cache_size": len(self._cache)})
            return
        entry.fresh = False
        entry.n_programs = entry.jax_cache_size()
        if entry.n_programs is None:
            # no jax-side probe: degrade to first-call-only accounting
            entry.stable = True
        self._stamp_entry(entry, state, tensor_arrays, dt)
        kind = "trace" if self._compiled_count == 0 else "retrace"
        self._compiled_count += 1
        _compile_log.log_event(
            kind, name=self._fn_name(), duration_s=dt,
            detail={"programs": self._compiled_count,
                    "cache_size": len(self._cache)})

    def _stamp_entry(self, entry, state, tensor_arrays, dt):
        """Record the just-compiled call's accounting context on the
        entry: wall time, trace-time sg_flags/grad mode, and the input
        ShapeDtypeStructs cost_report() re-lowers from."""
        entry.compile_ms = round(dt * 1e3, 3)
        from ..core import autograd as _autograd
        entry.sg_flags = tuple(self._sg_flags)
        entry.grad_mode = _autograd.is_grad_enabled()
        try:
            from ..profiler.cost import shape_structs
            # .shape/.dtype stay readable on donated buffers, so the
            # post-call capture is safe even with donate_state=True
            entry.avals = (shape_structs(state),
                           shape_structs(list(tensor_arrays)))
        except Exception:
            entry.avals = None

    def _account_programs(self, account):
        """Shared re-lowering loop under cost_report()/comm_report():
        re-lower every guard-cache program from the ShapeDtypeStructs
        recorded at its last-COMPILED call and hand the Lowered to
        `account` (which returns a dict). No tensor data is touched;
        the live state/flags the re-trace clobbers are restored after
        (asserted by test)."""
        programs = []
        fallbacks = 0
        for entry in self._cache.values():
            if entry is _EAGER_FALLBACK:
                fallbacks += 1
                continue
            if entry.avals is None:
                continue
            state_sds, arrays_sds = entry.avals
            snap = self._bundle.collect()
            # re-lower under the entry's OWN trace-time inputs: the
            # functional closure reads self._sg_flags off the instance
            # and the body may branch on ambient grad mode — both are
            # guard-key axes, so the last call's values can describe a
            # DIFFERENT program than this entry compiled
            from ..core import autograd as _autograd
            prev_flags = self._sg_flags
            prev_grad = _autograd.is_grad_enabled()
            if entry.sg_flags is not None:
                self._sg_flags = list(entry.sg_flags)
            try:
                _autograd.set_grad_enabled(entry.grad_mode)
                rec = account(entry.jitted.lower(state_sds, arrays_sds))
            except Exception as e:   # an accounting must never raise
                rec = {"error": f"{type(e).__name__}: {e}"[:200]}
            finally:
                self._sg_flags = prev_flags
                _autograd.set_grad_enabled(prev_grad)
                # lowering traced the function: restore the concrete
                # state the trace clobbered with tracers
                self._bundle.load(snap)
                self._clear_tracer_grads()
            rec["compile_ms"] = entry.compile_ms
            rec["input_shapes"] = [
                list(s.shape) for s in arrays_sds if hasattr(s, "shape")]
            programs.append(rec)
        return {"function": self._fn_name(),
                "num_programs": len(programs),
                "eager_fallback_keys": fallbacks,
                "programs": programs}

    def cost_report(self) -> dict:
        """Structured FLOPs / HBM-bytes / peak-memory accounting of
        every compiled program in the guard cache (ISSUE 11), via XLA's
        `cost_analysis()` / `memory_analysis()` (`profiler.cost` — see
        its docstring for how to read flops/io_bytes/peak_bytes
        honestly). Each program is re-lowered from the ShapeDtypeStructs
        recorded at its last-COMPILED call (the steady-state program —
        lazily created optimizer state makes the cold-start call 1 a
        run-once program, see _CacheEntry) — no tensor data is touched,
        and with the persistent compilation cache on the re-compile is
        a disk hit. The re-trace runs the python function under abstract
        values, so python-side counters (e.g. an optimizer step count)
        advance by one: call between steps, not mid-step."""
        from ..profiler import cost as _cost
        return self._account_programs(
            lambda lowered: _cost.lowered_cost(lowered).to_dict())

    def comm_report(self, mesh=None) -> dict:
        """Collective-traffic accounting of every compiled program in
        the guard cache (ISSUE 12, beside cost_report): per-program op
        counts and payload bytes per mesh axis from the post-SPMD HLO
        (`profiler.comm` — read its docstring before quoting bytes:
        logical payload, counted once per program, a LOWER bound under
        manual-collective Pallas kernels). `mesh` defaults to the
        ambient hybrid mesh (mesh_scope override, else the fleet.init
        singleton). The top level carries the cross-program aggregate
        (`payload_bytes` / `bytes_per_axis` / `op_counts`) so bench.py
        and dryrun evidence lines can quote one dict. Same re-lowering
        contract as cost_report (state restored, call between steps)."""
        from ..profiler import comm as _comm
        if mesh is None:
            mesh = _comm._default_mesh()
        rep = self._account_programs(
            lambda lowered: _comm.lowered_comm(lowered, mesh=mesh).to_dict())
        total = 0
        per_axis: Dict[str, int] = {}
        counts: Dict[str, int] = {}
        for prog in rep["programs"]:
            if "error" in prog:
                continue
            total += prog.get("payload_bytes", 0)
            for ax, b in (prog.get("bytes_per_axis") or {}).items():
                per_axis[ax] = per_axis.get(ax, 0) + b
            for k, n in (prog.get("op_counts") or {}).items():
                counts[k] = counts.get(k, 0) + n
        rep["payload_bytes"] = total
        rep["bytes_per_axis"] = per_axis
        rep["op_counts"] = counts
        return rep

    def _track_value(self, key, name, v):
        """One signature entry for a guarded value (closure cell or
        module global). Entries carry a type tag ("t"ensor / "s"calar /
        "o"bject / "state") so a version counter can never collide with
        a scalar VALUE (e.g. object-at-version-0 vs the int 0).

        Tensor values are tracked by OBJECT IDENTITY with a per-key
        version counter — not by `id()` alone, which CPython reuses
        after GC and would let a recycled address silently replay a
        stale compiled program. Bundle-tracked tensors are RUNTIME
        state: the trace reads them through bundle.load, never bakes
        them as constants, and the optimizer swaps _data every step —
        versioning their DATA would retrace per step; the tensor object
        id still guards against rebinding to a DIFFERENT parameter of
        the same shape (the bundle keeps the objects alive)."""
        track = getattr(self, "_cell_track", None)
        if track is None:
            track = self._cell_track = {}
        if isinstance(v, Tensor):
            d = v._data
            if id(v) in self._state_tensor_ids():
                return (name, "state", id(v),
                        tuple(getattr(d, "shape", ())),
                        str(getattr(d, "dtype", "")))
            rec = track.get(key)
            if rec is None or rec[0] is not d:
                rec = (d, (rec[1] + 1) if rec else 0)
                track[key] = rec
            return (name, "t", rec[1], tuple(getattr(d, "shape", ())),
                    str(getattr(d, "dtype", "")))
        if isinstance(v, (int, float, bool, str, bytes, type(None))):
            return (name, "s", v)
        rec = track.get(key)
        if rec is None or rec[0] is not v:
            rec = (v, (rec[1] + 1) if rec else 0)
            track[key] = rec
        return (name, "o", rec[1])

    def _closure_sig(self):
        """Versioned fingerprint of the ORIGINAL callable's closure cells
        (an AST-converted fn carries a by-value snapshot instead, so the
        live cells always belong to `_eager_callable` when set)."""
        import types as _types
        src = getattr(self, "_eager_callable", None) or self._callable
        f = src.__func__ if isinstance(src, _types.MethodType) else src
        if not isinstance(f, _types.FunctionType) or not f.__closure__:
            return ()
        sig = []
        for name, cell in zip(f.__code__.co_freevars, f.__closure__):
            try:
                v = cell.cell_contents
            except ValueError:
                sig.append((name, "<empty>"))
                continue
            sig.append(self._track_value(name, name, v))
        return tuple(sig)

    def _state_tensor_ids(self):
        """ids of Tensors owned by the state bundle (parameters, buffers,
        optimizer accumulators reachable via parameters()/state_dict()).
        Tensor objects are stable across steps (only their _data swaps),
        so this is computed once."""
        ids = getattr(self, "_state_ids_cache", None)
        if ids is None:
            ids = set()
            for obj in self._bundle.objects:
                if hasattr(obj, "parameters"):
                    try:
                        ids |= {id(p) for p in obj.parameters()}
                    except Exception:
                        pass
                if hasattr(obj, "state_dict"):
                    try:
                        ids |= {id(t) for t in obj.state_dict().values()
                                if isinstance(t, Tensor)}
                    except Exception:
                        pass
            self._state_ids_cache = ids
        return ids

    def _globals_sig(self):
        """Fingerprint of module-GLOBAL tensors the function reads — the
        same staleness class as closure cells: a global tensor is baked
        into the trace as a constant, so replacing its data must
        retrace. The tracked name set is snapshotted on first call
        (co_names that currently hold Tensors); a global that only
        becomes a Tensor later is not guarded."""
        import types as _types
        src = getattr(self, "_eager_callable", None) or self._callable
        f = src.__func__ if isinstance(src, _types.MethodType) else src
        if not isinstance(f, _types.FunctionType):
            return ()
        names = getattr(self, "_global_tensor_names", None)
        if names is None:
            # only names the bytecode actually LOADS as globals —
            # co_names also lists attribute/import names, which would
            # guard-track unrelated module tensors that happen to share
            # an attribute's name
            import dis
            g = f.__globals__
            loads = {ins.argval for ins in dis.get_instructions(f.__code__)
                     if ins.opname == "LOAD_GLOBAL"}
            names = tuple(sorted(n for n in loads
                                 if isinstance(g.get(n), Tensor)))
            self._global_tensor_names = names
        if not names:
            return ()
        # _track_value handles rebinding to non-Tensors too (scalar and
        # object branches), so a global flipping Tensor -> float -> float
        # keeps retracing on every change
        return tuple(self._track_value("g:" + name, name,
                                       f.__globals__.get(name))
                     for name in names)

    def _refresh_conversion(self, cur_sig):
        """Re-snapshot the dy2static conversion when the original
        function's closure cells changed (VERDICT r3 weak #8: converted
        code binds cells by value at conversion time, so a later cell
        mutation silently used stale values). If re-conversion fails,
        fall back to the ORIGINAL callable — slower (eager / re-break)
        but never stale."""
        orig = getattr(self, "_eager_callable", None)
        if orig is None:
            return
        if cur_sig != getattr(self, "_conv_closure_sig", cur_sig):
            from .dy2static import try_convert
            conv = try_convert(orig)
            self._callable = conv if conv is not None else orig
            self._conv_closure_sig = cur_sig

    def _clear_tracer_grads(self):
        """Drop tracer grad buffers a trace (aborted or finished) leaked
        into live parameters."""
        for obj in self._bundle.objects:
            if hasattr(obj, "parameters"):
                for p in obj.parameters():
                    if p._grad_buffer is not None and \
                            not isinstance(p._grad_buffer, (jax.Array, np.ndarray)):
                        p._grad_buffer = None

    def _graph_break(self, key, concrete_state, err, args, kwargs):
        """SOT-lite fallback with an AST rescue first: restore the
        concrete state the aborted trace clobbered (bundle.load ran with
        tracers), then try the dy2static AST conversion ONCE — python
        if/while over tensor predicates rewritten to static.nn
        cond/while_loop often compiles outright (the reference's AST
        mode). Only if the converted function also breaks does this call
        signature get guarded to eager. Python-side scalar mutations made
        before the break (e.g. a step counter) are not rolled back — same
        caveat as SOT's partial-frame replay."""
        self._bundle.load(concrete_state)
        self._clear_tracer_grads()
        if not getattr(self, "_ast_tried", False):
            self._ast_tried = True
            from .dy2static import try_convert
            t0 = time.perf_counter()
            converted = try_convert(self._callable)
            if converted is not None:
                _compile_log.log_event(
                    "ast_convert", name=self._fn_name(),
                    duration_s=time.perf_counter() - t0,
                    detail={"converted": str(getattr(
                        converted, "_dy2static_converted", "?"))})
                self._eager_callable = self._callable  # for later breaks
                self._conv_closure_sig = self._closure_sig()
                self._callable = converted
                self._cache.pop(key, None)
                warnings.warn(
                    "to_static: AST-converted "
                    f"{getattr(converted, '_dy2static_converted', '?')} "
                    "control-flow statement(s) to compiled cond/while "
                    "(dy2static); retracing.", RuntimeWarning,
                    stacklevel=3)
                return self.__call__(*args, **kwargs)
        self._cache[key] = _EAGER_FALLBACK
        self._fallback_count += 1
        name = self._fn_name()
        first_line = str(err).strip().split("\n")[0]
        _record_fallback({
            "function": name,
            "error": type(err).__name__,
            "message": first_line[:200],
        })
        _compile_log.log_event(
            "eager_fallback", name=name,
            detail={"error": type(err).__name__,
                    "fallback_keys": self._fallback_count})
        warnings.warn(
            f"to_static: graph break in {name!r} "
            f"({type(err).__name__}: {first_line[:200]}). This call "
            "signature now runs EAGERLY (no XLA fusion). Rewrite the "
            "data-dependent control flow with paddle.where/lax.cond to "
            "recover the compiled path.",
            RuntimeWarning, stacklevel=3)
        return self._callable(*args, **kwargs)

    # -- paddle API surface -----------------------------------------------
    @property
    def code(self):
        import inspect
        try:
            return inspect.getsource(self._callable)
        except OSError:
            return "<source unavailable>"

    def concrete_program(self):
        return self

    def rollback(self):
        return self._orig_fn


class _TensorSlotType:
    def __repr__(self):
        return "<TENSOR>"


_TENSOR_SLOT = _TensorSlotType()


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, state_objects=None, full_graph=False, **kwargs):
    """Parity: paddle.jit.to_static. `state_objects` lists extra stateful
    objects (optimizers, schedulers) whose state should be threaded through
    the compiled program — needed when the function mutates them.

    full_graph=False (default, like the reference's SOT mode) falls back
    to eager execution per call signature when tracing meets
    data-dependent python control flow; full_graph=True (AST mode) makes
    that a hard error."""

    def deco(fn):
        return TracedFunction(fn, state_objects=state_objects,
                              input_spec=input_spec, full_graph=full_graph)

    if function is not None:
        return deco(function)
    return deco


def not_to_static(fn):
    fn._not_to_static = True
    return fn


def ignore_module(modules):
    return None


def functional_call(layer, params_and_buffers, *args, method=None, **kwargs):
    """Run `layer.forward` with parameters temporarily replaced by the given
    dict of arrays (jit-friendly module application). `method` names an
    alternate entry point on the layer (e.g. the serving engine drives
    `forward_paged_decode` through the same state swap)."""
    sd = layer.state_dict()
    saved = {k: t._data for k, t in sd.items()}
    try:
        for k, v in params_and_buffers.items():
            if k in sd:
                sd[k]._data = v._data if isinstance(v, Tensor) else v
        if method is not None:
            return getattr(layer, method)(*args, **kwargs)
        return layer(*args, **kwargs)
    finally:
        for k, t in sd.items():
            t._data = saved[k]


# -------------------------------------------------------------------- save/load
def save(layer, path, input_spec=None, **configs):
    """Serialize a Layer (or TracedFunction) for deployment.

    Parity: paddle.jit.save (reference python/paddle/jit/api.py). Artifact:
    `{path}.pdiparams` (pickled numpy state dict) + `{path}.pdmodel.mlir`
    (StableHLO, when an input_spec is provided) — the StableHLO module plays
    the role of the reference's serialized PIR program.
    """
    from ..nn.layer.layers import Layer
    target = layer.__wrapped__ if isinstance(layer, TracedFunction) else layer
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    if isinstance(target, Layer):
        sd = {k: np.asarray(v._data) for k, v in target.state_dict().items()}
        with open(path + ".pdiparams", "wb") as f:
            pickle.dump(sd, f)
        if input_spec is not None:
            import jax.export

            def pure(state, *xs):
                return functional_call(
                    target, {k: v for k, v in state.items()},
                    *[Tensor(x) for x in xs])._data

            example_state = {k: v._data for k, v in target.state_dict().items()}
            shapes = [jax.ShapeDtypeStruct(tuple(s.shape),
                                           jnp.dtype(getattr(s, "dtype", jnp.float32)))
                      for s in input_spec]
            exported = jax.export.export(jax.jit(pure))(example_state, *shapes)
            with open(path + ".pdmodel.mlir", "wb") as f:
                f.write(exported.serialize())
            # sidecar metadata: named IO for the inference Predictor
            import json
            meta = {
                "inputs": [{
                    "name": getattr(s, "name", None) or f"x{i}",
                    "shape": list(getattr(s, "shape", ())),
                    "dtype": str(getattr(s, "dtype", "float32")),
                } for i, s in enumerate(input_spec)],
            }
            with open(path + ".pdmodel.meta.json", "w") as f:
                json.dump(meta, f)
    else:
        raise TypeError("jit.save expects a Layer or TracedFunction")


def load(path, **configs):
    """Load a saved artifact. Returns a callable running the exported
    StableHLO if present, else the raw state dict."""
    params_path = path + ".pdiparams"
    model_path = path + ".pdmodel.mlir"
    state = None
    if os.path.exists(params_path):
        with open(params_path, "rb") as f:
            state = pickle.load(f)
    if os.path.exists(model_path):
        import jax.export
        with open(model_path, "rb") as f:
            exported = jax.export.deserialize(f.read())
        jstate = {k: jnp.asarray(v) for k, v in state.items()}

        def runner(*xs):
            arrs = [x._data if isinstance(x, Tensor) else jnp.asarray(x) for x in xs]
            return Tensor(exported.call(jstate, *arrs))
        runner.state_dict = lambda: state
        return runner
    return state


class InputSpec:
    """Parity: paddle.static.InputSpec."""

    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        from ..core.dtype import convert_dtype
        self.shape = tuple(-1 if s is None else int(s) for s in shape)
        self.dtype = convert_dtype(dtype)
        self.name = name



class TranslatedLayer:
    """Marker/result type of jit.load (parity: jit/translated_layer.py).
    jit.load in this build returns a runnable program wrapper; this alias
    keeps isinstance checks from reference code importable."""


_code_level = 0
_verbosity = 0
_to_static_enabled = True


def set_code_level(level=100, also_to_stderr=False):
    """Parity: paddle.jit.set_code_level (SOT transformed-code logging).
    Stored for introspection; this build has no bytecode transformer to
    print."""
    global _code_level
    _code_level = level


def set_verbosity(level=0, also_to_stderr=False):
    global _verbosity
    _verbosity = level


def enable_to_static(enable=True):
    """Globally toggle to_static compilation (parity:
    jit.enable_to_static): when off, TracedFunction calls fall through to
    eager execution."""
    global _to_static_enabled
    _to_static_enabled = bool(enable)
