"""paddle_tpu.jit — trace-to-compiled execution.

Parity: reference `python/paddle/jit/` (to_static/SOT/save/load). The
reference needs a bytecode VM (SOT) + AST transforms + PIR programs because
its eager mode can't be traced; here the eager tape IS jax-traceable, so
`to_static` is a thin stateful-to-functional adapter around `jax.jit`:
model/optimizer/RNG state is threaded as pytree inputs/outputs, mutation is
replayed after the call, and XLA compiles fwd+bwd+update into one program.
"""
from .api import to_static, not_to_static, TracedFunction, save, load, functional_call, ignore_module  # noqa: F401
from .api import (TranslatedLayer, set_code_level, set_verbosity,  # noqa: F401
                  enable_to_static, to_static_report)

__all__ = ["to_static", "not_to_static", "save", "load", "functional_call",
           "TranslatedLayer", "set_code_level", "set_verbosity",
           "enable_to_static", "to_static_report"]
