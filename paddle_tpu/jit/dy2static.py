"""AST dy2static-lite: rewrite python control flow into compiled ops.

Parity: the reference's AST transform pipeline
(`python/paddle/jit/dy2static/program_translator.py:377`,
`convert_operators.py` convert_ifelse/convert_while_loop — ~35k LoC with
a bytecode VM on top). This is the load-bearing subset: `if` statements,
`while` loops and `for` loops (over `range(...)` — incl. tensor bounds —
and over tensors) whose predicates/bounds turn out to be traced tensors
are rewritten into `paddle.static.nn.cond` / `while_loop` calls, so the
model COMPILES instead of graph-breaking to eager.

Pipeline position (jit/api.py): trace fails with a concretization error
-> try_convert() rewrites the function's AST -> retrace; only if the
converted function still breaks does the SOT-lite eager fallback engage.

`break`/`continue` in while/for bodies ARE converted (parity:
break_continue_transformer.py): each lowers to a masked flag — `break`
joins the compiled loop's condition, `continue` guards the rest of the
iteration — with the flag-guarded tails going through the normal
traced-`if` conversion, so its both-branches-and-select caveat
applies. A TRACED break flag is only sound where the flag can actually
stop the loop (the while_loop lowerings); host-executed loops
(concrete bounds, short unrolled tensor iteration) raise to the eager
fallback instead of running a loop the flag cannot stop.

Side-effect caveat, sibling of the cond one above (ADVICE r5 #1): a
loop body that lowers to lax.scan / while_loop is TRACED ONCE — a call
to a side-effecting builtin (`print`, `breakpoint`, `input`) inside it
runs at trace time (once, printing tracer reprs), not per iteration.
Mutation of python state is detected and keeps the loop eager (see the
Restrictions below), but pure-output builtins are invisible to those
checks, so the successful lowering emits a `UserWarning` naming the
builtin instead (`_warn_trace_time_side_effects`) — the compiled result
is numerically right; only the printing cadence changes.

Restrictions (each skips the rewrite for that statement, keeping plain
python semantics — the fallback still works):
  * branches containing return/break/continue/yield; loop bodies
    containing return/yield, or break/continue inside an opaque
    compound (try/with)
  * nested function definitions are not descended into
  * closure variables are bound by VALUE at conversion time (the
    reference snapshots cells the same way when synthesizing code)
  * compiled while/for-range loops trace the body ONCE (static-graph
    loop semantics, like the reference's converted loops): under
    grad-enabled tracing a probe detects RNG draws / grad-carrying
    bodies and falls back to eager; under no_grad a converted loop
    keeps the single-draw semantics
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap
import types
from typing import List, Optional, Set, Tuple

import numpy as np

__all__ = ["try_convert", "DygraphToStaticBreak"]


class DygraphToStaticBreak(Exception):
    """Raised by the runtime helpers when a rewritten construct cannot be
    represented under tracing (e.g. branches with mismatched structures);
    jit/api.py treats it exactly like a jax concretization error."""


from collections import Counter  # noqa: E402

# Per-reason fallback observability (VERDICT r4 item 9): every decision
# that keeps code out of the compiled path increments a named counter —
# the SOT-gap inventory that makes the cost of eager fallbacks measurable.
_FALLBACK_COUNTS: Counter = Counter()


def fallback_counters():
    """Snapshot of the per-reason break/decline counters. Reasons:
    grad-loop, rng-draw, traced-step, break-flag-traced,
    cond-lower-failed, while-lower-failed, for-lower-failed,
    scan-declined (a lax.scan lowering attempted but abandoned)."""
    return dict(_FALLBACK_COUNTS)


def reset_fallback_counters():
    _FALLBACK_COUNTS.clear()


def _note(reason):
    _FALLBACK_COUNTS[reason] += 1


def _break(reason, msg):
    """Count + build (not raise) the break exception, so call sites keep
    their explicit `raise` and exception chaining."""
    _FALLBACK_COUNTS[reason] += 1
    _dy2static_debug_log(f"fallback[{reason}]: {msg}")
    return DygraphToStaticBreak(msg)


# Canonical vocabulary lives in analysis.purity (tpu-lint rule A5) so
# the static linter and this converter can never drift; the names kept
# here are aliases for the original private spellings.
from ..analysis import purity as _purity  # noqa: E402 (stdlib-only module)

_SIDE_EFFECT_BUILTINS = _purity.SIDE_EFFECT_BUILTINS


def _global_loads_in_code(code):
    """Names loaded as globals/builtins (LOAD_GLOBAL/LOAD_NAME), NOT
    attribute accesses — co_names alone would flag `layer.input` as a
    call of the builtin `input`."""
    import dis
    names = set()
    for ins in dis.get_instructions(code):
        if ins.opname in ("LOAD_GLOBAL", "LOAD_NAME"):
            names.add(ins.argval)
    for const in code.co_consts:
        if isinstance(const, types.CodeType):
            names |= _global_loads_in_code(const)
    return names


def _warn_trace_time_side_effects(body_fn, kind):
    """A loop body lowered to a compiled loop (lax.scan / while_loop)
    runs its python ONCE, at trace time — a `print` inside it prints a
    tracer repr once instead of a value per iteration (module-docstring
    caveat, ADVICE r5 #1). Mutating side effects are detected elsewhere
    and keep the loop eager; pure-output builtins can't be, so warn."""
    code = getattr(body_fn, "__code__", None)
    if code is None:
        return
    found = sorted(_global_loads_in_code(code) & _SIDE_EFFECT_BUILTINS)
    if found:
        import warnings
        # promoted to a reportable diagnostic (tpu-lint A5): surfaces in
        # jit.to_static_report()["purity_diagnostics"] and FALLBACKS.md
        _purity.record_loop_side_effect(
            found, kind, getattr(code, "co_filename", None),
            getattr(code, "co_firstlineno", 0),
            getattr(body_fn, "__name__", "<body>"))
        warnings.warn(
            f"loop body calling {', '.join(found)}() was compiled to a "
            f"{kind}: the call ran ONCE at trace time (printing tracer "
            "values), not per iteration. Wrap the loop in "
            "paddle.jit.not_to_static (or drop the call) if you need "
            "per-iteration side effects.", UserWarning, stacklevel=3)


class _Undefined:
    __slots__ = ("name",)

    def __init__(self, name):
        self.name = name

    def __repr__(self):
        return f"<undefined {self.name}>"


def _is_tracer_tensor(p):
    import jax
    from ..core.tensor import Tensor
    return isinstance(p, Tensor) and isinstance(p._data, jax.core.Tracer)


def _to_bool(p):
    from ..core.tensor import Tensor
    if isinstance(p, Tensor):
        return bool(np.asarray(p._data).reshape(()))
    return bool(p)


def _t_not(v):
    """`not v` for python bools and (possibly traced) Tensors."""
    from ..core.tensor import Tensor
    if isinstance(v, Tensor):
        import jax.numpy as jnp
        return Tensor(jnp.logical_not(v._data))
    return not v


def _t_and(a, b):
    """`a and b` (non-short-circuit) for bools and Tensors."""
    from ..core.tensor import Tensor
    if isinstance(a, Tensor) or isinstance(b, Tensor):
        import jax.numpy as jnp
        ad = a._data if isinstance(a, Tensor) else a
        bd = b._data if isinstance(b, Tensor) else b
        return Tensor(jnp.logical_and(ad, bd))
    return bool(a) and bool(b)


def _none_set(*flags):
    """True iff no lowered break/continue flag is set; Tensor-valued when
    any flag is traced (the rewritten guard `if __pt_none_set(...)` then
    lowers through the normal traced-if path)."""
    out = True
    for f in flags:
        out = _t_and(out, _t_not(f))
    return out


def _is_tensorish(v):
    from ..core.tensor import Tensor
    return isinstance(v, Tensor)


def _bool_and(*thunks):
    """`a and b and ...` in TEST position: python short-circuit for
    concrete values, tensor logical_and when any operand is a Tensor
    (no short-circuit across tensor operands — side-effect-free test
    expressions assumed, like every converted predicate). Returns a
    truth value (bool or boolean Tensor), not python's last-operand."""
    acc = None
    for th in thunks:
        v = th()
        if _is_tensorish(v):
            acc = v if acc is None else _t_and(acc, v)
        elif not v:
            return False          # concrete falsy short-circuits all
    return True if acc is None else acc


def _t_or(a, b):
    """`a or b` (non-short-circuit) for bools and Tensors."""
    from ..core.tensor import Tensor
    if isinstance(a, Tensor) or isinstance(b, Tensor):
        import jax.numpy as jnp
        ad = a._data if isinstance(a, Tensor) else a
        bd = b._data if isinstance(b, Tensor) else b
        return Tensor(jnp.logical_or(ad, bd))
    return bool(a) or bool(b)


def _bool_or(*thunks):
    acc = None
    for th in thunks:
        v = th()
        if _is_tensorish(v):
            acc = v if acc is None else _t_or(acc, v)
        elif v:
            return True           # concrete truthy short-circuits all
    return False if acc is None else acc


def _bool_not(v):
    return _t_not(v) if _is_tensorish(v) else (not v)


_CHAIN_OPS = {
    "Lt": lambda a, b: a < b, "LtE": lambda a, b: a <= b,
    "Gt": lambda a, b: a > b, "GtE": lambda a, b: a >= b,
    "Eq": lambda a, b: a == b, "NotEq": lambda a, b: a != b,
    "Is": lambda a, b: a is b, "IsNot": lambda a, b: a is not b,
    "In": lambda a, b: a in b, "NotIn": lambda a, b: a not in b,
}


def _chain(left_th, *parts):
    """Chained comparison `a < b < c` in TEST position: each comparator
    evaluates exactly ONCE (python semantics), pairwise results combine
    like _bool_and."""
    prev = left_th()
    acc = None
    it = iter(parts)
    for opname in it:
        cur = next(it)()
        r = _CHAIN_OPS[opname](prev, cur)
        if _is_tensorish(r):
            acc = r if acc is None else _t_and(acc, r)
        elif not r:
            return False
        prev = cur
    return True if acc is None else acc


_CMP_NAME = {ast.Lt: "Lt", ast.LtE: "LtE", ast.Gt: "Gt", ast.GtE: "GtE",
             ast.Eq: "Eq", ast.NotEq: "NotEq", ast.Is: "Is",
             ast.IsNot: "IsNot", ast.In: "In", ast.NotIn: "NotIn"}


def _thunk(expr):
    return ast.Lambda(
        args=ast.arguments(posonlyargs=[], args=[], vararg=None,
                           kwonlyargs=[], kw_defaults=[], kwarg=None,
                           defaults=[]),
        body=expr)


def _lower_bool_test(e):
    """Rewrite a TEST expression so tensor operands stop hitting
    bool(tracer): and/or/not become lazy helper calls (python
    short-circuit preserved for concrete operands, logical ops for
    tensors), multi-op comparison chains become __pt_chain (each
    comparator still evaluated once). Parity: the reference's
    convert_logical_and/or/not (jit/dy2static/convert_operators.py).

    Walrus assignments inside the test would become lambda-local and
    lose their binding — leave such tests untouched (traced operands
    then fall back to eager, exactly the pre-lowering behavior)."""
    if any(isinstance(n, ast.NamedExpr) for n in ast.walk(e)):
        return e
    if isinstance(e, ast.BoolOp):
        fname = "__pt_bool_and" if isinstance(e.op, ast.And) \
            else "__pt_bool_or"
        return ast.Call(func=_name(fname, ast.Load()),
                        args=[_thunk(_lower_bool_test(v))
                              for v in e.values], keywords=[])
    if isinstance(e, ast.UnaryOp) and isinstance(e.op, ast.Not):
        return ast.Call(func=_name("__pt_bool_not", ast.Load()),
                        args=[_lower_bool_test(e.operand)], keywords=[])
    if isinstance(e, ast.Compare) and len(e.ops) > 1 \
            and all(type(op) in _CMP_NAME for op in e.ops):
        args = [_thunk(e.left)]
        for op, comp in zip(e.ops, e.comparators):
            args.append(ast.Constant(value=_CMP_NAME[type(op)]))
            args.append(_thunk(comp))
        return ast.Call(func=_name("__pt_chain", ast.Load()),
                        args=args, keywords=[])
    return e


def _run_if(pred, true_fn, false_fn):
    """Runtime helper for rewritten `if`: concrete predicates keep exact
    python semantics; traced predicates lower to static.nn.cond."""
    if _is_tracer_tensor(pred):
        from ..static import nn as snn
        try:
            return snn.cond(pred, true_fn, false_fn)
        except Exception as e:  # structure mismatch, undefined var, ...
            raise _break(
                "cond-lower-failed",
                f"converted `if` could not lower to cond: {e}") from e
    return true_fn() if _to_bool(pred) else false_fn()


def _to_int(v):
    from ..core.tensor import Tensor
    if isinstance(v, Tensor):
        return int(np.asarray(v._data).reshape(()))
    return int(v)


def _grad_sensitive(vals):
    """True when autograd is on and any loop-carried Tensor requires
    grad: lax.while_loop has NO reverse-mode AD, so lowering such a loop
    would silently emit stop_gradient outputs — raise instead, and the
    eager fallback trains with correct gradients."""
    from ..core import autograd
    from ..core.tensor import Tensor
    if not autograd.is_grad_enabled():
        return False
    return any(isinstance(v, Tensor) and not v.stop_gradient
               for v in vals)


def _rng_fingerprint():
    """Identity fingerprint of every live RNG stream: the global key
    object plus each TP tracker substream's key (draws REBIND the key
    object, so identity change == a draw happened — works for traced
    keys where value comparison is impossible). The stream enumeration
    has ONE owner — loop_grad._rng_snapshot — so a stream added there is
    never missed here (or vice versa)."""
    from .loop_grad import _rng_snapshot
    snap = _rng_snapshot()
    return (tuple(id(key) for _st, key in snap["pairs"]), snap["names"])


def _probe_body_grads(body_fn, args):
    """Entry carries may be grad-free while the BODY pulls grad-requiring
    closure tensors into the carry (s = s + h with h from the net) — run
    one probe iteration and inspect its outputs. Under no_grad the probe
    is DELIBERATELY skipped: converted loops then keep static-graph
    single-draw semantics (module docstring) and the probe's python-level
    side effects don't run an extra time; this is a semantics choice,
    not merely an optimization. Any non-grad probe failure is ignored
    here because the while_loop attempt right after surfaces it as a
    proper conversion break.

    Returns the probe outputs (a tuple) when the probe ran and passed,
    else None — callers may reuse them (e.g. to seed _Undefined carry
    slots) WITHOUT running the body's side effects a second time."""
    from ..core import autograd
    if not autograd.is_grad_enabled():
        return None
    rng_before = _rng_fingerprint()
    try:
        out = body_fn(*args)
    except Exception:
        return None
    if _rng_fingerprint() != rng_before:
        # one traced body = ONE draw repeated every iteration; the eager
        # fallback keeps per-iteration draws. Covers the TP tracker
        # substreams too (get_rng_state_tracker().rng_state(...) swaps
        # the global in and out, leaving ITS identity unchanged).
        raise _break(
            "rng-draw",
            "loop body draws from the RNG; a compiled loop would repeat "
            "one draw — using the eager fallback for per-iteration draws")
    vals = tuple(out) if isinstance(out, (list, tuple)) else (out,)
    if _grad_sensitive(vals):
        raise _break(
            "grad-loop",
            "loop body produces grad-requiring tensors; while_loop is "
            "forward-only — using the eager fallback so gradients stay "
            "correct")
    return vals


def _run_for_range(start, stop, step, body_fn, loop_vars, brk_idx=None):
    """Runtime helper for rewritten `for t in range(...)` (parity:
    the reference loop transformer converts `for`-over-range into its
    while lowering, `jit/dy2static/transformers/loop_transformer.py:111`).

    Contract: loop_vars = (target_init, *carried); body_fn(k, *carried)
    -> (target_out, *carried_out) where k is the iteration counter —
    python rebinds the target from the iterator each step regardless of
    body reassignment, and the post-loop target is the LAST body value.
    Concrete bounds keep exact python semantics (including a possibly
    still-undefined target when the range is empty); a traced bound
    lowers to static.nn.while_loop with (counter, target, *carried)."""
    import jax

    def traced(v):
        return isinstance(getattr(v, "_data", v), jax.core.Tracer)

    tgt, carried = loop_vars[0], tuple(loop_vars[1:])
    if not traced(step) and _to_int(step) == 0:
        raise ValueError("range() arg 3 must not be zero")
    if not (traced(start) or traced(stop) or traced(step)):
        i, st, sp = _to_int(start), _to_int(stop), _to_int(step)
        if len(range(i, st, sp)) > _ITER_UNROLL_LIMIT:
            # long concrete-bound loop: try the lax.scan lowering (ONE
            # compiled op with reverse AD instead of an O(n) unrolled
            # trace; loop_grad.py). The probe is iteration 0 either way.
            from .loop_grad import try_scan_range
            res = try_scan_range(i, st, sp, body_fn, carried, brk_idx)
            if res[0] == "done":
                _warn_trace_time_side_effects(body_fn, "lax.scan")
                return res[1]
            _, reason, i, vals = res
            tgt, carried = vals[0], tuple(vals[1:])
            if reason is not None:
                _note(reason if reason == "rng-draw" else "scan-declined")
                _dy2static_debug_log(
                    f"for-range scan lowering declined ({reason}); host "
                    "loop continues from iteration 1")
        while (i < st) if sp > 0 else (i > st):
            if brk_idx is not None:
                bf = carried[brk_idx]
                if traced(bf):
                    # only the masked TAIL of the setting iteration is
                    # guarded; statements before the flag check would
                    # keep executing in a host loop the flag cannot
                    # stop — eager is the only correct semantics
                    raise _break(
                        "break-flag-traced",
                        "break flag became traced inside a "
                        "concrete-bound for — using the eager fallback")
                if _to_bool(bf):
                    break   # exact python: stop before the next iteration
            out = body_fn(i, *carried)
            tgt, carried = out[0], tuple(out[1:])
            i += sp
        return (tgt,) + carried
    if traced(step):
        raise _break(
            "traced-step",
            "for-range with a traced step: the loop direction is "
            "data-dependent; rewrite with lax primitives")
    if _grad_sensitive(loop_vars):
        # a traced bound has NO static trip count (it lives in tensor
        # data, not shapes) — the scan lowering cannot apply; this is
        # the one loop family that stays eager under grad (see
        # loop_grad.py module docstring)
        raise _break(
            "grad-loop",
            "traced-bound for carries grad-requiring tensors; "
            "while_loop is forward-only — using the eager fallback so "
            "gradients stay correct")
    sp = _to_int(step)
    from ..core.tensor import Tensor
    import jax.numpy as jnp
    start_v = start._data if isinstance(start, Tensor) else start
    k0 = Tensor(jnp.asarray(start_v))
    # probe with the TENSOR counter the real body will receive — an int
    # probe would raise on tensor-method counter use and silently skip
    # both the RNG and grad checks
    p_vals = _probe_body_grads(body_fn, (k0,) + carried)
    if p_vals is not None and any(isinstance(v, _Undefined)
                                  for v in carried):
        # names first assigned INSIDE the body (e.g. a nested loop's
        # target) enter the carry as sentinels, which while_loop cannot
        # type — seed them from the probe's outputs (NO extra body
        # call: under no_grad the probe is skipped by design and the
        # undefined carry falls through to the conversion break below)
        carried = tuple(p_vals[1 + j] if isinstance(v, _Undefined) else v
                        for j, v in enumerate(carried))
    stop_v = stop._data if isinstance(stop, Tensor) else stop
    if isinstance(tgt, _Undefined):
        # while_loop carried values need a concrete type; python would
        # leave the target unbound on an empty range — benign deviation,
        # documented: the target reads as the start counter then
        tgt = k0
    from ..static import nn as snn

    def cond(k, t, *vs):
        base = Tensor(k._data < stop_v) if sp > 0 else \
            Tensor(k._data > stop_v)
        if brk_idx is None:
            return base
        return _t_and(base, _t_not(vs[brk_idx]))

    def body(k, t, *vs):
        out = body_fn(k, *vs)
        return (Tensor(k._data + sp), out[0]) + tuple(out[1:])

    try:
        res = snn.while_loop(cond, body, [k0, tgt] + list(carried))
    except Exception as e:
        raise _break(
            "for-lower-failed",
            f"converted `for` could not lower to while_loop: {e}") from e
    _warn_trace_time_side_effects(body_fn, "while_loop")
    return tuple(res[1:])


_ITER_UNROLL_LIMIT = 64


def _register_debug_flag():
    from ..utils.flags import define_flag
    define_flag("dy2static_debug", False,
                "log dy2static loop-lowering decisions")


_register_debug_flag()


def _dy2static_debug_log(msg):
    """FLAGS_dy2static_debug=1 surfaces silent lowering decisions (a
    failed while_loop lowering is otherwise indistinguishable from a
    successful one — both keep the function compiled). The flag is
    registered once at import so runtime set_flags overrides stick."""
    from ..utils.flags import flags
    if flags("dy2static_debug"):
        print(f"[dy2static_debug] {msg}")


def _run_for_iter(seq, body_fn, loop_vars, brk_idx=None):
    """Runtime helper for rewritten `for x in seq`. Tensors iterate along
    dim 0 with a STATIC trip count (shapes are static under jit): short
    loops unroll into the trace; LONG tensor loops (> 64 rows) lower to
    a while_loop indexing `seq[i]` so the HLO stays O(1) in the length —
    unless the carry is grad-sensitive (while_loop is forward-only;
    unrolling keeps gradients correct there). Other iterables keep plain
    python semantics. Same (target, *carried) contract as
    `_run_for_range`."""
    from ..core.tensor import Tensor
    tgt, carried = loop_vars[0], tuple(loop_vars[1:])
    start = 0
    if isinstance(seq, Tensor) and seq.shape[0] > _ITER_UNROLL_LIMIT:
        # Probe = ITERATION 0, always kept: its python-level side
        # effects (appends, RNG draws) happened exactly once, like
        # eager. The probe's outcome picks the path:
        #   * body drew from the RNG -> continue UNROLLING from row 1
        #     (per-iteration draws stay correct; a compiled loop traces
        #     the body once);
        #   * grad-sensitive (the seq, a carry, or a probe output
        #     requires grad) -> lax.scan lowering with external capture
        #     (loop_grad.try_scan_iter: ONE taped op with reverse AD);
        #     a declined lowering unrolls from row 1 instead;
        #   * pure grad-free body -> while_loop over ALL rows (re-running
        #     row 0 inside it is unobservable for a pure body; the
        #     probe's traced ops are DCE'd);
        #   * while_loop trace failure -> continue unrolling from row 1.
        # Every RNG draw REPLACES its stream's key object
        # (RNGState.next_key rebinds), so the identity fingerprint
        # detects a draw even for traced keys and tracker substreams.
        from . import loop_grad
        from ..core import autograd as _ag
        orig = (tgt,) + carried            # pre-probe carries
        rng_before = _rng_fingerprint()
        cap = loop_grad._Capture(
            exclude_ids=[id(v) for v in (seq,) + orig
                         if isinstance(v, Tensor)])
        with loop_grad._capturing(cap if _ag.is_grad_enabled() else None):
            # row via __getitem__ (taped): a raw Tensor(seq._data[0])
            # wrapper would sever the gradient path into seq for the
            # probe's iteration
            out = body_fn(seq[0], *carried)  # raises like eager
        vals = tuple(out) if isinstance(out, (list, tuple)) else (out,)
        tgt, carried = vals[0], tuple(vals[1:])
        start = 1
        drew_rng = _rng_fingerprint() != rng_before
        if drew_rng:
            _note("rng-draw")
            _dy2static_debug_log(
                "body draws from the RNG: unrolling keeps per-iteration "
                "draws")
        elif _grad_sensitive((seq,) + orig + vals):
            res, reason = loop_grad.try_scan_iter(seq, body_fn, vals,
                                                  cap.externals, brk_idx)
            if res is not None:
                _warn_trace_time_side_effects(body_fn, "lax.scan")
                return res
            if reason is not None:
                _note(reason if reason == "rng-draw" else "scan-declined")
                _dy2static_debug_log(
                    f"tensor-iter scan lowering declined ({reason}); "
                    "unrolling from row 1 keeps gradients correct")
        else:
            try:
                import jax.numpy as jnp
                from ..static import nn as snn
                n = seq.shape[0]
                k0 = Tensor(jnp.asarray(0))
                # start from the PRE-probe carries (the loop re-runs row
                # 0 — unobservable for this pure body); probe values
                # only seed _Undefined slots as type placeholders
                seeds = [vals[j] if isinstance(v, _Undefined) else v
                         for j, v in enumerate(orig)]
                def _iter_cond(k, t, *vs):
                    base = Tensor(k._data < n)
                    if brk_idx is None:
                        return base
                    return _t_and(base, _t_not(vs[brk_idx]))

                res = snn.while_loop(
                    _iter_cond,
                    lambda k, t, *vs: (Tensor(k._data + 1),) + tuple(
                        body_fn(Tensor(seq._data[k._data]), *vs)),
                    [k0] + seeds)
                _warn_trace_time_side_effects(body_fn, "while_loop")
                return tuple(res[1:])
            except Exception as e:
                _dy2static_debug_log(
                    f"tensor-iter while_loop lowering failed, "
                    f"unrolling: {e!r}")
    import jax as _jax

    def _tr(v):
        return isinstance(getattr(v, "_data", v), _jax.core.Tracer)

    if isinstance(seq, Tensor):
        # rows through the op funnel: unrolled iterations must keep the
        # gradient edge into seq, exactly like python's `for row in t`
        # (Tensor.__iter__ -> __getitem__)
        items = (seq[j] for j in range(start, seq.shape[0]))
    else:
        items = iter(seq)
    while True:
        # flag check BEFORE pulling the next item: python's `break`
        # does not advance the iterator again, and an extra next()
        # would run stateful-iterator side effects / over-advance a
        # generator the caller keeps using
        if brk_idx is not None:
            bf = carried[brk_idx]
            if _tr(bf):
                # an unrolled host loop cannot be stopped by a traced
                # flag, and only the setting iteration's tail is masked
                # — eager is the only correct semantics
                raise _break(
                    "break-flag-traced",
                    "break flag became traced in an unrolled for — "
                    "using the eager fallback")
            if _to_bool(bf):
                break       # exact python semantics for a concrete flag
        try:
            item = next(items)
        except StopIteration:
            break
        out = body_fn(item, *carried)
        tgt, carried = out[0], tuple(out[1:])
    return (tgt,) + carried


def _run_while(cond_fn, body_fn, loop_vars, brk_idx=None):
    """Runtime helper for rewritten `while`.

    brk_idx: index in loop_vars of a lowered `break` flag (the masked
    break/continue conversion) — the loop additionally stops once it is
    set: short-circuited exactly in the concrete path, folded into the
    while_loop condition in the traced path."""
    import jax
    first = cond_fn(*loop_vars)
    tracers = _is_tracer_tensor(first) or any(
        isinstance(getattr(v, "_data", v), jax.core.Tracer)
        for v in loop_vars)
    if not tracers:
        while True:
            if brk_idx is not None:
                bf = loop_vars[brk_idx]
                if _is_tracer_tensor(bf):
                    # a traced predicate set the flag mid-loop while the
                    # cond stayed concrete: only eager keeps semantics
                    raise _break(
                        "break-flag-traced",
                        "break flag became traced inside a concrete "
                        "while — using the eager fallback")
                if _to_bool(bf):
                    break
            if not _to_bool(cond_fn(*loop_vars)):
                break
            out = body_fn(*loop_vars)
            loop_vars = tuple(out) if isinstance(out, (list, tuple)) \
                else (out,)
        return tuple(loop_vars)
    if _grad_sensitive(loop_vars):
        # a while's trip count is never static — unbounded whiles keep
        # the eager fallback by design (VERDICT r4 item 2)
        raise _break(
            "grad-loop",
            "traced while carries grad-requiring tensors; while_loop is "
            "forward-only — using the eager fallback so gradients stay "
            "correct")
    _probe_body_grads(body_fn, tuple(loop_vars))
    cond2 = cond_fn
    if brk_idx is not None:
        def cond2(*vs):
            return _t_and(_t_not(vs[brk_idx]), cond_fn(*vs))
    from ..static import nn as snn
    try:
        res = tuple(snn.while_loop(cond2, body_fn, list(loop_vars)))
    except Exception as e:
        raise _break(
            "while-lower-failed",
            f"converted `while` could not lower to while_loop: {e}") from e
    _warn_trace_time_side_effects(body_fn, "while_loop")
    return res


# --------------------------------------------------------- AST analysis
class _AssignCollector(ast.NodeVisitor):
    """Names bound by a statement (stores, aug-assigns, for-targets,
    with-as); does not descend into nested function/class definitions."""

    def __init__(self):
        self.names: Set[str] = set()

    def visit_Name(self, node):
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            self.names.add(node.id)

    def visit_FunctionDef(self, node):
        self.names.add(node.name)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node):
        self.names.add(node.name)

    def visit_Lambda(self, node):
        pass


def _assigned_names(stmts) -> Set[str]:
    c = _AssignCollector()
    for s in stmts:
        c.visit(s)
    return c.names


class _ReadCollector(ast.NodeVisitor):
    """Names read (Load ctx) by a statement list, excluding nested
    function/class bodies."""

    def __init__(self):
        self.names: Set[str] = set()

    def visit_Name(self, node):
        if isinstance(node.ctx, ast.Load):
            self.names.add(node.id)

    def visit_FunctionDef(self, node):
        pass

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_ClassDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef


def _read_names(stmts) -> Set[str]:
    c = _ReadCollector()
    for s in stmts:
        c.visit(s)
    return c.names


class _CtrlScanner(ast.NodeVisitor):
    """Detects constructs that make a body non-extractable."""

    def __init__(self):
        self.blocked = False

    def visit_Return(self, node):
        self.blocked = True

    def visit_Break(self, node):
        self.blocked = True

    def visit_Continue(self, node):
        self.blocked = True

    def visit_Yield(self, node):
        self.blocked = True

    visit_YieldFrom = visit_Yield

    def visit_FunctionDef(self, node):
        pass  # nested defs keep their own control flow

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef


def _blocked(stmts) -> bool:
    s = _CtrlScanner()
    for st in stmts:
        s.visit(st)
    return s.blocked


def _ctrl_profile(st):
    """(escapes, at_level): `escapes` = Return/Yield anywhere in the
    statement (excluding nested function/class defs) — never lowerable
    inside a loop body; `at_level` = Break/Continue bound to THE
    ENCLOSING loop (i.e. not inside a nested For/While)."""
    escapes = [False]
    at_level = [False]

    def walk(n, level):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda, ast.ClassDef)):
            return
        if isinstance(n, (ast.Return, ast.Yield, ast.YieldFrom)):
            escapes[0] = True
        if level and isinstance(n, (ast.Break, ast.Continue)):
            at_level[0] = True
        if isinstance(n, (ast.For, ast.While)):
            for c in n.body:
                walk(c, False)       # bound to the nested loop itself
            for c in n.orelse:
                walk(c, level)       # else-clause breaks bind the
            handled = set(map(id, n.body)) | set(map(id, n.orelse))
            for c in ast.iter_child_nodes(n):
                if id(c) not in handled:
                    walk(c, False)   # header exprs: escapes (yield) only
            return                   # ENCLOSING loop, not the nested one
        for child in ast.iter_child_nodes(n):
            walk(child, level)

    walk(st, True)
    return escapes[0], at_level[0]


def _assign_flag(name, value):
    return ast.Assign(targets=[_name(name, ast.Store())],
                      value=ast.Constant(value=value))


def _mask_ctrl(stmts, brk, cont):
    """Lower Break/Continue in a loop-body statement list to masked flag
    assignments: `break` -> `brk = True`, `continue` -> `cont = True`,
    and every statement that can follow a flag-set runs under
    `if __pt_none_set(flags):` (which the normal if-rewriter then
    converts — traced flags go through cond's both-branches-and-select
    semantics, same caveats as any converted traced `if`).

    Returns (new_stmts, used_brk, used_cont) or None when the list is
    not lowerable (Return/Yield at loop level, or Break/Continue inside
    an opaque compound like try/with)."""
    out: List[ast.stmt] = []
    used_b = used_c = False
    for i, st in enumerate(stmts):
        if isinstance(st, ast.Break):
            out.append(_assign_flag(brk, True))
            return out, True, used_c      # tail is unreachable python
        if isinstance(st, ast.Continue):
            out.append(_assign_flag(cont, True))
            return out, used_b, True
        escapes, at_level = _ctrl_profile(st)
        if escapes:
            return None
        if at_level:
            if not isinstance(st, ast.If):
                return None               # break inside try/with/...
            r_body = _mask_ctrl(st.body, brk, cont)
            r_else = _mask_ctrl(st.orelse, brk, cont)
            if r_body is None or r_else is None:
                return None
            used_b |= r_body[1] or r_else[1]
            used_c |= r_body[2] or r_else[2]
            out.append(ast.If(test=st.test,
                              body=r_body[0] or [ast.Pass()],
                              orelse=r_else[0]))
            rest = stmts[i + 1:]
            if rest:
                r_tail = _mask_ctrl(rest, brk, cont)
                if r_tail is None:
                    return None
                flags = [n for n, u in ((brk, used_b), (cont, used_c))
                         if u]
                out.append(ast.If(
                    test=ast.Call(
                        func=_name("__pt_none_set", ast.Load()),
                        args=[_name(f, ast.Load()) for f in flags],
                        keywords=[]),
                    body=r_tail[0] or [ast.Pass()], orelse=[]))
                used_b |= r_tail[1]
                used_c |= r_tail[2]
            return out, used_b, used_c
        out.append(st)
    return out, used_b, used_c


def _if_contains_return(st) -> bool:
    """Return directly in an If's branches (recursing through nested
    Ifs only — returns inside loops/try/with are NOT this pass's
    business)."""
    if not isinstance(st, ast.If):
        return False
    for stmts in (st.body, st.orelse):
        for s in stmts:
            if isinstance(s, ast.Return) or _if_contains_return(s):
                return True
    return False


def _lower_returns(stmts, cont, rv):
    """Single-exit lowering for returns under IF statements: the
    classic continuation-into-branches transform (parity: the
    reference's return transformer,
    jit/dy2static/transformers/return_transformer.py) —

        if p: return a          if p: rv = a
        REST            ==>     else: REST'
                                (fn ends with `return rv`)

    `cont` is the ALREADY-LOWERED continuation (what runs if control
    falls through `stmts`); a Return terminates its path with an
    rv-assign and drops the continuation, and a return-bearing If
    pushes the continuation into each non-terminal branch (deep-copied
    — the rewriter later mutates statements in place, so branches must
    not share AST nodes). Statements containing returns this pass
    cannot lift (loops, try/with) pass through verbatim: their returns
    still execute as real python returns, making the trailing
    `return rv` simply unreachable on those paths."""
    import copy

    out = []
    for i, st in enumerate(stmts):
        if isinstance(st, ast.Return):
            out.append(ast.Assign(
                targets=[_name(rv, ast.Store())],
                value=st.value if st.value is not None
                else ast.Constant(value=None)))
            return out                # continuation dropped: path done
        if _if_contains_return(st):
            k = _lower_returns(stmts[i + 1:], cont, rv)
            nt = _lower_returns(st.body, k, rv)
            nf = _lower_returns(st.orelse, copy.deepcopy(k), rv)
            out.append(ast.If(test=st.test,
                              body=nt or [ast.Pass()], orelse=nf))
            return out
        out.append(st)
    out.extend(cont)
    return out


class _IfExpLowerer(ast.NodeTransformer):
    """`a if pred else b` anywhere in the function becomes
    __pt_run_if(pred', lambda: a, lambda: b): concrete predicates keep
    exact python semantics (only the taken branch evaluates); traced
    predicates lower to cond's both-branches-and-select instead of
    dying at bool(tracer). Ternaries containing walrus assignments are
    left alone (lambda-wrapping would localize the binding)."""

    def __init__(self):
        self.count = 0

    def visit_IfExp(self, node):
        node = self.generic_visit(node)      # innermost-first
        if any(isinstance(n, (ast.NamedExpr, ast.Yield, ast.YieldFrom,
                              ast.Await))
               for n in ast.walk(node)):
            # walrus would bind lambda-locally; yield inside a lambda
            # is LEGAL (silently a generator-lambda — corrupting the
            # enclosing generator); await in a lambda is a
            # SyntaxError that would kill the whole conversion
            return node
        self.count += 1
        return ast.Call(
            func=_name("__pt_run_if", ast.Load()),
            args=[_lower_bool_test(node.test),
                  _thunk(node.body), _thunk(node.orelse)],
            keywords=[])


def _maybe_single_exit(fdef) -> bool:
    """Apply _lower_returns to a function body when (and only when)
    some If contains a return — the pattern that otherwise forces the
    eager fallback for traced predicates. Mutates fdef in place;
    True if transformed."""

    def has_candidate(stmts):
        return any(_if_contains_return(s) for s in stmts)

    if not has_candidate(fdef.body):
        return False
    rv = "__pt_rv"
    new = _lower_returns(fdef.body, [], rv)
    fdef.body = (
        [ast.Assign(targets=[_name(rv, ast.Store())],
                    value=ast.Constant(value=None))]
        + new
        + [ast.Return(value=_name(rv, ast.Load()))])
    return True


_MUTATOR_METHODS = _purity.MUTATOR_METHODS


def _has_uncarried_mutation(stmts, carried: Set[str]) -> bool:
    """True when a loop body mutates python-level state that is NOT
    loop-carried: container mutator methods (lst.append, d.update, ...),
    paddle in-place tensor ops (trailing underscore: add_, clip_, ...),
    and subscript/attribute stores whose base name isn't carried. A
    compiled loop traces its body ONCE, so such mutations would run
    once instead of per-iteration — silently diverging from eager
    (measured: 5 eager appends vs 2 under the old conversion). Carried
    names are safe: their updates flow functionally through the carry
    (and non-jax carried types fail while_loop into the eager
    fallback)."""
    found = [False]

    def base_name(n):
        while isinstance(n, (ast.Subscript, ast.Attribute)):
            n = n.value
        return n.id if isinstance(n, ast.Name) else None

    class V(ast.NodeVisitor):
        def visit_Call(self, node):
            f = node.func
            if isinstance(f, ast.Attribute):
                mut = f.attr in _MUTATOR_METHODS or (
                    f.attr.endswith("_") and not f.attr.endswith("__"))
                if mut and base_name(f.value) not in carried:
                    found[0] = True
            elif isinstance(f, ast.Name) and f.id in ("setattr", "delattr"):
                found[0] = True
            self.generic_visit(node)

        def _store_target(self, t):
            if isinstance(t, (ast.Subscript, ast.Attribute)):
                if base_name(t) not in carried:
                    found[0] = True
            elif isinstance(t, (ast.Tuple, ast.List)):
                for e in t.elts:
                    self._store_target(e)
            elif isinstance(t, ast.Starred):
                self._store_target(t.value)

        def visit_Assign(self, node):
            for t in node.targets:
                self._store_target(t)
            self.generic_visit(node)

        def visit_AnnAssign(self, node):
            self._store_target(node.target)
            self.generic_visit(node)

        def visit_AugAssign(self, node):
            self._store_target(node.target)
            self.generic_visit(node)

        def visit_Delete(self, node):
            for t in node.targets:
                self._store_target(t)
            self.generic_visit(node)

        def visit_FunctionDef(self, node):
            pass

        visit_AsyncFunctionDef = visit_FunctionDef
        visit_Lambda = visit_FunctionDef
        visit_ClassDef = visit_FunctionDef

    for s in stmts:
        V().visit(s)
    return found[0]


def _name(id_, ctx):
    return ast.Name(id=id_, ctx=ctx)


def _tuple_of(names: List[str], ctx):
    return ast.Tuple(elts=[_name(n, ctx) for n in names], ctx=ctx)


def _live_read_names(stmts) -> Set[str]:
    """Over-approximate liveness reads: EVERY Name load, including
    inside nested function/lambda bodies (unlike _read_names, which
    models direct-scope reads for the captured-defaults machinery —
    liveness must see closure reads too or it would prune a name a
    nested def still needs)."""
    names: Set[str] = set()
    for s in stmts:
        for n in ast.walk(s):
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
                names.add(n.id)
            elif isinstance(n, ast.AugAssign) and isinstance(
                    n.target, ast.Name):
                # `y += 1` requires y bound — a liveness USE even
                # though the target ctx is Store
                names.add(n.target.id)
            elif isinstance(n, ast.Delete):
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        names.add(t.id)
    return names


class _Rewriter:
    """Statement-list rewriter tracking which names are bound so far (to
    know when a branch-assigned name needs an undefined-sentinel init)."""

    def __init__(self):
        self.count = 0
        self.uid = 0

    def rewrite_body(self, stmts, bound: Set[str],
                     live_after: Set[str] = frozenset()) -> List[ast.stmt]:
        out: List[ast.stmt] = []
        # names live AFTER each statement: one backward accumulation
        # (recomputing reads of every suffix would be O(n^2) AST walks)
        suffix = [set(live_after)]
        for st in reversed(stmts[1:] if stmts else []):
            suffix.append(suffix[-1] | _live_read_names([st]))
        suffix.reverse()
        for i, st in enumerate(stmts):
            live = suffix[i]
            if isinstance(st, ast.If) and not _blocked(st.body + st.orelse):
                out.extend(self._rewrite_if(st, bound, live))
            elif isinstance(st, ast.While) and not st.orelse:
                # bodies with break/continue are lowered to masked flags
                # inside _rewrite_while; return/yield (or flags in
                # opaque compounds) leave the loop as plain python
                out.extend(self._rewrite_while(st, bound))
            elif isinstance(st, ast.For) and not st.orelse \
                    and isinstance(st.target, ast.Name):
                out.extend(self._rewrite_for(st, bound))
            else:
                # recurse into compound statements' bodies in place.
                # Sibling fields of the SAME statement (while/for else,
                # try handlers/finally) run after the field being
                # rewritten, so their reads must join the liveness —
                # over-approximate with the whole statement's reads
                live_in_st = live | _live_read_names([st])
                for field in ("body", "orelse", "finalbody"):
                    sub = getattr(st, field, None)
                    if sub and not isinstance(
                            st, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                        setattr(st, field,
                                self.rewrite_body(sub, bound, live_in_st))
                out.append(st)
            bound |= _assigned_names([st])
        return out

    def _fn_def(self, fname, params, body, result_names,
                default_params=()):
        """`params` are plain positional args (while carried vars);
        `default_params` become keyword args whose defaults capture the
        CURRENT outer value at definition time — this is how an extracted
        branch can read a name it also assigns (a bare closure read would
        be an UnboundLocalError once the name becomes function-local)."""
        body = list(body)
        body.append(ast.Return(value=_tuple_of(result_names, ast.Load())))
        args = ast.arguments(
            posonlyargs=[],
            args=[ast.arg(arg=p) for p in params]
            + [ast.arg(arg=p) for p in default_params],
            vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None,
            defaults=[_name(p, ast.Load()) for p in default_params])
        return ast.FunctionDef(name=fname, args=args, body=body,
                               decorator_list=[], returns=None)

    def _rewrite_if(self, node: ast.If, bound: Set[str],
                    live: Set[str] = frozenset()) -> List[ast.stmt]:
        self.uid += 1
        k = self.uid
        targets_all = _assigned_names(node.body) | _assigned_names(
            node.orelse)
        # LIVENESS PRUNING: only names read after the if join the
        # select — a branch-local temp assigned in one branch would
        # otherwise force a select against an undefined sentinel and
        # fail the whole conversion (the single-exit return lowering
        # produces exactly that shape: rv assigned in every path, the
        # temp dead after)
        targets = sorted(t for t in targets_all if t in live)
        body = self.rewrite_body(node.body, set(bound),
                                 set(targets) | set(live))
        orelse = self.rewrite_body(node.orelse, set(bound),
                                   set(targets) | set(live)) \
            if node.orelse else [ast.Pass()]
        # names a branch reads AND a branch assigns: must enter as
        # captured default params (see _fn_def); the sentinel inits
        # below guarantee the default expression is evaluable
        reads = _read_names(node.body) | _read_names(node.orelse)
        captured = sorted(reads & targets_all)
        pre: List[ast.stmt] = []
        for t in sorted(set(targets) | set(captured)):
            if t not in bound:
                pre.append(ast.Assign(
                    targets=[_name(t, ast.Store())],
                    value=ast.Call(
                        func=_name("__pt_undef", ast.Load()),
                        args=[ast.Constant(value=t)], keywords=[])))
        tf = self._fn_def(f"__pt_true_{k}", [], body, targets,
                          default_params=captured)
        ff = self._fn_def(f"__pt_false_{k}", [], orelse, targets,
                          default_params=captured)
        call = ast.Call(func=_name("__pt_run_if", ast.Load()),
                        args=[_lower_bool_test(node.test),
                              _name(tf.name, ast.Load()),
                              _name(ff.name, ast.Load())], keywords=[])
        if targets:
            assign: ast.stmt = ast.Assign(
                targets=[_tuple_of(targets, ast.Store())], value=call)
        else:
            assign = ast.Expr(value=call)
        self.count += 1
        return pre + [tf, ff, assign]

    def _lower_flags(self, stmts):
        """break/continue -> masked flags (see _mask_ctrl). Returns
        (new_stmts, brk_name|None, cont_name|None) or None."""
        self.uid += 1
        brk = f"__pt_brk_{self.uid}"
        cont = f"__pt_cont_{self.uid}"
        res = _mask_ctrl(stmts, brk, cont)
        if res is None:
            return None
        new, used_b, used_c = res
        if not (used_b or used_c):
            # the blockage belongs to NESTED loops (their own
            # break/continue): nothing to mask here — convert this
            # loop normally; rewrite_body lowers the inner loops
            return stmts, None, None
        if used_c:
            # continue-flag resets at the top of EVERY iteration
            new = [_assign_flag(cont, False)] + new
        return new, (brk if used_b else None), (cont if used_c else None)

    def _loop_pre_inits(self, carried, bound, flag_names):
        pre: List[ast.stmt] = []
        for t in carried:
            if t in flag_names:
                pre.append(_assign_flag(t, False))
            elif t not in bound:
                pre.append(ast.Assign(
                    targets=[_name(t, ast.Store())],
                    value=ast.Call(
                        func=_name("__pt_undef", ast.Load()),
                        args=[ast.Constant(value=t)], keywords=[])))
        return pre

    def _keep_plain(self, node, bound):
        """Leave the loop as plain python but still rewrite its body so
        nested convertible ifs/loops compile (the pre-flag-lowering code
        reached these through rewrite_body's fallthrough branch). The
        after-loop liveness is unknown here, so over-approximate with
        everything the loop reads or assigns (pruning less only costs
        select width, never correctness)."""
        live = _live_read_names([node]) | _assigned_names(node.body)
        node.body = self.rewrite_body(node.body, set(bound), live)
        return [node]

    def _rewrite_while(self, node: ast.While,
                      bound: Set[str]) -> List[ast.stmt]:
        body_src = node.body
        brk_name = cont_name = None
        if _blocked(node.body):
            low = self._lower_flags(node.body)
            if low is None:
                # return/yield or opaque break: plain python loop
                return self._keep_plain(node, bound)
            body_src, brk_name, cont_name = low
        self.uid += 1
        k = self.uid
        carried = sorted(_assigned_names(body_src))
        if not carried:
            # nothing loop-carried: plain python loop
            return self._keep_plain(node, bound)
        if _has_uncarried_mutation(body_src, set(carried)) \
                or _has_uncarried_mutation(
                    [ast.Expr(value=node.test)], set(carried)):
            # trace-once conversion would run the mutation once, not
            # per-iteration — plain python keeps eager semantics (the
            # TEST is also per-iteration code: `while stack.pop():`).
            # Promoted to a reportable diagnostic (tpu-lint A5).
            _purity.record_loop_mutation(node.lineno, "while loop")
            return self._keep_plain(node, bound)
        # carried names are body-fn PARAMS — bound at body entry (flags
        # are pre-initialized to False; without this an if that only
        # assigns a flag would wrongly sentinel-init it). live_after:
        # every carried name feeds the next iteration / the result
        # tuple, plus anything the body itself reads
        body = self.rewrite_body(
            body_src, set(bound) | set(carried),
            set(carried) | _live_read_names(body_src))
        flag_names = {n for n in (brk_name, cont_name) if n}
        pre = self._loop_pre_inits(carried, bound, flag_names)
        cf = self._fn_def(f"__pt_cond_{k}", carried,
                          [], [])  # placeholder, replaced below
        cf.body = [ast.Return(value=_lower_bool_test(node.test))]
        bf = self._fn_def(f"__pt_body_{k}", carried, body, carried)
        kw = []
        if brk_name is not None:
            kw.append(ast.keyword(
                arg="brk_idx",
                value=ast.Constant(value=carried.index(brk_name))))
        call = ast.Call(
            func=_name("__pt_run_while", ast.Load()),
            args=[_name(cf.name, ast.Load()), _name(bf.name, ast.Load()),
                  _tuple_of(carried, ast.Load())], keywords=kw)
        assign = ast.Assign(targets=[_tuple_of(carried, ast.Store())],
                            value=call)
        self.count += 1
        return pre + [cf, bf, assign]

    def _rewrite_for(self, node: ast.For,
                     bound: Set[str]) -> List[ast.stmt]:
        """`for t in range(...)` -> __pt_run_for_range (lowers to
        while_loop on a traced bound); `for t in seq` ->
        __pt_run_for_iter (static trip count over tensors). Parity:
        reference loop_transformer.py:111-138 converts both forms."""
        body_src = node.body
        brk_name = cont_name = None
        if _blocked(node.body):
            low = self._lower_flags(node.body)
            if low is None:
                # return/yield or opaque break: plain python loop
                return self._keep_plain(node, bound)
            body_src, brk_name, cont_name = low
        self.uid += 1
        k = self.uid
        tname = node.target.id
        carried = sorted(_assigned_names(body_src) - {tname})
        if _has_uncarried_mutation(body_src, set(carried) | {tname}):
            # see _rewrite_while: mutations of non-carried state must
            # keep plain-python per-iteration semantics (recorded as a
            # tpu-lint A5 diagnostic like the while case)
            _purity.record_loop_mutation(node.lineno, "for loop")
            return self._keep_plain(node, bound)
        body = self.rewrite_body(
            body_src, set(bound) | {tname} | set(carried),
            {tname} | set(carried) | _live_read_names(body_src))
        flag_names = {n for n in (brk_name, cont_name) if n}
        pre = self._loop_pre_inits([tname] + carried, bound, flag_names)
        bf = self._fn_def(f"__pt_forbody_{k}", [tname] + carried, body,
                          [tname] + carried)
        loop_vars = _tuple_of([tname] + carried, ast.Load())
        kw = []
        if brk_name is not None:
            kw.append(ast.keyword(
                arg="brk_idx",
                value=ast.Constant(value=carried.index(brk_name))))
        it = node.iter
        if isinstance(it, ast.Call) and isinstance(it.func, ast.Name) \
                and it.func.id == "range" and not it.keywords \
                and 1 <= len(it.args) <= 3 \
                and not any(isinstance(a, ast.Starred) for a in it.args):
            a = list(it.args)
            if len(a) == 1:
                start, stop, step = ast.Constant(0), a[0], ast.Constant(1)
            elif len(a) == 2:
                start, stop, step = a[0], a[1], ast.Constant(1)
            else:
                start, stop, step = a
            call = ast.Call(
                func=_name("__pt_run_for_range", ast.Load()),
                args=[start, stop, step, _name(bf.name, ast.Load()),
                      loop_vars], keywords=kw)
        else:
            call = ast.Call(
                func=_name("__pt_run_for_iter", ast.Load()),
                args=[it, _name(bf.name, ast.Load()), loop_vars],
                keywords=kw)
        assign = ast.Assign(
            targets=[_tuple_of([tname] + carried, ast.Store())],
            value=call)
        self.count += 1
        return pre + [bf, assign]


def try_convert(fn) -> Optional[types.FunctionType]:
    """AST-convert `fn`'s data-dependent control flow. Returns the
    converted callable, or None when nothing was (or could be)
    converted. Never raises."""
    try:
        return _convert(fn)
    except Exception:
        return None


def _convert(fn):
    bound_self = getattr(fn, "__self__", None)
    func = fn.__func__ if bound_self is not None else fn
    if not isinstance(func, types.FunctionType):
        return None
    src = textwrap.dedent(inspect.getsource(func))
    tree = ast.parse(src)
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return None
    fdef.decorator_list = []
    if any(isinstance(n, ast.Global) for n in ast.walk(fdef)):
        # the recompiled copy executes in a COPIED globals dict, so a
        # `global x` write would update the snapshot, not the module —
        # silent state divergence (also covers the eager-fallback path,
        # which permanently runs the copy after a second graph break)
        return None
    # stamp the purity-diagnostic context (tpu-lint A5): rewrite-time
    # declines map their AST-relative linenos back to the real file
    try:
        first_line = inspect.getsourcelines(func)[1]
    except (OSError, TypeError):
        first_line = 1
    _purity.set_context(inspect.getsourcefile(func), first_line,
                        func.__qualname__)
    try:
        # single-exit lowering FIRST: ifs that return become rv-assigning
        # ifs the rewriter below can convert (traced early returns
        # otherwise always fall back to eager)
        _maybe_single_exit(fdef)
        ifexp = _IfExpLowerer()
        ifexp.visit(fdef)
        rw = _Rewriter()
        arg_names = {a.arg for a in (fdef.args.posonlyargs + fdef.args.args
                                     + fdef.args.kwonlyargs)}
        if fdef.args.vararg:
            arg_names.add(fdef.args.vararg.arg)
        if fdef.args.kwarg:
            arg_names.add(fdef.args.kwarg.arg)
        fdef.body = rw.rewrite_body(fdef.body, set(arg_names))
    finally:
        _purity.clear_context()
    if rw.count == 0 and ifexp.count == 0:
        return None
    ast.fix_missing_locations(tree)
    code = compile(tree, filename=f"<dy2static {func.__name__}>",
                   mode="exec")
    namespace = dict(func.__globals__)
    # closure cells bound by value (documented restriction)
    for name, cell in zip(func.__code__.co_freevars,
                          func.__closure__ or ()):
        try:
            namespace[name] = cell.cell_contents
        except ValueError:
            return None  # empty cell: cannot snapshot
    namespace["__pt_run_if"] = _run_if
    namespace["__pt_run_while"] = _run_while
    namespace["__pt_run_for_range"] = _run_for_range
    namespace["__pt_run_for_iter"] = _run_for_iter
    namespace["__pt_undef"] = _Undefined
    namespace["__pt_none_set"] = _none_set
    namespace["__pt_bool_and"] = _bool_and
    namespace["__pt_bool_or"] = _bool_or
    namespace["__pt_bool_not"] = _bool_not
    namespace["__pt_chain"] = _chain
    exec(code, namespace)
    new_fn = namespace[fdef.name]
    functools.update_wrapper(new_fn, func)
    new_fn._dy2static_converted = rw.count + ifexp.count
    if bound_self is not None:
        return types.MethodType(new_fn, bound_self)
    return new_fn
