"""ProgramCache: the ONE owner of every bucketed compiled program.

Until ISSUE 8 the prefill/chunk, decode, verify and draft-model program
buckets lived in engine-local dicts with hand-maintained count bounds —
tolerable for a (family, B, P) key space, but TP serving multiplies
every key by the mesh shape and quantized serving already multiplied it
by (kv_dtype, wq). This module centralizes the store so the
TP x quant x spec key space has one owner:

* keys are tuples whose FIRST element names the program family
  ("chunk", "decode", "verify", ... — families are registered up front
  with their bucket-grid bound);
* `get(key, builder)` compiles on miss, reports the compile through the
  `on_compile` hook (the engine wires it to
  `ServingMetrics.on_recompile`), and ENFORCES the registered family
  bound — exceeding it raises instead of silently recompiling forever,
  because an unbounded program cache is exactly the bug the bucket grid
  exists to prevent;
* per-family counts (`counts()`) and bounds (`max_count(family)`)
  replace the single flat number, so "which family is compiling?" is
  answerable from metrics instead of a debugger.

The bound callables are evaluated lazily (engines finalize their bucket
lists after construction-time clamping), and the bound is the grid for
ONE mesh shape — an engine owns one mesh, so its key space is
`bucket grid x {its mesh shape}`; processes mixing TP degrees get one
cache per engine and the global compile count stays the sum of the
per-engine grids (the "mesh shapes actually used" bound in ISSUE 8).
"""
from __future__ import annotations

from typing import Callable, Dict, Optional

__all__ = ["ProgramCache"]


class ProgramCache:
    """Keyed store of compiled programs with per-family compile bounds.

    on_compile: optional zero-arg hook fired once per compilation (cache
    miss) — the engine's recompile counter.
    """

    def __init__(self, on_compile: Optional[Callable[[], None]] = None):
        self._programs: Dict[tuple, object] = {}
        self._bounds: Dict[str, Callable[[], int]] = {}
        self._counts: Dict[str, int] = {}
        self._on_compile = on_compile

    def register_family(self, family: str, bound: Callable[[], int]):
        """Declare a program family and its (lazily evaluated) compile
        bound — the bucket-grid size for this family."""
        self._bounds[family] = bound
        self._counts.setdefault(family, 0)
        return self

    # ------------------------------------------------------------- access
    def get(self, key: tuple, builder: Callable[[], object]):
        """The program for `key` (key[0] = family), compiling via
        `builder` on miss. Raises KeyError for an unregistered family
        and RuntimeError when a compile would exceed the family bound —
        a blown bound means a key axis leaked out of the bucket grid
        (the unbounded-recompilation bug class), which must fail loud,
        not page the on-call about mystery latency."""
        prog = self._programs.get(key)
        if prog is not None:
            return prog
        family = key[0]
        if family not in self._bounds:
            raise KeyError(f"unregistered program family {family!r} "
                           f"(known: {sorted(self._bounds)})")
        bound = int(self._bounds[family]())
        if self._counts[family] + 1 > bound:
            raise RuntimeError(
                f"program family {family!r} would exceed its compile "
                f"bound {bound} with key {key!r} — a key axis is not "
                f"riding the bucket grid")
        prog = builder()
        self._programs[key] = prog
        self._counts[family] += 1
        if self._on_compile is not None:
            self._on_compile()
        return prog

    # ------------------------------------------------------------ counts
    @property
    def num_programs(self) -> int:
        return len(self._programs)

    def counts(self) -> Dict[str, int]:
        """{family: programs compiled} — every registered family
        appears, compiled or not."""
        return dict(self._counts)

    def max_count(self, family: Optional[str] = None) -> int:
        """The compile bound: one family's grid, or (default) the sum
        over every registered family."""
        if family is not None:
            return int(self._bounds[family]())
        return sum(int(b()) for b in self._bounds.values())

    def keys(self):
        """The live program keys (tests assert the key-suffix axes —
        quant config, mesh shape — actually ride them)."""
        return list(self._programs.keys())

    def __len__(self):
        return len(self._programs)

    def __contains__(self, key):
        return key in self._programs
