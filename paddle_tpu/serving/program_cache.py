"""ProgramCache: the ONE owner of every bucketed compiled program.

Until ISSUE 8 the prefill/chunk, decode, verify and draft-model program
buckets lived in engine-local dicts with hand-maintained count bounds —
tolerable for a (family, B, P) key space, but TP serving multiplies
every key by the mesh shape and quantized serving already multiplied it
by (kv_dtype, wq). This module centralizes the store so the
TP x quant x spec key space has one owner:

* keys are tuples whose FIRST element names the program family
  ("chunk", "decode", "verify", ... — families are registered up front
  with their bucket-grid bound);
* `get(key, builder)` compiles on miss, reports the compile through the
  `on_compile` hook (the engine wires it to
  `ServingMetrics.on_recompile`), and ENFORCES the registered family
  bound — exceeding it raises instead of silently recompiling forever,
  because an unbounded program cache is exactly the bug the bucket grid
  exists to prevent;
* per-family counts (`counts()`) and bounds (`max_count(family)`)
  replace the single flat number, so "which family is compiling?" is
  answerable from metrics instead of a debugger.

The bound callables are evaluated lazily (engines finalize their bucket
lists after construction-time clamping), and the bound is the grid for
ONE mesh shape — an engine owns one mesh, so its key space is
`bucket grid x {its mesh shape}`; processes mixing TP degrees get one
cache per engine and the global compile count stays the sum of the
per-engine grids (the "mesh shapes actually used" bound in ISSUE 8).

Observability (ISSUE 11): every stored program rides in a thin
`_TrackedProgram` wrapper — its FIRST launch (the jit trace+compile)
is timed and logged to the shared compile-event ring
(`profiler.compile_log`, kind `program_compile`), and the launch args'
ShapeDtypeStructs are recorded so `cost_table()` can re-lower each
program for XLA cost/memory accounting (`profiler.cost`) without
holding tensor data. Steady-state launches pay one attribute check.
"""
from __future__ import annotations

import time
from typing import Callable, Dict, Optional

__all__ = ["ProgramCache"]


class _TrackedProgram:
    """Callable wrapper over one compiled-program builder result: times
    the first launch (= jit compile) and keeps abstract arg shapes for
    later cost accounting. Transparent to call sites — engines only
    ever `prog(*args)`.

    Disk-loaded programs (ISSUE 14) ride the same wrapper with
    `from_disk=True` and a `fallback` builder: a deserialized
    executable that fails its FIRST call (a stale entry whose damage
    the checksums could not see — e.g. an aval-shape drift) is
    replaced by a fresh build in place, counted as a cache reject —
    the persistent cache degrades to recompile, never to a crashed
    worker."""

    __slots__ = ("fn", "key", "first_call_ms", "arg_avals", "_cost",
                 "_comm", "from_disk", "fallback", "on_reject")

    def __init__(self, fn, key, *, from_disk=False, fallback=None,
                 on_reject=None):
        self.fn = fn
        self.key = key
        self.first_call_ms = None
        self.arg_avals = None
        self._cost = None
        self._comm = {}
        self.from_disk = from_disk
        self.fallback = fallback
        self.on_reject = on_reject

    def __call__(self, *args):
        if self.first_call_ms is None:
            t0 = time.perf_counter()
            if self.from_disk and self.fallback is not None:
                try:
                    out = self.fn(*args)
                except Exception as exc:                  # noqa: BLE001
                    # Only a FATAL failure indicts the ENTRY (stale
                    # avals, foreign executable). Transient device
                    # errors and poison must propagate to the engine's
                    # supervisor — its retry path owns the donated-
                    # buffer hazard, and a perfectly good entry must
                    # not be rejected for the device's flakiness.
                    from .supervisor import FATAL, classify_failure
                    if classify_failure(exc) != FATAL:
                        raise
                    self.fn = self.fallback()
                    self.from_disk = False
                    if self.on_reject is not None:
                        self.on_reject()
                    out = self.fn(*args)
            else:
                out = self.fn(*args)
            dt = time.perf_counter() - t0
            self.first_call_ms = round(dt * 1e3, 3)
            try:
                from ..profiler.cost import shape_structs
                self.arg_avals = shape_structs(list(args))
            except Exception:
                self.arg_avals = None
            from ..profiler import compile_log
            compile_log.log_event(
                "program_compile", name=str(self.key[0]), duration_s=dt,
                detail={"key": repr(self.key)[:120],
                        "from_disk": self.from_disk})
            return out
        return self.fn(*args)

    def cost_report(self) -> Optional[dict]:
        """XLA cost/memory accounting of this program (lazy, cached):
        re-lowers from the recorded arg avals — only possible for
        jax.jit-built programs that have launched at least once."""
        if self._cost is not None:
            return self._cost
        if self.arg_avals is None or not hasattr(self.fn, "lower"):
            return None
        try:
            from ..profiler import cost as _cost
            rec = _cost.lowered_cost(
                self.fn.lower(*self.arg_avals)).to_dict()
        except Exception as e:   # accounting must never break serving
            # transient failures are NOT cached — the next call retries
            rec = {"error": f"{type(e).__name__}: {e}"[:200]}
            rec["compile_ms"] = self.first_call_ms
            return rec
        rec["compile_ms"] = self.first_call_ms
        self._cost = rec
        return rec

    def comm_report(self, mesh=None) -> Optional[dict]:
        """Collective-traffic accounting of this program (ISSUE 12):
        op counts + payload bytes per mesh axis from the compiled HLO
        (`profiler.comm`). A meshless call resolves the ambient hybrid
        mesh FIRST — `lowered_comm` would fall back to it anyway, so
        resolving up front keeps the cache key (the mesh-axes
        signature) matched to the attribution actually performed; with
        no mesh anywhere, ops stay unattributed under the None key."""
        from ..profiler import comm as _comm
        if mesh is None:
            mesh = _comm._default_mesh()
        try:
            axes = tuple(getattr(mesh, "jax_mesh", mesh).axis_names) \
                if mesh is not None else None
        except Exception:
            axes = None
        if axes in self._comm:
            return self._comm[axes]
        if self.arg_avals is None or not hasattr(self.fn, "lower"):
            return None
        try:
            rec = _comm.lowered_comm(
                self.fn.lower(*self.arg_avals), mesh=mesh).to_dict()
        except Exception as e:   # accounting must never break serving
            # transient failures are NOT cached — the next call retries
            return {"error": f"{type(e).__name__}: {e}"[:200]}
        self._comm[axes] = rec
        return rec


class ProgramCache:
    """Keyed store of compiled programs with per-family compile bounds.

    on_compile: optional zero-arg hook fired once per compilation (cache
    miss) — the engine's recompile counter.
    """

    def __init__(self, on_compile: Optional[Callable[[], None]] = None,
                 disk=None):
        self._programs: Dict[tuple, object] = {}
        self._bounds: Dict[str, Callable[[], int]] = {}
        self._counts: Dict[str, int] = {}
        self._on_compile = on_compile
        # optional persistent CompileCache (ISSUE 14): consulted on
        # every miss BEFORE the builder; set post-construction by the
        # engine (`self.programs.disk = CompileCache(...)`)
        self.disk = disk

    def register_family(self, family: str, bound: Callable[[], int]):
        """Declare a program family and its (lazily evaluated) compile
        bound — the bucket-grid size for this family."""
        self._bounds[family] = bound
        self._counts.setdefault(family, 0)
        return self

    # ------------------------------------------------------------- access
    def get(self, key: tuple, builder: Callable[[], object]):
        """The program for `key` (key[0] = family), compiling via
        `builder` on miss. Raises KeyError for an unregistered family
        and RuntimeError when a compile would exceed the family bound —
        a blown bound means a key axis leaked out of the bucket grid
        (the unbounded-recompilation bug class), which must fail loud,
        not page the on-call about mystery latency."""
        prog = self._programs.get(key)
        if prog is not None:
            return prog
        family = key[0]
        if family not in self._bounds:
            raise KeyError(f"unregistered program family {family!r} "
                           f"(known: {sorted(self._bounds)})")
        bound = int(self._bounds[family]())
        if self._counts[family] + 1 > bound:
            raise RuntimeError(
                f"program family {family!r} would exceed its compile "
                f"bound {bound} with key {key!r} — a key axis is not "
                f"riding the bucket grid")
        loaded = self.disk.load(key) if self.disk is not None else None
        if loaded is not None:
            # disk hit: the deserialized executable skips trace AND
            # compile; `builder` stays attached as the first-call
            # fallback, and a fallback rebuild counts a disk reject.
            # NOT a compile for on_compile/metrics purposes — the
            # recompiles counter keeps meaning "XLA compiled here".
            def _reject():
                # hits stays MONOTONIC (it is exposed as a Prometheus
                # counter; a decrement would read as a counter reset):
                # net useful hits = hits - rejects
                self.disk.counters["rejects"] += 1
                # a checksummed-but-unrunnable entry: mark it so the
                # next save_all REWRITES it from the fresh build
                self.disk.rejected_keys.add(key)
                if self._on_compile is not None:
                    self._on_compile()   # the fallback IS a compile
            prog = _TrackedProgram(loaded, key, from_disk=True,
                                   fallback=builder, on_reject=_reject)
        else:
            prog = _TrackedProgram(builder(), key)
            if self._on_compile is not None:
                self._on_compile()
        self._programs[key] = prog
        self._counts[family] += 1
        return prog

    # ------------------------------------------------------------ counts
    @property
    def num_programs(self) -> int:
        return len(self._programs)

    def counts(self) -> Dict[str, int]:
        """{family: programs compiled} — every registered family
        appears, compiled or not."""
        return dict(self._counts)

    def max_count(self, family: Optional[str] = None) -> int:
        """The compile bound: one family's grid, or (default) the sum
        over every registered family."""
        if family is not None:
            return int(self._bounds[family]())
        return sum(int(b()) for b in self._bounds.values())

    def keys(self):
        """The live program keys (tests assert the key-suffix axes —
        quant config, mesh shape — actually ride them)."""
        return list(self._programs.keys())

    # ------------------------------------------------------- accounting
    def compile_times_ms(self) -> Dict[tuple, Optional[float]]:
        """{key: first-launch wall ms} — None for programs never
        launched (built but not yet called)."""
        return {k: p.first_call_ms for k, p in self._programs.items()}

    def cost_table(self) -> Dict[tuple, Optional[dict]]:
        """{key: XLA cost/memory dict} over every launched program
        (ISSUE 11) — flops, bytes, peak_bytes per bucketed program, so
        "which bucket family is paying for its HBM" is answerable from
        metrics. Lazy: each program's accounting is computed once, on
        the first cost_table() call after its first launch."""
        return {k: p.cost_report() for k, p in self._programs.items()}

    def comm_table(self, mesh=None) -> Dict[tuple, Optional[dict]]:
        """{key: collective-traffic dict} over every launched program
        (ISSUE 12) — op counts and payload bytes per mesh axis, so
        "which bucketed program moves how much over 'model'" is
        answerable from metrics (the TP row-parallel psum shows up on
        the decode family's rows). Pass the engine's mesh for axis
        attribution; `ServingEngine.comm_table()` does."""
        return {k: p.comm_report(mesh=mesh)
                for k, p in self._programs.items()}

    def family_costs(self) -> Dict[str, dict]:
        """Per-family aggregate of cost_table(): program count, summed
        flops, max peak_bytes — the capacity-planning view."""
        out: Dict[str, dict] = {}
        for key, rec in self.cost_table().items():
            fam = out.setdefault(str(key[0]), {
                "programs": 0, "accounted": 0, "flops": 0.0,
                "max_peak_bytes": 0})
            fam["programs"] += 1
            if not rec or "error" in rec:
                continue
            fam["accounted"] += 1
            fam["flops"] += rec.get("flops", 0.0)
            fam["max_peak_bytes"] = max(fam["max_peak_bytes"],
                                        rec.get("peak_bytes", 0))
        return out

    def __len__(self):
        return len(self._programs)

    def __contains__(self, key):
        return key in self._programs
