"""End-to-end request tracing + engine flight recorder (ISSUE 10).

Two host-side recorders over the serving stack, both deliberately NOT a
second profiler — they reuse the profiler's clock and export format so
one Perfetto load shows everything on a shared timeline:

* **RequestTracer / RequestTrace** — one trace per request, carried from
  Fleet admission through routing, prefill chunks, decode/verify
  iterations, supervisor retries, quarantine and migration park/re-land.
  Spans and marks are stamped with the SAME `time.perf_counter_ns`
  clock `profiler.RecordEvent` uses, so `export()` merges the request
  lifecycle rows with the profiler's host spans into ONE chrome-trace
  JSON (`{"traceEvents": ...}`) that Perfetto opens directly: host work
  (pid = this process) next to request rows (pid = `REQUEST_PID`, one
  tid per request id). Completed traces live in a bounded ring —
  a long-lived server never accumulates one entry per request ever
  served (the `max_retained_finished` lesson, applied to traces).

  Cheap-when-on, free-when-off: the engine holds `tracer=None` by
  default and every call site is guarded by that one check, so the
  default hot path allocates NOTHING trace-related (asserted by
  tests/test_serving_trace.py). A fleet shares ONE tracer across its
  replicas (pass the same instance to every engine) so a migrated
  request's trace follows it across engines.

* **FlightRecorder** — a bounded ring of per-iteration `StepRecord`
  dicts (program launches with bucket keys, batch composition, tokens
  in/out, pool occupancy, radix/spec stats, retry/quarantine counts,
  step latency). Always on (one small dict per non-idle step),
  queryable via `ServingEngine.timeline()`, and attached to every
  engine snapshot — so an `engine_failures` postmortem ships the last
  N steps of context with the drain state (the PR-3 snapshot).

All record payloads are JSON-safe by construction (plain ints / floats
/ strings / lists / dicts) — the snapshot contract requires it.
"""
from __future__ import annotations

import json
import time
from collections import deque
from typing import Dict, List, Optional

__all__ = ["RequestTrace", "RequestTracer", "FlightRecorder",
           "REQUEST_PID"]

# chrome-trace pid for the per-request rows; the profiler's host spans
# keep os.getpid(), so the two groups render as separate named
# processes in Perfetto (metadata events label both)
REQUEST_PID = 1


def _json_safe(v):
    """Coerce span/mark args to JSON-safe plain types (numpy ints from
    token ids are the common offender)."""
    if isinstance(v, bool) or v is None or isinstance(v, str):
        return v
    if isinstance(v, (int, float)):
        return v
    if isinstance(v, (list, tuple)):
        return [_json_safe(x) for x in v]
    if isinstance(v, dict):
        return {str(k): _json_safe(x) for k, x in v.items()}
    # numpy scalars (and 0-d arrays): .item() preserves the value's
    # kind — int(np.float32(0.37)) would silently truncate to 0
    item = getattr(v, "item", None)
    if item is not None:
        try:
            unwrapped = item()
        except (TypeError, ValueError):
            unwrapped = None
        if isinstance(unwrapped, (bool, int, float, str)):
            return unwrapped
    try:
        return int(v)
    except (TypeError, ValueError):
        try:
            return float(v)
        except (TypeError, ValueError):
            return str(v)


class RequestTrace:
    """One request's lifecycle: spans (named intervals) + marks (named
    instants), all in perf_counter nanoseconds."""

    __slots__ = ("request_id", "meta", "spans", "marks", "t_begin",
                 "t_end", "t_queue", "finish_reason")

    def __init__(self, request_id: int, t_begin: int, **meta):
        self.request_id = int(request_id)
        self.meta = {k: _json_safe(v) for k, v in meta.items()}
        self.spans: List[dict] = []
        self.marks: List[dict] = []
        self.t_begin = int(t_begin)
        self.t_end: Optional[int] = None
        # queue-wait anchor: reset at preemption / adoption so the next
        # admission's queue_wait span measures THIS wait, not the
        # request's whole life
        self.t_queue = int(t_begin)
        self.finish_reason: Optional[str] = None

    def span(self, name: str, t0: int, t1: int, **args):
        self.spans.append({"name": name, "t0": int(t0), "t1": int(t1),
                           "args": {k: _json_safe(v)
                                    for k, v in args.items()}})

    def mark(self, name: str, t: int, **args):
        self.marks.append({"name": name, "t": int(t),
                           "args": {k: _json_safe(v)
                                    for k, v in args.items()}})

    # ---- views -----------------------------------------------------------
    def span_names(self) -> List[str]:
        return [s["name"] for s in self.spans]

    def count_spans(self, name: str) -> int:
        return sum(1 for s in self.spans if s["name"] == name)

    def mark_names(self) -> List[str]:
        return [m["name"] for m in self.marks]

    def duration_ns(self) -> Optional[int]:
        if self.t_end is None:
            return None
        return self.t_end - self.t_begin

    def to_dict(self) -> dict:
        return {"request_id": self.request_id, "meta": dict(self.meta),
                "t_begin": self.t_begin, "t_end": self.t_end,
                "finish_reason": self.finish_reason,
                "spans": [dict(s) for s in self.spans],
                "marks": [dict(m) for m in self.marks]}

    def __repr__(self):
        state = self.finish_reason if self.finish_reason else "live"
        return (f"RequestTrace({self.request_id}, {state}, "
                f"spans={len(self.spans)}, marks={len(self.marks)})")


class RequestTracer:
    """Registry of live + completed request traces.

    `clock_ns` is injectable for deterministic tests but defaults to
    `time.perf_counter_ns` — the SAME clock `profiler.RecordEvent`
    stamps host spans with, which is what makes the merged export a
    single honest timeline. Every method is a no-op for unknown request
    ids, so call sites never need existence checks.
    """

    def __init__(self, max_completed: int = 512, clock_ns=None):
        self._clock_ns = (clock_ns if clock_ns is not None
                          else time.perf_counter_ns)
        self.live: Dict[int, RequestTrace] = {}
        self.completed: deque = deque(maxlen=int(max_completed))
        self.num_started = 0
        self.num_completed = 0

    def now_ns(self) -> int:
        return int(self._clock_ns())

    # ---- lifecycle -------------------------------------------------------
    def begin(self, request_id: int, **meta) -> RequestTrace:
        """Start (or return the live) trace for `request_id`.
        Idempotent on purpose: a migrated request re-`begin`s on its
        target engine and must keep accumulating into ONE trace."""
        tr = self.live.get(request_id)
        if tr is None:
            tr = RequestTrace(request_id, self.now_ns(), **meta)
            self.live[request_id] = tr
            self.num_started += 1
        return tr

    def get(self, request_id: int) -> Optional[RequestTrace]:
        return self.live.get(request_id)

    def span(self, request_id: int, name: str, t0: int, t1: int, **args):
        tr = self.live.get(request_id)
        if tr is not None:
            tr.span(name, t0, t1, **args)

    def span_many(self, request_ids, name: str, t0: int, t1: int,
                  **args):
        """One span on EVERY given request — the batched-launch hot
        path. The args are identical across the batch by contract, so
        they are sanitized once and the record dict is shared (export
        paths copy before annotating; nothing mutates stored spans)."""
        rec = {"name": name, "t0": int(t0), "t1": int(t1),
               "args": {k: _json_safe(v) for k, v in args.items()}}
        live = self.live
        for rid in request_ids:
            tr = live.get(rid)
            if tr is not None:
                tr.spans.append(rec)

    def mark(self, request_id: int, name: str, **args):
        tr = self.live.get(request_id)
        if tr is not None:
            tr.mark(name, self.now_ns(), **args)

    def finish(self, request_id: int, reason: str):
        """Move a live trace to the bounded completed ring (idempotent
        — the fleet and the engine may both observe a terminal state)."""
        tr = self.live.pop(request_id, None)
        if tr is None:
            return
        tr.t_end = self.now_ns()
        tr.finish_reason = str(reason)
        self.completed.append(tr)
        self.num_completed += 1

    # ---- views -----------------------------------------------------------
    def traces(self, include_live: bool = True) -> List[RequestTrace]:
        out = list(self.completed)
        if include_live:
            out.extend(self.live.values())
        return out

    # ---- export ----------------------------------------------------------
    def chrome_events(self) -> List[dict]:
        """Request lifecycle rows as chrome-trace events (ts/dur in
        microseconds, one tid per request id under REQUEST_PID)."""
        events = [{"name": "process_name", "ph": "M", "pid": REQUEST_PID,
                   "args": {"name": "serving requests"}}]
        for tr in self.traces():
            events.append({"name": "thread_name", "ph": "M",
                           "pid": REQUEST_PID, "tid": tr.request_id,
                           "args": {"name": f"req {tr.request_id}"}})
            for s in tr.spans:
                events.append({"name": s["name"], "ph": "X",
                               "cat": "request", "ts": s["t0"] / 1e3,
                               "dur": max(0.0, (s["t1"] - s["t0"]) / 1e3),
                               "pid": REQUEST_PID, "tid": tr.request_id,
                               "args": dict(s["args"],
                                            request_id=tr.request_id)})
            for m in tr.marks:
                events.append({"name": m["name"], "ph": "i", "s": "t",
                               "cat": "request", "ts": m["t"] / 1e3,
                               "pid": REQUEST_PID, "tid": tr.request_id,
                               "args": dict(m["args"],
                                            request_id=tr.request_id)})
        return events

    def export(self, path: Optional[str] = None,
               include_profiler: bool = True,
               flight_recorder=None) -> dict:
        """One merged chrome-trace document: request rows + (by
        default) the profiler's RecordEvent host spans, on the shared
        perf_counter clock. `flight_recorder` (a FlightRecorder or a
        plain record list) rides along under its own key for
        tools/trace_report.py. Writes JSON to `path` when given;
        returns the document either way."""
        events = self.chrome_events()
        if include_profiler:
            import os
            from .. import profiler
            host = profiler.host_events()
            if host:
                events.append({"name": "process_name", "ph": "M",
                               "pid": os.getpid(),
                               "args": {"name": "host spans"}})
            for e in host:
                events.append({"name": e["name"], "ph": "X",
                               "cat": e["type"], "ts": e["ts"] / 1e3,
                               "dur": e["dur"] / 1e3,
                               "pid": os.getpid(), "tid": e["tid"]})
        doc = {"displayTimeUnit": "ms", "traceEvents": events,
               "requestTraces": [tr.to_dict() for tr in self.traces()]}
        if flight_recorder is not None:
            recs = (flight_recorder.records()
                    if hasattr(flight_recorder, "records")
                    else list(flight_recorder))
            doc["flightRecorder"] = recs
        if path is not None:
            with open(path, "w") as f:
                json.dump(doc, f)
        return doc


class FlightRecorder:
    """Bounded ring of per-iteration engine step records.

    A record is one JSON-safe dict per NON-IDLE engine step (recording
    idle polling steps would let a quiet fleet loop evict the history
    that matters). `records()` returns oldest-first; the engine's
    snapshot embeds exactly this list so every postmortem carries the
    last `maxlen` steps of context.
    """

    __slots__ = ("_ring", "num_recorded")

    def __init__(self, max_steps: int = 128):
        self._ring: deque = deque(maxlen=int(max_steps))
        self.num_recorded = 0

    @property
    def maxlen(self) -> int:
        return self._ring.maxlen

    def record(self, rec: dict):
        self._ring.append(rec)
        self.num_recorded += 1

    def records(self) -> List[dict]:
        return [dict(r) for r in self._ring]

    def __len__(self):
        return len(self._ring)
