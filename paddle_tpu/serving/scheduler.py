"""Iteration-level continuous-batching scheduler.

Follows Orca's iteration-level scheduling (Yu et al., OSDI '22): every
engine step re-forms the batch from whatever is in flight, so a finishing
request's slot is reused immediately instead of waiting for the whole
batch to drain. Admission is FCFS under a per-step token budget; memory
pressure is resolved by cached-prefix LRU eviction first (radix tree,
when enabled), then preempt-by-eviction (vLLM-style recompute
preemption: the victim's pages are freed and it re-enters the waiting
queue with its generated tokens folded into the prompt).

Two serving optimizations ride the same admission path (ISSUE 2):

* **Radix prefix reuse** (SGLang RadixAttention): intake matches the
  longest block-aligned cached prefix of the (resume) prompt, shares
  those pages through the allocator's refcounts, and skips their
  prefill; finished/preempted sequences donate their full pages back.
* **Chunked prefill** (Sarathi-Serve): a prompt is processed in
  token-budget-sized CHUNKS interleaved with ongoing decode steps —
  a long prompt no longer monopolizes an engine step, and the old
  "oversized prompts admitted alone" special case is gone: any positive
  budget admits the head-of-line request with a budget-sized first
  chunk.

Per-request state machine:

    WAITING --admit--> PREFILL --last chunk + first token--> DECODE
       ^               (1..k chunk steps)                      |
       +---------------------- preempt ------------------------+
                                                  --eos/len--> FINISHED

`cancel()` exits any live state (queued, chunk-prefilling, decoding,
preempted-and-waiting) into FINISHED at an iteration boundary, with the
finish_reason recording why ("abort" / "expired" / "quarantined").
Aborted and expired requests DONATE their computed pages to the radix
cache (their KV is valid — the client just stopped wanting it);
quarantined requests never donate (their pages may hold NaN K/V).

The scheduler is pure host logic and deterministic: given the same
arrival sequence and the same allocator geometry it produces the same
step-by-step batch composition (golden-trace tested; the radix LRU uses
a monotonic counter, never wall-clock).
"""
from __future__ import annotations

import enum
import itertools
from collections import deque
from typing import List, Optional

from .kv_cache import BlockAllocator, BlocksExhausted

__all__ = ["RequestState", "Request", "PrefillChunk", "ScheduleStep",
           "Scheduler", "adapter_prefix_key"]


def adapter_prefix_key(ids, adapter):
    """Radix-cache key for a (possibly adapter'd) token sequence
    (ISSUE 15): a request served under a LoRA adapter namespaces every
    token with the adapter id, so identical token prefixes under
    different adapters (or adapter vs base) can NEVER share cached KV
    pages — their K/V differ by the adapter delta. Length-preserving,
    so all page-alignment math is untouched; the tree compares tokens
    by equality only, so tuple tokens slot straight in."""
    if adapter is None:
        return ids
    return [(adapter, t) for t in ids]


class RequestState(enum.Enum):
    WAITING = "waiting"
    PREFILL = "prefill"
    DECODE = "decode"
    FINISHED = "finished"


_req_counter = itertools.count()


def bump_request_counter(beyond: int):
    """Advance the global request-id counter past `beyond` — resuming a
    snapshot restores requests under their ORIGINAL ids, and new
    requests added afterwards must not collide with them."""
    global _req_counter
    nxt = next(_req_counter)
    if nxt <= beyond:
        _req_counter = itertools.count(beyond + 1)
    else:
        _req_counter = itertools.count(nxt)


class Request:
    """One generation request tracked through the state machine."""

    def __init__(self, prompt_ids, max_new_tokens: int,
                 eos_token_id: Optional[int] = None,
                 request_id: Optional[int] = None,
                 adapter: Optional[str] = None):
        self.request_id = (next(_req_counter) if request_id is None
                           else request_id)
        self.prompt_ids = [int(t) for t in prompt_ids]
        if not self.prompt_ids:
            raise ValueError("empty prompt")
        self.max_new_tokens = int(max_new_tokens)
        if self.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        self.eos_token_id = eos_token_id
        # LoRA adapter name (ISSUE 15; None = base model). Rides the
        # launch slot mapping and snapshots. `adapter_key` is the
        # radix-namespace token: the ENGINE overrides it with the
        # registry's (name, load-generation) so prefixes cached under
        # replaced weights of the same name can never match — the bare
        # name is only the registry-less default.
        self.adapter = adapter
        self.adapter_key = adapter
        self.state = RequestState.WAITING
        self.output_ids: List[int] = []
        self.seq = None                 # KVSequence while holding pages
        self.pending_copies = []        # CoW copies due before this step
        self.num_preemptions = 0
        self.finish_reason: Optional[str] = None
        self.arrival = self.request_id  # FCFS key (monotonic ids)
        # tokens whose K/V is valid in the paged cache (cached-prefix
        # match at admission + every chunk/decode write; maintained by
        # the scheduler at admission and the engine after each launch)
        self.num_computed = 0
        # cached-prefix tokens matched at the LAST admission
        self.cached_tokens = 0
        # --- resilience (ISSUE 3) ---
        # absolute engine-clock deadline (None = no TTL); the engine
        # cancels past-deadline requests at each iteration boundary
        self.deadline: Optional[float] = None
        # set by ServingEngine.abort(); honored at the next boundary
        self.aborted = False
        # --- disaggregated prefill/decode (ISSUE 18) ---
        # colocate=True pins the request to local decode even on a
        # prefill-role engine (the fleet's role-starved fallback);
        # handoff_prefix_len records the block-aligned token span
        # donated by finish_handoff — the span the fleet's kv_pull
        # ships to the decode-role adopter
        self.colocate = False
        self.handoff_prefix_len = 0

    # prompt the next prefill must process (original prompt + anything
    # generated before a preemption — recompute-style resume)
    @property
    def resume_ids(self) -> List[int]:
        return self.prompt_ids + self.output_ids

    @property
    def num_generated(self) -> int:
        return len(self.output_ids)

    def remaining_new_tokens(self) -> int:
        return self.max_new_tokens - self.num_generated

    def __repr__(self):
        return (f"Request({self.request_id}, {self.state.name}, "
                f"prompt={len(self.prompt_ids)}, out={len(self.output_ids)})")


class PrefillChunk:
    """One scheduled prefill chunk: process resume_ids[start:start+length]
    (is_last == the chunk reaches the prompt end, so the engine samples
    the first token from its final live position)."""

    __slots__ = ("request", "start", "length", "is_last", "is_first")

    def __init__(self, request, start, length, is_last, is_first):
        self.request = request
        self.start = start
        self.length = length
        self.is_last = is_last
        self.is_first = is_first

    @property
    def request_id(self):
        return self.request.request_id

    def __repr__(self):
        return (f"PrefillChunk(req={self.request_id}, "
                f"[{self.start}:{self.start + self.length}]"
                f"{' last' if self.is_last else ''})")


class ScheduleStep:
    """One engine step's worth of work: prefill chunks (each runs as its
    own bucketed program) + the decode batch."""

    __slots__ = ("prefills", "decodes", "preempted")

    def __init__(self, prefills, decodes, preempted):
        self.prefills = prefills
        self.decodes = decodes
        self.preempted = preempted

    def is_empty(self):
        return not (self.prefills or self.decodes)


class Scheduler:
    """FCFS continuous-batching scheduler over a BlockAllocator.

    token_budget caps the tokens processed per step (each decode request
    costs 1, a prefill chunk costs its length) — the knob that trades
    time-to-first-token against decode throughput when prefills and
    decodes interleave. max_batch_size caps concurrent in-flight
    (PREFILL/DECODE) requests, which bounds the decode batch bucket.
    prefix_cache (a RadixCache over the same allocator, or None) enables
    cached-prefix reuse + donation.
    """

    def __init__(self, allocator: BlockAllocator, max_batch_size: int = 8,
                 token_budget: int = 512,
                 max_prompt_len: Optional[int] = None,
                 prefix_cache=None,
                 max_queue_len: Optional[int] = None):
        self.allocator = allocator
        self.max_batch_size = int(max_batch_size)
        self.token_budget = int(token_budget)
        self.max_prompt_len = max_prompt_len
        self.prefix_cache = prefix_cache
        # admission control: bound on len(waiting). A preempted request
        # re-entering the queue is NOT subject to it (it was already
        # admitted once; shedding it would drop accepted work).
        self.max_queue_len = (None if max_queue_len is None
                              else int(max_queue_len))
        # per-step token cost of one decoding request. Plain decode = 1;
        # the spec-decode engine sets 1 + spec_k so the verify tokens
        # (draft positions scored per sequence per step) are charged
        # against the same budget prefill chunks draw from — otherwise
        # speculative steps would silently blow the TTFT-vs-throughput
        # contract the budget exists to enforce. The multi-step decode
        # engine (ISSUE 13) sets decode_steps for the same reason: one
        # schedule() decision now covers a K-token launch, so admission
        # and preemption at K-step boundaries must see the true
        # per-launch token traffic.
        self.decode_token_cost = 1
        self.waiting: deque = deque()
        self.prefilling: List[Request] = []   # admitted, chunks pending
        self.running: List[Request] = []      # decoding, arrival order
        self.num_preemptions = 0

    # ---- intake ----------------------------------------------------------
    def add_request(self, req: Request, force: bool = False):
        """Queue `req` (FCFS). `force=True` bypasses the admission bound
        — used for snapshot-restored requests, which were admitted once
        already; validation still applies."""
        if self.max_prompt_len is not None and \
                len(req.prompt_ids) > self.max_prompt_len:
            raise ValueError(
                f"prompt length {len(req.prompt_ids)} exceeds engine "
                f"max_prompt_len {self.max_prompt_len}")
        cap = (self.allocator.num_pages - 1) * self.allocator.page_size
        if len(req.prompt_ids) + req.max_new_tokens > cap:
            raise ValueError(
                f"request needs {len(req.prompt_ids) + req.max_new_tokens} "
                f"tokens of KV > total capacity {cap}")
        if not force and self.max_queue_len is not None and \
                len(self.waiting) >= self.max_queue_len:
            from .errors import EngineOverloaded
            raise EngineOverloaded(
                f"waiting queue full ({len(self.waiting)} >= "
                f"max_queue_len {self.max_queue_len})",
                queue_depth=len(self.waiting),
                max_queue_len=self.max_queue_len)
        req.state = RequestState.WAITING
        self.waiting.append(req)

    def has_work(self) -> bool:
        return bool(self.waiting or self.prefilling or self.running)

    @property
    def queue_depth(self) -> int:
        return len(self.waiting)

    @property
    def num_in_flight(self) -> int:
        return len(self.running) + len(self.prefilling)

    # ---- prefix cache plumbing ------------------------------------------
    def _donate(self, req: Request):
        """Hand the request's computed full pages to the radix tree
        (finish AND preemption both donate — an evicted victim's resume
        then re-matches its own prefix instead of recomputing it)."""
        if self.prefix_cache is None or req.seq is None:
            return
        # adapter-namespaced key (ISSUE 15): an adapter'd request's KV
        # holds the adapter delta — it must never serve another
        # adapter's (or the base model's, or a RELOADED same-name
        # adapter's) identical token prefix
        ids = adapter_prefix_key(req.prompt_ids + req.output_ids,
                                 req.adapter_key)
        n = min(req.num_computed, len(ids), req.seq.num_tokens)
        ps = self.allocator.page_size
        full = (n // ps) * ps
        if full:
            try:
                self.prefix_cache.insert(ids[:full],
                                         req.seq.pages[:full // ps])
            except Exception:
                # a failed donation (e.g. injected fault) only costs a
                # future cache hit; the donor still frees normally and
                # the tree was not mutated (insert raises before any
                # adoption), so reclamation stays exact
                pass

    def _reclaim(self, need_pages: int, protect=()) -> bool:
        """Cached-prefix LRU eviction — ALWAYS tried before preempting a
        live request (SERVING.md eviction ordering)."""
        if self.prefix_cache is None or need_pages <= 0:
            return False
        return self.prefix_cache.evict(need_pages, protect) >= need_pages

    # ---- preemption ------------------------------------------------------
    def _preempt_one(self, keep: Request) -> Optional[Request]:
        """Evict the LAST-arrived in-flight request (decoding OR
        mid-prefill) — possibly `keep` itself when IT is the newest
        (strict FCFS priority: a newer request never survives at an
        older one's expense). The victim donates its computed pages to
        the prefix cache (when enabled), frees the rest, and resumes by
        re-prefilling prompt+generated (recompute, not swap — there is
        no host swap space worth the round-trip on TPU; with the radix
        tree the donated pages usually turn the recompute into a cache
        hit)."""
        pool = self.running + self.prefilling
        victim = max(pool, key=lambda r: r.arrival)
        if victim in self.running:
            self.running.remove(victim)
        else:
            self.prefilling.remove(victim)
        self._donate(victim)
        self.allocator.free_sequence(victim.seq)
        victim.seq = None
        victim.state = RequestState.WAITING
        victim.num_computed = 0
        victim.cached_tokens = 0
        victim.num_preemptions += 1
        self.num_preemptions += 1
        # preempted requests head the queue: FCFS by original arrival
        self.waiting.appendleft(victim)
        return victim

    # ---- the per-step decision ------------------------------------------
    def schedule(self) -> ScheduleStep:
        preempted: List[Request] = []

        # 1. guarantee every decoding request can append this step's
        #    token (may cross a page boundary); on pressure evict cached
        #    prefixes first, then the newest in-flight request.
        survivors: List[Request] = []
        for req in list(self.running):
            if req not in self.running:
                continue               # evicted by an earlier iteration
            while True:
                try:
                    copies = self.allocator.append_token(req.seq)
                    req.pending_copies = copies
                    survivors.append(req)
                    break
                except BlocksExhausted:
                    if self._reclaim(1):
                        continue
                    victim = self._preempt_one(keep=req)
                    preempted.append(victim)
                    if victim is req:
                        break
        decodes = [r for r in survivors if r in self.running]
        budget = self.token_budget - len(decodes) * self.decode_token_cost

        # 2. continue in-flight prefills FCFS: each gets at most one
        #    chunk per step, sized to the remaining budget.
        chunks: List[PrefillChunk] = []
        # (snapshot taken after step 1: preemption cannot mutate it here)
        for req in sorted(self.prefilling, key=lambda r: r.arrival):
            if budget <= 0:
                break
            n = len(req.resume_ids)
            take = min(budget, n - req.num_computed)
            if take <= 0:
                continue
            chunks.append(PrefillChunk(req, req.num_computed, take,
                                       req.num_computed + take == n,
                                       is_first=False))
            budget -= take

        # 3. admit waiting prompts FCFS while budget/slots/pages allow.
        #    A cached-prefix match shares its pages and shrinks what
        #    must be prefilled; the first chunk takes whatever budget is
        #    left (chunked prefill — no oversized-prompt special case).
        #    Headroom check only: a prompt must see pages for prompt
        #    tokens + 1 free, which makes an immediate post-prefill
        #    preemption unlikely but does NOT reserve the extra page —
        #    same-step admissions crossing a boundary together can still
        #    contend, and preemption (step 1) resolves it.
        while self.waiting and budget > 0 and \
                self.num_in_flight < self.max_batch_size:
            req = self.waiting[0]
            ids = req.resume_ids
            n = len(ids)
            mpages, m = [], 0
            if self.prefix_cache is not None:
                # host-tier promotion (ISSUE 17) is scheduled against
                # the same chunked-prefill budget a recompute of those
                # tokens would draw — one token is held back so the
                # admitted request can always take a non-empty first
                # chunk in this step
                promoted_before = getattr(self.prefix_cache,
                                          "num_promoted_pages", 0)
                mpages, m = self.prefix_cache.match(
                    adapter_prefix_key(ids, req.adapter_key),
                    promote_budget=budget - 1)
                budget -= (getattr(self.prefix_cache,
                                   "num_promoted_pages", promoted_before)
                           - promoted_before) * self.allocator.page_size
                if m >= n:
                    # full hit: the LAST token must still run through
                    # the model to produce the next-token logits
                    keep = (n - 1) // self.allocator.page_size
                    mpages, m = mpages[:keep], \
                        keep * self.allocator.page_size
            short = (self.allocator.pages_needed(n + 1) - len(mpages)
                     - self.allocator.num_free)
            if short > 0 and not self._reclaim(short, protect=mpages):
                break                  # no pages — decodes will drain/free
            try:
                req.seq = self.allocator.alloc_sequence_with_prefix(
                    n, mpages)
            except BlocksExhausted:
                break
            self.waiting.popleft()
            req.state = RequestState.PREFILL
            req.num_computed = m
            req.cached_tokens = m
            self.prefilling.append(req)
            take = min(budget, n - m)
            chunks.append(PrefillChunk(req, m, take, m + take == n,
                                       is_first=True))
            budget -= take
        return ScheduleStep(chunks, decodes, preempted)

    # ---- completion hooks (engine calls these) ---------------------------
    def on_prefilled(self, req: Request):
        """Last chunk processed and first token sampled: request joins
        the decode batch (unless that token already finished it)."""
        if req in self.prefilling:
            self.prefilling.remove(req)
        req.state = RequestState.DECODE
        self.running.append(req)
        self.running.sort(key=lambda r: r.arrival)

    def finish_handoff(self, req: Request) -> int:
        """Finish a just-prefilled request for cross-worker handoff
        (ISSUE 18): its computed pages donate to the radix tree exactly
        like any finish, and the return value is the block-aligned
        token count of the donated span — the single source for how
        many tokens of `prompt+output` the fleet's kv_pull can ship.
        0 when nothing donates (no prefix cache, or a sub-page
        prompt): the decode side then simply re-prefills."""
        ids = req.prompt_ids + req.output_ids
        n = min(req.num_computed, len(ids),
                req.seq.num_tokens if req.seq is not None else 0)
        full = (n // self.allocator.page_size) * self.allocator.page_size
        if self.prefix_cache is None:
            full = 0
        self.finish(req, "handoff", donate=True)
        return full

    def finish(self, req: Request, reason: str, donate: bool = True):
        if req in self.running:
            self.running.remove(req)
        if req in self.prefilling:
            self.prefilling.remove(req)
        if req.seq is not None:
            if donate:
                self._donate(req)
            self.allocator.free_sequence(req.seq)
            req.seq = None
        req.state = RequestState.FINISHED
        req.finish_reason = reason

    def cancel(self, req: Request, reason: str,
               donate: bool = True) -> bool:
        """Cancel a request in ANY live state — queued, mid-prefill,
        decoding, or preempted-back-to-waiting. Pages are donated to the
        prefix cache (valid KV; `donate=False` for quarantine — poisoned
        KV must never enter the tree) and freed. Returns False when the
        request already finished."""
        if req.state is RequestState.FINISHED:
            return False
        try:
            self.waiting.remove(req)
        except ValueError:
            pass
        self.finish(req, reason, donate=donate)
        return True
