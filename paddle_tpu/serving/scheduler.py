"""Iteration-level continuous-batching scheduler.

Follows Orca's iteration-level scheduling (Yu et al., OSDI '22): every
engine step re-forms the batch from whatever is in flight, so a finishing
request's slot is reused immediately instead of waiting for the whole
batch to drain. Admission is FCFS under a per-step token budget; memory
pressure is resolved by preempt-by-eviction (vLLM-style recompute
preemption: the victim's pages are freed and it re-enters the waiting
queue with its generated tokens folded into the prompt).

Per-request state machine:

    WAITING --admit--> PREFILL --first token--> DECODE --eos/len--> FINISHED
       ^                                          |
       +------------------ preempt ---------------+

The scheduler is pure host logic and deterministic: given the same
arrival sequence and the same allocator geometry it produces the same
step-by-step batch composition (golden-trace tested).
"""
from __future__ import annotations

import enum
import itertools
from collections import deque
from typing import List, Optional

from .kv_cache import BlockAllocator, BlocksExhausted

__all__ = ["RequestState", "Request", "ScheduleStep", "Scheduler"]


class RequestState(enum.Enum):
    WAITING = "waiting"
    PREFILL = "prefill"
    DECODE = "decode"
    FINISHED = "finished"


_req_counter = itertools.count()


class Request:
    """One generation request tracked through the state machine."""

    def __init__(self, prompt_ids, max_new_tokens: int,
                 eos_token_id: Optional[int] = None,
                 request_id: Optional[int] = None):
        self.request_id = (next(_req_counter) if request_id is None
                           else request_id)
        self.prompt_ids = [int(t) for t in prompt_ids]
        if not self.prompt_ids:
            raise ValueError("empty prompt")
        self.max_new_tokens = int(max_new_tokens)
        if self.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        self.eos_token_id = eos_token_id
        self.state = RequestState.WAITING
        self.output_ids: List[int] = []
        self.seq = None                 # KVSequence while holding pages
        self.pending_copies = []        # CoW copies due before this step
        self.num_preemptions = 0
        self.finish_reason: Optional[str] = None
        self.arrival = self.request_id  # FCFS key (monotonic ids)

    # prompt the next prefill must process (original prompt + anything
    # generated before a preemption — recompute-style resume)
    @property
    def resume_ids(self) -> List[int]:
        return self.prompt_ids + self.output_ids

    @property
    def num_generated(self) -> int:
        return len(self.output_ids)

    def remaining_new_tokens(self) -> int:
        return self.max_new_tokens - self.num_generated

    def __repr__(self):
        return (f"Request({self.request_id}, {self.state.name}, "
                f"prompt={len(self.prompt_ids)}, out={len(self.output_ids)})")


class ScheduleStep:
    """One engine step's worth of work: prompts to prefill (each runs as
    its own bucketed program) + the decode batch."""

    __slots__ = ("prefills", "decodes", "preempted")

    def __init__(self, prefills, decodes, preempted):
        self.prefills = prefills
        self.decodes = decodes
        self.preempted = preempted

    def is_empty(self):
        return not (self.prefills or self.decodes)


class Scheduler:
    """FCFS continuous-batching scheduler over a BlockAllocator.

    token_budget caps the tokens processed per step (each decode request
    costs 1, a prefill costs its prompt length) — the knob that trades
    time-to-first-token against decode throughput when prefills and
    decodes interleave. max_batch_size caps concurrent in-flight
    (PREFILL/DECODE) requests, which bounds the decode batch bucket.
    """

    def __init__(self, allocator: BlockAllocator, max_batch_size: int = 8,
                 token_budget: int = 512,
                 max_prompt_len: Optional[int] = None):
        self.allocator = allocator
        self.max_batch_size = int(max_batch_size)
        self.token_budget = int(token_budget)
        self.max_prompt_len = max_prompt_len
        self.waiting: deque = deque()
        self.running: List[Request] = []   # arrival order
        self.num_preemptions = 0

    # ---- intake ----------------------------------------------------------
    def add_request(self, req: Request):
        if self.max_prompt_len is not None and \
                len(req.prompt_ids) > self.max_prompt_len:
            raise ValueError(
                f"prompt length {len(req.prompt_ids)} exceeds engine "
                f"max_prompt_len {self.max_prompt_len}")
        cap = (self.allocator.num_pages - 1) * self.allocator.page_size
        if len(req.prompt_ids) + req.max_new_tokens > cap:
            raise ValueError(
                f"request needs {len(req.prompt_ids) + req.max_new_tokens} "
                f"tokens of KV > total capacity {cap}")
        req.state = RequestState.WAITING
        self.waiting.append(req)

    def has_work(self) -> bool:
        return bool(self.waiting or self.running)

    @property
    def queue_depth(self) -> int:
        return len(self.waiting)

    # ---- preemption ------------------------------------------------------
    def _preempt_one(self, keep: Request) -> Optional[Request]:
        """Evict the LAST-arrived running request — possibly `keep`
        itself when IT is the newest (strict FCFS priority: a newer
        request never survives at an older one's expense). The victim's
        pages free immediately; it resumes by re-prefilling
        prompt+generated (recompute, not swap — there is no host swap
        space worth the round-trip on TPU)."""
        victim = self.running[-1]
        self.running.remove(victim)
        self.allocator.free_sequence(victim.seq)
        victim.seq = None
        victim.state = RequestState.WAITING
        victim.num_preemptions += 1
        self.num_preemptions += 1
        # preempted requests head the queue: FCFS by original arrival
        self.waiting.appendleft(victim)
        return victim

    # ---- the per-step decision ------------------------------------------
    def schedule(self) -> ScheduleStep:
        preempted: List[Request] = []

        # 1. guarantee every running request can append this step's token
        #    (may cross a page boundary); evict newest-first on pressure.
        survivors: List[Request] = []
        for req in list(self.running):
            if req not in self.running:
                continue               # evicted by an earlier iteration
            while True:
                try:
                    copies = self.allocator.append_token(req.seq)
                    req.pending_copies = copies
                    survivors.append(req)
                    break
                except BlocksExhausted:
                    victim = self._preempt_one(keep=req)
                    preempted.append(victim)
                    if victim is req:
                        break
        decodes = [r for r in survivors if r in self.running]
        budget = self.token_budget - len(decodes)

        # 2. admit waiting prompts FCFS while budget/slots/pages allow.
        #    Headroom check only: a prompt must see pages for prompt
        #    tokens + 1 free, which makes an immediate post-prefill
        #    preemption unlikely but does NOT reserve the extra page —
        #    same-step admissions crossing a boundary together can still
        #    contend, and preemption (step 1) resolves it.
        prefills: List[Request] = []
        while self.waiting and budget > 0 and \
                len(self.running) + len(prefills) < self.max_batch_size:
            req = self.waiting[0]
            n = len(req.resume_ids)
            if n > budget and (prefills or budget < self.token_budget):
                break                  # FCFS head-of-line: wait for budget
            # else: n exceeds even the FULL budget — admit it alone once
            # the step is otherwise empty, or it would livelock at the
            # head of the queue forever (the budget is a latency knob,
            # not an admissibility bound)
            if not self.allocator.can_allocate(n + 1):
                break                  # no pages — decodes will drain/free
            self.waiting.popleft()
            req.seq = self.allocator.alloc_sequence(n)
            req.state = RequestState.PREFILL
            prefills.append(req)
            budget -= n
        return ScheduleStep(prefills, decodes, preempted)

    # ---- completion hooks (engine calls these) ---------------------------
    def on_prefilled(self, req: Request):
        """Prompt processed and first token sampled: request joins the
        decode batch (unless that token already finished it)."""
        req.state = RequestState.DECODE
        self.running.append(req)
        self.running.sort(key=lambda r: r.arrival)

    def finish(self, req: Request, reason: str):
        if req in self.running:
            self.running.remove(req)
        if req.seq is not None:
            self.allocator.free_sequence(req.seq)
            req.seq = None
        req.state = RequestState.FINISHED
        req.finish_reason = reason
