"""Persistent AOT program cache for serving engines (ISSUE 14).

A restarted worker process pays the full bucket-grid compile storm
before its first token unless something remembers the executables. The
ProgramCache keys are already canonical — ("decode", B, P, kv_dtype,
wq, ("tp", tp)) names one program completely for one engine geometry —
so this module serializes each LAUNCHED program's compiled XLA
executable to disk under that key and hands it back to the next
process holding the same geometry:

* **save**: for every launched program (its first call recorded the
  argument avals), re-lower AOT (`fn.lower(*avals).compile()`) and
  write `pickle(jax.experimental.serialize_executable.serialize(...))`
  to one file per key;
* **load**: on a ProgramCache miss, look the key up on disk; a hit
  skips BOTH jax tracing and XLA compilation (deserialize + call);
* **reject, never crash**: a corrupt file (bad magic/version/checksum/
  truncation), a fingerprint mismatch (different jax/jaxlib/backend/
  device topology/model geometry), or an executable that fails its
  first call degrades to a counted recompile — a worker must reach
  first-token on a damaged cache directory, just slower.

Entry format (one file per key, name = sha1(key repr)):

    line 1: header JSON {magic, format, fingerprint, key, body_sha256,
            body_len, saved_unix}
    rest:   the pickled (payload, in_tree, out_tree) triple

The fingerprint folds in jax/jaxlib versions, backend, device kind and
count, plus whatever the owner passes as `extra` — the engine passes
its model geometry/state signature, so an engine with different
weights' SHAPES can never adopt a stale executable (same-shape weight
VALUES are call-time arguments, not baked into the executable).

Counters {hits, misses, rejects, saved} surface through the engine's
ServingMetrics as `compile_cache_*` (auto-exposed by the drift-tested
Prometheus registry). Fault point `cache.corrupt_entry` flips bytes of
an entry body at read time — the checksum-reject path, proven in the
soak.

Importable without jax: jax and the serializer load lazily inside
save/load.
"""
from __future__ import annotations

import hashlib
import json
import os
import pickle
import tempfile
import time
from typing import Dict, Optional

from ..utils import faults

__all__ = ["CompileCache", "cache_fingerprint", "FORMAT_VERSION",
           "FAULT_CORRUPT"]

MAGIC = "PTCC"
FORMAT_VERSION = 1

# Fires in _read_entry with the raw body in hand: a payload means "the
# disk lied" — bytes are flipped BEFORE checksum verification, so the
# reject path (not a crash) is what the firing proves.
FAULT_CORRUPT = faults.register_point("cache.corrupt_entry")


def cache_fingerprint(extra: Optional[str] = None) -> str:
    """Environment fingerprint an executable is only valid under:
    jax/jaxlib versions, backend, device kind x count — plus the
    owner's `extra` (model geometry). Serialized executables embed
    backend-specific code; running one under any other combination is
    undefined, so a mismatch REJECTS to recompile."""
    import jax
    import jaxlib
    devs = jax.devices()
    parts = [f"jax={jax.__version__}", f"jaxlib={jaxlib.__version__}",
             f"backend={jax.default_backend()}",
             f"devices={len(devs)}x{devs[0].device_kind if devs else '?'}"]
    if extra:
        parts.append(f"extra={extra}")
    return hashlib.sha256("|".join(parts).encode()).hexdigest()[:32]


class CompileCache:
    """One on-disk executable store for one engine geometry.

    `path` is the cache directory (created on demand). `extra` joins
    the environment fingerprint — pass the model/engine geometry
    signature so two engines with different models never share a
    directory's entries even if their ProgramCache keys collide.
    """

    def __init__(self, path: str, *, extra: Optional[str] = None):
        self.path = str(path)
        self._extra = extra
        self._fingerprint: Optional[str] = None
        self.counters: Dict[str, int] = {
            "hits": 0, "misses": 0, "rejects": 0, "saved": 0}
        # keys whose entry was rejected this process (corrupt body,
        # stale payload, first-call failure): save_all REWRITES these
        # even when the on-disk header still looks valid — otherwise a
        # damaged-body entry would defeat the warm-restart contract
        # for its key on every future restart
        self.rejected_keys: set = set()

    def fingerprint(self) -> str:
        if self._fingerprint is None:
            self._fingerprint = cache_fingerprint(self._extra)
        return self._fingerprint

    # ---- paths -----------------------------------------------------------
    def entry_path(self, key: tuple) -> str:
        name = hashlib.sha1(repr(key).encode()).hexdigest()
        return os.path.join(self.path, f"{name}.ptcc")

    def keys_on_disk(self):
        """Key reprs of every readable entry (diagnostics/tests)."""
        out = []
        if not os.path.isdir(self.path):
            return out
        for fn in sorted(os.listdir(self.path)):
            if not fn.endswith(".ptcc"):
                continue
            try:
                with open(os.path.join(self.path, fn), "rb") as f:
                    out.append(json.loads(f.readline())["key"])
            except Exception:                             # noqa: BLE001
                continue
        return out

    # ---- write -----------------------------------------------------------
    def save_entry(self, key: tuple, compiled) -> bool:
        """Serialize one AOT-compiled program under `key` (atomic
        rename; concurrent writers of the same key are last-wins with
        either side's complete file). Returns False when this jax
        build cannot serialize executables."""
        try:
            from jax.experimental.serialize_executable import serialize
            body = pickle.dumps(serialize(compiled))
        except Exception:                                 # noqa: BLE001
            return False
        header = {"magic": MAGIC, "format": FORMAT_VERSION,
                  "fingerprint": self.fingerprint(), "key": repr(key),
                  "body_sha256": hashlib.sha256(body).hexdigest(),
                  "body_len": len(body), "saved_unix": int(time.time())}
        os.makedirs(self.path, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=self.path, suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(json.dumps(header).encode() + b"\n")
                f.write(body)
            os.replace(tmp, self.entry_path(key))
        except Exception:                                 # noqa: BLE001
            try:
                os.remove(tmp)
            except OSError:
                pass
            return False
        self.counters["saved"] += 1
        self.rejected_keys.discard(key)
        return True

    def save_all(self, program_cache) -> int:
        """Persist every launched program the ProgramCache holds that
        is (a) AOT-lowerable (jit-built, launched at least once so its
        arg avals were recorded) and (b) not already on disk under the
        current fingerprint. Returns entries written. Re-lowering is a
        second compile per NEW entry — drain/shutdown-time cost, never
        on the serving path."""
        written = 0
        for key in program_cache.keys():
            prog = program_cache._programs[key]
            fn = getattr(prog, "fn", prog)
            avals = getattr(prog, "arg_avals", None)
            if avals is None or not hasattr(fn, "lower"):
                continue   # never launched, or loaded-from-disk already
            if key not in self.rejected_keys and \
                    self._header_ok(self.entry_path(key)):
                continue
            try:
                compiled = fn.lower(*avals).compile()
            except Exception:                             # noqa: BLE001
                continue
            if self.save_entry(key, compiled):
                written += 1
        return written

    # ---- read ------------------------------------------------------------
    def _header_ok(self, path: str) -> bool:
        """Cheap staleness probe: does a valid-looking entry under the
        CURRENT fingerprint exist at `path`? (save_all's skip test —
        full validation happens at load.)"""
        try:
            with open(path, "rb") as f:
                h = json.loads(f.readline())
            return (h.get("magic") == MAGIC
                    and h.get("format") == FORMAT_VERSION
                    and h.get("fingerprint") == self.fingerprint())
        except Exception:                                 # noqa: BLE001
            return False

    def _read_entry(self, key: tuple):
        """Validate and unpickle one entry; raises ValueError naming
        the defect on any mismatch (the caller counts a reject)."""
        path = self.entry_path(key)
        with open(path, "rb") as f:
            header_line = f.readline()
            body = f.read()
        if faults.fire(FAULT_CORRUPT) is not None and body:
            body = bytes([body[0] ^ 0xFF]) + body[1:]
        try:
            h = json.loads(header_line)
        except Exception as e:                            # noqa: BLE001
            raise ValueError(f"unreadable header: {e}") from e
        if h.get("magic") != MAGIC:
            raise ValueError(f"bad magic {h.get('magic')!r}")
        if h.get("format") != FORMAT_VERSION:
            raise ValueError(f"format {h.get('format')} != "
                             f"{FORMAT_VERSION}")
        if h.get("fingerprint") != self.fingerprint():
            raise ValueError("environment/model fingerprint mismatch")
        if h.get("key") != repr(key):
            raise ValueError("key collision: entry names a different "
                             "program")
        if len(body) != h.get("body_len"):
            raise ValueError(f"truncated body: {len(body)} != "
                             f"{h.get('body_len')}")
        if hashlib.sha256(body).hexdigest() != h.get("body_sha256"):
            raise ValueError("body checksum mismatch")
        return pickle.loads(body)

    def load(self, key: tuple):
        """The deserialized executable for `key`, or None (counted as
        hit / miss / reject; every damage class degrades to None — the
        caller recompiles)."""
        path = self.entry_path(key)
        if not os.path.exists(path):
            self.counters["misses"] += 1
            return None
        try:
            payload, in_tree, out_tree = self._read_entry(key)
            from jax.experimental.serialize_executable import \
                deserialize_and_load
            loaded = deserialize_and_load(payload, in_tree, out_tree)
        except Exception:                                 # noqa: BLE001
            self.counters["rejects"] += 1
            self.rejected_keys.add(key)
            return None
        self.counters["hits"] += 1
        return loaded

    def __repr__(self):
        return (f"CompileCache({self.path!r}, "
                f"hits={self.counters['hits']}, "
                f"misses={self.counters['misses']}, "
                f"rejects={self.counters['rejects']})")
