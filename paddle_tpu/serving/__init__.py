"""paddle_tpu.serving — continuous-batching inference engine.

Architecture (SERVING.md): Orca-style iteration-level scheduling +
vLLM-style paged KV management + SGLang-style radix prefix caching +
Sarathi-style chunked prefill, compiled into a bounded grid of bucketed
XLA programs over the chip-validated paged-attention kernels; a
resilience layer (ISSUE 3) adds request deadlines/abort, bounded-queue
admission control, supervised step retries with poison quarantine, and
snapshot/resume across device failures; speculative decoding (ISSUE 5,
`serving.spec`) drafts K candidate tokens per sequence (n-gram prompt
lookup or a smaller draft model) and verifies them against the paged
cache in one bucketed launch with KV rollback for rejected drafts; the
fleet front-end (ISSUE 7, `serving.fleet`) multiplexes a streaming API
over N in-process replicas with prefix-affinity routing, replica
supervision, and zero-loss failover via snapshot live-migration; the
cross-process tier (ISSUE 14) moves replicas into worker processes
over a framed TCPStore mailbox (`ProcessFleet`/`worker.py`/
`transport.py`) with crash-proof restart through heartbeat-shipped
snapshots and a persistent AOT compile cache
(`serving.compile_cache`), fronted by HTTP/SSE (`HttpFrontend`);
multi-LoRA serving (ISSUE 15, `serving.lora`) serves N adapters per
engine — paged adapter-weight storage under the BlockAllocator
discipline, a batched heterogeneous segment-bmm delta kernel, and the
adapter id threaded through radix keys, snapshots and fleet routing.
"""
from .engine import ServingEngine, tp_serving_mesh
from .program_cache import ProgramCache
from .compile_cache import CompileCache
from .errors import (EngineFailure, EngineOverloaded, PoisonedComputation,
                     SnapshotVersionError, TransientDeviceError)
from .kv_cache import BlockAllocator, BlocksExhausted, KVSequence, PAD_PAGE
from .metrics import ServingMetrics
from .radix_cache import RadixCache, RadixNode
from .scheduler import (PrefillChunk, Request, RequestState, ScheduleStep,
                        Scheduler)
from .lora import (AdapterBusy, AdapterError, AdapterLoadError,
                   AdapterNotLoaded, AdapterRegistry, LoRAAdapter)
from .spec import DraftModelProposer, NgramProposer, Proposer
from .supervisor import RetryPolicy, StepSupervisor, classify_failure
from .trace import FlightRecorder, RequestTrace, RequestTracer
from .exposition import render_prometheus
from .fleet import (Channel, Fleet, FleetHandle, FleetServer, HttpFrontend,
                    PrefixAffinityRouter, ProcessFleet, RandomRouter,
                    Replica, ReplicaState, RoundRobinRouter, TokenStream,
                    TransportError, WorkerProc, WorkerState)

__all__ = ["ServingEngine", "BlockAllocator", "BlocksExhausted",
           "KVSequence", "PAD_PAGE", "ServingMetrics", "RadixCache",
           "RadixNode", "PrefillChunk", "Request", "RequestState",
           "ScheduleStep", "Scheduler", "EngineFailure", "EngineOverloaded",
           "PoisonedComputation", "TransientDeviceError",
           "SnapshotVersionError", "RetryPolicy",
           "StepSupervisor", "classify_failure", "Proposer",
           "NgramProposer", "DraftModelProposer", "Fleet", "FleetHandle",
           "FleetServer", "TokenStream", "Replica", "ReplicaState",
           "PrefixAffinityRouter", "RandomRouter", "RoundRobinRouter",
           "tp_serving_mesh", "ProgramCache", "RequestTracer",
           "RequestTrace", "FlightRecorder", "render_prometheus",
           "CompileCache", "Channel", "TransportError", "HttpFrontend",
           "ProcessFleet", "WorkerProc", "WorkerState",
           "AdapterRegistry", "LoRAAdapter", "AdapterError",
           "AdapterNotLoaded", "AdapterLoadError", "AdapterBusy"]
