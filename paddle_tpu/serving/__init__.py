"""paddle_tpu.serving — continuous-batching inference engine.

Architecture (SERVING.md): Orca-style iteration-level scheduling +
vLLM-style paged KV management + SGLang-style radix prefix caching +
Sarathi-style chunked prefill, compiled into a bounded grid of bucketed
XLA programs over the chip-validated paged-attention kernels; a
resilience layer (ISSUE 3) adds request deadlines/abort, bounded-queue
admission control, supervised step retries with poison quarantine, and
snapshot/resume across device failures; speculative decoding (ISSUE 5,
`serving.spec`) drafts K candidate tokens per sequence (n-gram prompt
lookup or a smaller draft model) and verifies them against the paged
cache in one bucketed launch with KV rollback for rejected drafts; the
fleet front-end (ISSUE 7, `serving.fleet`) multiplexes a streaming API
over N in-process replicas with prefix-affinity routing, replica
supervision, and zero-loss failover via snapshot live-migration.
"""
from .engine import ServingEngine, tp_serving_mesh
from .program_cache import ProgramCache
from .errors import (EngineFailure, EngineOverloaded, PoisonedComputation,
                     SnapshotVersionError, TransientDeviceError)
from .kv_cache import BlockAllocator, BlocksExhausted, KVSequence, PAD_PAGE
from .metrics import ServingMetrics
from .radix_cache import RadixCache, RadixNode
from .scheduler import (PrefillChunk, Request, RequestState, ScheduleStep,
                        Scheduler)
from .spec import DraftModelProposer, NgramProposer, Proposer
from .supervisor import RetryPolicy, StepSupervisor, classify_failure
from .trace import FlightRecorder, RequestTrace, RequestTracer
from .exposition import render_prometheus
from .fleet import (Fleet, FleetHandle, FleetServer, PrefixAffinityRouter,
                    RandomRouter, Replica, ReplicaState, RoundRobinRouter,
                    TokenStream)

__all__ = ["ServingEngine", "BlockAllocator", "BlocksExhausted",
           "KVSequence", "PAD_PAGE", "ServingMetrics", "RadixCache",
           "RadixNode", "PrefillChunk", "Request", "RequestState",
           "ScheduleStep", "Scheduler", "EngineFailure", "EngineOverloaded",
           "PoisonedComputation", "TransientDeviceError",
           "SnapshotVersionError", "RetryPolicy",
           "StepSupervisor", "classify_failure", "Proposer",
           "NgramProposer", "DraftModelProposer", "Fleet", "FleetHandle",
           "FleetServer", "TokenStream", "Replica", "ReplicaState",
           "PrefixAffinityRouter", "RandomRouter", "RoundRobinRouter",
           "tp_serving_mesh", "ProgramCache", "RequestTracer",
           "RequestTrace", "FlightRecorder", "render_prometheus"]
