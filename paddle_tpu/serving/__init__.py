"""paddle_tpu.serving — continuous-batching inference engine.

Architecture (SERVING.md): Orca-style iteration-level scheduling +
vLLM-style paged KV management, compiled into a bounded grid of
bucketed XLA programs over the chip-validated paged-attention kernels.
"""
from .engine import ServingEngine
from .kv_cache import BlockAllocator, BlocksExhausted, KVSequence, PAD_PAGE
from .metrics import ServingMetrics
from .scheduler import Request, RequestState, ScheduleStep, Scheduler

__all__ = ["ServingEngine", "BlockAllocator", "BlocksExhausted",
           "KVSequence", "PAD_PAGE", "ServingMetrics", "Request",
           "RequestState", "ScheduleStep", "Scheduler"]
