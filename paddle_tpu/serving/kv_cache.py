"""Paged KV-cache block management for the serving engine.

Host-side bookkeeping only: pages are integer ids into the device-side
(num_pages, KVH, page_size, D) cache arrays owned by the engine; this
module decides WHICH page holds WHICH tokens. Design follows the
block-based KV management of vLLM/PagedAttention (Kwon et al., SOSP '23):
fixed-size pages, a free list, per-page reference counts so a forked
prefix shares pages copy-on-write.

Kernel contract (kernels/paged_attention.py): page 0 is the reserved pad
page — block-table slots past a sequence's live pages must hold a valid
page id, and 0 is the designated one (reads of it are masked by
seq_lens). The allocator therefore never hands out page 0.
"""
from __future__ import annotations

import struct
import zlib
from collections import deque
from typing import Dict, List, Tuple

import numpy as np

from ..utils import faults

__all__ = ["BlockAllocator", "KVSequence", "BlocksExhausted", "PAD_PAGE",
           "HostPageStore", "HostPagesExhausted", "HostPageError",
           "HostPageCorrupt", "HostPageSlow", "HostPageLost",
           "encode_page_payload", "decode_page_payload"]

PAD_PAGE = 0

# Fault-injection point (ISSUE 3): an armed spec makes _alloc_page raise
# BlocksExhausted as if the pool were dry — the scheduler must degrade
# through its reclamation ladder (radix LRU eviction, then
# preempt-by-eviction), never crash or leak.
FAULT_ALLOC = faults.register_point("serving.kv.alloc_page")

# Fault-injection points (ISSUE 17): the host spill tier's read path.
# Each degrades a promotion into recompute-from-radix-prefix — the
# engine's outputs must stay bit-identical in all three cases, only the
# cached-token accounting changes.
FAULT_HOST_CORRUPT = faults.register_point("host_spill.corrupt")
FAULT_HOST_SLOW = faults.register_point("host_spill.slow")
FAULT_HOST_LOST = faults.register_point("host_spill.lost")


class BlocksExhausted(Exception):
    """No free page — the scheduler turns this into a preemption."""


class KVSequence:
    """One sequence's view of the cache: ordered page ids + token count.
    Page j covers token positions [j*page_size, (j+1)*page_size)."""

    __slots__ = ("pages", "num_tokens", "freed")

    def __init__(self):
        self.pages: List[int] = []
        self.num_tokens = 0
        self.freed = False

    def num_pages(self):
        return len(self.pages)


class BlockAllocator:
    """Ref-counted page allocator over `num_pages` fixed-size pages.

    Invariant (checked by the property tests): every page is either in
    the free list with refcount 0 or held by >= 1 sequences with a
    positive refcount — never both, never negative.
    """

    def __init__(self, num_pages: int, page_size: int):
        if num_pages < 2:
            raise ValueError("need >= 2 pages (page 0 is the pad page)")
        if page_size <= 0 or page_size % 8 != 0:
            # the Pallas kernel needs sublane-tiled pages
            raise ValueError(f"page_size {page_size} must be a positive "
                             "multiple of 8")
        self.num_pages = num_pages
        self.page_size = page_size
        # FIFO free list: steady-state serving cycles through HBM pages
        # instead of hammering the most recently freed ones
        self._free = deque(range(1, num_pages))
        self._refs: Dict[int, int] = {}

    # ---- low-level page ops ---------------------------------------------
    def _alloc_page(self) -> int:
        if faults.fire(FAULT_ALLOC) is not None:
            raise BlocksExhausted("injected allocator OOM")
        if not self._free:
            raise BlocksExhausted(
                f"all {self.num_pages - 1} KV pages in use")
        pid = self._free.popleft()
        self._refs[pid] = 1
        return pid

    def _incref(self, pid: int):
        self._refs[pid] += 1

    def _decref(self, pid: int):
        r = self._refs.get(pid)
        if r is None or r <= 0:
            raise RuntimeError(f"double free of page {pid}")
        if r == 1:
            del self._refs[pid]
            self._free.append(pid)
        else:
            self._refs[pid] = r - 1

    # ---- occupancy -------------------------------------------------------
    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_used(self) -> int:
        return (self.num_pages - 1) - len(self._free)

    def occupancy(self) -> float:
        return self.num_used / float(self.num_pages - 1)

    def pages_needed(self, num_tokens: int) -> int:
        return -(-num_tokens // self.page_size)

    def can_allocate(self, num_tokens: int) -> bool:
        return self.pages_needed(num_tokens) <= self.num_free

    # ---- sequence API ----------------------------------------------------
    def _alloc_pages(self, n: int) -> List[int]:
        """n fresh pages, all-or-nothing: a mid-loop BlocksExhausted
        (possible via the injected-OOM fault even after a num_free
        pre-check) rolls the partial allocation back before re-raising,
        so no page ever leaks with a refcount and no owner."""
        got: List[int] = []
        try:
            for _ in range(n):
                got.append(self._alloc_page())
        except BlocksExhausted:
            for pid in got:
                self._decref(pid)
            raise
        return got

    def alloc_sequence(self, num_tokens: int) -> KVSequence:
        """Pages for `num_tokens` tokens (a prompt about to prefill).
        All-or-nothing: on exhaustion nothing is held."""
        need = self.pages_needed(num_tokens)
        if need > self.num_free:
            raise BlocksExhausted(
                f"need {need} pages, {self.num_free} free")
        seq = KVSequence()
        seq.pages = self._alloc_pages(need)
        seq.num_tokens = num_tokens
        return seq

    def alloc_sequence_with_prefix(self, num_tokens,
                                   prefix_pages) -> KVSequence:
        """Pages for `num_tokens` tokens whose first
        len(prefix_pages) * page_size tokens are already cached: the
        prefix pages are SHARED (refcounts bumped — the radix tree or a
        donor sequence keeps its own refs) and only the remainder is
        freshly allocated. All-or-nothing like alloc_sequence."""
        need = self.pages_needed(num_tokens)
        if len(prefix_pages) > need:
            raise ValueError(
                f"prefix of {len(prefix_pages)} pages exceeds the "
                f"{need} pages {num_tokens} tokens need")
        fresh = need - len(prefix_pages)
        if fresh > self.num_free:
            raise BlocksExhausted(
                f"need {fresh} fresh pages, {self.num_free} free")
        seq = KVSequence()
        for pid in prefix_pages:
            self._incref(pid)
        try:
            fresh_pages = self._alloc_pages(fresh)
        except BlocksExhausted:
            for pid in prefix_pages:   # all-or-nothing: drop shared refs
                self._decref(pid)
            raise
        seq.pages = list(prefix_pages) + fresh_pages
        seq.num_tokens = num_tokens
        return seq

    def append_token(self, seq: KVSequence) -> List[Tuple[int, int]]:
        """Grow `seq` by one token, returning the (src_page, dst_page)
        device copies the caller must perform (copy-on-write when the
        written page is shared with a fork; empty list otherwise)."""
        if seq.freed:
            raise RuntimeError("append to a freed sequence")
        copies: List[Tuple[int, int]] = []
        pos = seq.num_tokens
        j = pos // self.page_size
        if j == len(seq.pages):            # crossing into a new page
            seq.pages.append(self._alloc_page())
        else:
            pid = seq.pages[j]
            if self._refs[pid] > 1:        # shared with a fork: CoW
                new = self._alloc_page()
                self._decref(pid)
                seq.pages[j] = new
                copies.append((pid, new))
        seq.num_tokens = pos + 1
        return copies

    def truncate_sequence(self, seq: KVSequence, num_tokens: int):
        """Shrink `seq` to its first `num_tokens` tokens, releasing the
        pages that covered only the dropped tail — the speculative-
        decoding KV ROLLBACK: rejected draft tokens' pages return to
        the free list; the page holding the last surviving token stays
        (its dead tail slots are masked by seq_lens, the same contract
        as any partially-filled page).

        Invariants preserved by construction: releases go through
        `_decref`, so a dropped page shared with a fork or held by the
        radix tree (donated while this sequence still lived) merely
        loses this sequence's ref — CoW bookkeeping and tree refs stay
        exact, and `check_invariants` holds after any truncation.
        `num_tokens=0` is legal (all pages released, sequence still
        usable/growable — unlike `free_sequence` it is NOT terminal).
        """
        if seq.freed:
            raise RuntimeError("truncate of a freed sequence")
        num_tokens = int(num_tokens)
        if not 0 <= num_tokens <= seq.num_tokens:
            raise ValueError(
                f"truncate to {num_tokens} outside [0, {seq.num_tokens}]")
        keep = self.pages_needed(num_tokens)
        dropped = seq.pages[keep:]
        del seq.pages[keep:]
        for pid in dropped:
            self._decref(pid)
        seq.num_tokens = num_tokens

    def fork_sequence(self, seq: KVSequence) -> KVSequence:
        """Prefix fork: the child shares every page (refcounts bumped);
        the first divergent append to a shared page triggers CoW."""
        if seq.freed:
            raise RuntimeError("fork of a freed sequence")
        child = KVSequence()
        child.pages = list(seq.pages)
        child.num_tokens = seq.num_tokens
        for pid in child.pages:
            self._incref(pid)
        return child

    def free_sequence(self, seq: KVSequence):
        if seq.freed:
            raise RuntimeError("double free of sequence")
        for pid in seq.pages:
            self._decref(pid)
        seq.pages = []
        seq.num_tokens = 0
        seq.freed = True

    # ---- kernel-facing tensors ------------------------------------------
    def block_table(self, seqs, max_pages: int) -> np.ndarray:
        """(B, max_pages) int32 block table; unused slots hold PAD_PAGE
        (the `paged_attention_decode` padding contract)."""
        bt = np.full((len(seqs), max_pages), PAD_PAGE, np.int32)
        for i, s in enumerate(seqs):
            if len(s.pages) > max_pages:
                raise ValueError(
                    f"sequence holds {len(s.pages)} pages > table width "
                    f"{max_pages}")
            bt[i, :len(s.pages)] = s.pages
        return bt

    def seq_lens(self, seqs) -> np.ndarray:
        return np.asarray([s.num_tokens for s in seqs], np.int32)

    def check_invariants(self):
        """Debug/test hook: free list and refcounts partition the pages."""
        free = set(self._free)
        held = set(self._refs)
        assert not (free & held), f"pages both free and held: {free & held}"
        assert all(r > 0 for r in self._refs.values())
        assert PAD_PAGE not in free and PAD_PAGE not in held
        assert len(free) + len(held) == self.num_pages - 1


# ---------------------------------------------------------------------------
# Host spill tier (ISSUE 17): pinned host-RAM pages under the radix cache.
# ---------------------------------------------------------------------------

class HostPagesExhausted(Exception):
    """No free host page — the radix cache falls back to dropping."""


class HostPageError(Exception):
    """A host page read failed; promotion degrades to recompute."""


class HostPageCorrupt(HostPageError):
    """Payload failed its CRC — the stored bytes are untrustworthy."""


class HostPageSlow(HostPageError):
    """The host read missed its deadline; the page itself is intact."""


class HostPageLost(HostPageError):
    """The backing host buffer is gone (e.g. reclaimed by the OS)."""


# Page-payload wire format. One payload carries ONE radix page's KV bytes
# across every layer (k row, v row, plus the int8 scale rows when the
# cache is quantized). The same bytes are the demote/promote unit AND the
# PR-14 mailbox frame body for cross-worker prefix pulls, so corruption
# detection must be real: the header carries a CRC32 of the body and
# decode refuses anything that does not check out.
PAYLOAD_MAGIC = b"KVPG"
PAYLOAD_VERSION = 1
_PAYLOAD_HEADER = struct.Struct(">4sBHI")   # magic, version, n_arrays, crc


def _np_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        # bfloat16 etc. live in ml_dtypes (a jax dependency), not numpy
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))


def encode_page_payload(arrays) -> bytes:
    """Serialize a list of ndarrays (one page's per-layer rows) into a
    self-describing CRC-protected byte string."""
    parts: List[bytes] = []
    for a in arrays:
        a = np.ascontiguousarray(a)
        dt = str(a.dtype).encode("ascii")
        parts.append(struct.pack(">B", len(dt)))
        parts.append(dt)
        parts.append(struct.pack(">B", a.ndim))
        parts.append(struct.pack(f">{a.ndim}I", *a.shape))
        raw = a.tobytes()
        parts.append(struct.pack(">I", len(raw)))
        parts.append(raw)
    body = b"".join(parts)
    head = _PAYLOAD_HEADER.pack(PAYLOAD_MAGIC, PAYLOAD_VERSION,
                                len(arrays), zlib.crc32(body) & 0xFFFFFFFF)
    return head + body


def decode_page_payload(buf: bytes) -> List[np.ndarray]:
    """Inverse of encode_page_payload. Raises HostPageCorrupt on any
    structural or CRC mismatch — a corrupt page must never reach the
    device arrays."""
    if len(buf) < _PAYLOAD_HEADER.size:
        raise HostPageCorrupt("payload truncated before header")
    magic, version, n_arrays, crc = _PAYLOAD_HEADER.unpack_from(buf)
    if magic != PAYLOAD_MAGIC or version != PAYLOAD_VERSION:
        raise HostPageCorrupt(f"bad payload header {magic!r} v{version}")
    body = buf[_PAYLOAD_HEADER.size:]
    if zlib.crc32(body) & 0xFFFFFFFF != crc:
        raise HostPageCorrupt("payload CRC mismatch")
    arrays: List[np.ndarray] = []
    off = 0
    try:
        for _ in range(n_arrays):
            (dlen,) = struct.unpack_from(">B", body, off); off += 1
            dtype = _np_dtype(body[off:off + dlen].decode("ascii"))
            off += dlen
            (ndim,) = struct.unpack_from(">B", body, off); off += 1
            shape = struct.unpack_from(f">{ndim}I", body, off)
            off += 4 * ndim
            (nbytes,) = struct.unpack_from(">I", body, off); off += 4
            raw = body[off:off + nbytes]
            off += nbytes
            if len(raw) != nbytes:
                raise HostPageCorrupt("payload truncated inside array")
            arrays.append(np.frombuffer(raw, dtype).reshape(shape).copy())
    except (struct.error, ValueError) as e:
        raise HostPageCorrupt(f"payload structure invalid: {e}") from None
    if off != len(body):
        raise HostPageCorrupt(f"{len(body) - off} trailing payload bytes")
    return arrays


class HostPageStore:
    """Ref-counted host-RAM page pool: the spill tier's analogue of
    BlockAllocator, holding encoded page payloads instead of device
    rows. Ids are dense ints over `num_pages` slots with the same
    free-list/refcount discipline (no pad page — host ids never reach
    a device block table).

    The read path (`get`) is where the host_spill fault points live:
    `lost` fires before the lookup (the buffer is gone — the store
    forgets it too, so recovery matches reality), `slow` models a
    deadline miss on an intact page, and `corrupt` flips a body byte so
    decode_page_payload's CRC check — not the injection site — is what
    detects it.
    """

    def __init__(self, num_pages: int):
        if num_pages <= 0:
            raise ValueError("host spill pool needs >= 1 page")
        self.num_pages = num_pages
        self._free = deque(range(num_pages))
        self._refs: Dict[int, int] = {}
        self._payloads: Dict[int, bytes] = {}
        self.bytes_stored = 0

    # ---- page ops --------------------------------------------------------
    def put(self, payload: bytes) -> int:
        if not self._free:
            raise HostPagesExhausted(
                f"all {self.num_pages} host pages in use")
        hid = self._free.popleft()
        self._refs[hid] = 1
        self._payloads[hid] = bytes(payload)
        self.bytes_stored += len(payload)
        return hid

    def get(self, hid: int) -> bytes:
        if faults.fire(FAULT_HOST_LOST) is not None:
            self._forget(hid)
            raise HostPageLost(f"host page {hid} backing buffer gone")
        if self._refs.get(hid, 0) <= 0:
            raise KeyError(f"host page {hid} not held")
        if faults.fire(FAULT_HOST_SLOW) is not None:
            raise HostPageSlow(f"host page {hid} read missed deadline")
        payload = self._payloads[hid]
        if faults.fire(FAULT_HOST_CORRUPT) is not None:
            payload = payload[:-1] + bytes([payload[-1] ^ 0xFF])
        return payload

    def _forget(self, hid: int):
        """Lost-page recovery: drop the slot entirely regardless of
        refcount (the holder's decref path is bypassed — the caller
        drops its radix node instead)."""
        if hid in self._refs:
            del self._refs[hid]
            self.bytes_stored -= len(self._payloads.pop(hid))
            self._free.append(hid)

    def incref(self, hid: int):
        self._refs[hid] += 1

    def decref(self, hid: int):
        r = self._refs.get(hid)
        if r is None or r <= 0:
            raise RuntimeError(f"double free of host page {hid}")
        if r == 1:
            del self._refs[hid]
            self.bytes_stored -= len(self._payloads.pop(hid))
            self._free.append(hid)
        else:
            self._refs[hid] = r - 1

    def holds(self, hid: int) -> bool:
        """True iff the store still holds `hid` (a lost-fault recovery
        may have forgotten it out from under its holders)."""
        return self._refs.get(hid, 0) > 0

    # ---- occupancy -------------------------------------------------------
    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_used(self) -> int:
        return self.num_pages - len(self._free)

    def occupancy(self) -> float:
        return self.num_used / float(self.num_pages)

    def check_invariants(self):
        free = set(self._free)
        held = set(self._refs)
        assert not (free & held), f"host pages free AND held: {free & held}"
        assert all(r > 0 for r in self._refs.values())
        assert held == set(self._payloads), "payloads out of sync with refs"
        assert len(free) + len(held) == self.num_pages
        assert self.bytes_stored == \
            sum(len(p) for p in self._payloads.values())
