"""Back-compat shim (ISSUE 11): the Prometheus renderer moved to
`paddle_tpu.profiler.exposition` so the training monitor and the
serving metrics scrape through ONE rule set. Every public name is
re-exported; new code should import from the profiler module."""
from __future__ import annotations

from ..profiler.exposition import (metric_name, parse_exposition_names,
                                   prometheus_lines, render_prometheus,
                                   sanitize_label_value,
                                   sanitize_metric_name)

__all__ = ["render_prometheus", "prometheus_lines", "metric_name",
           "sanitize_metric_name", "sanitize_label_value",
           "parse_exposition_names"]
