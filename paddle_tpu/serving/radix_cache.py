"""Radix-tree prefix cache over the paged KV allocator.

Design follows SGLang's RadixAttention (Zheng et al., 2023): completed
(or evicted) sequences donate their KV pages into a radix tree keyed by
token content; a new request walks the tree at intake, reuses the
longest cached block-aligned prefix through the allocator's ref-counted
sharing, and only prefills the remainder. Zero-active-ref cached nodes
are LRU-evicted when the allocator runs dry — BEFORE any running
request is preempted (see SERVING.md "Eviction ordering").

Granularity is the allocator's page: every edge in the tree covers a
whole number of pages (len(node.key) == len(node.pages) * page_size),
children are keyed by their edge's FIRST PAGE of tokens (a tuple of
page_size ints), and node splits only happen at page boundaries — a
page's KV covers exactly page_size token positions, so sub-page sharing
is impossible by construction.

Reference-count contract: the tree holds exactly ONE allocator ref for
every page it stores (taken at `insert`, released at eviction/`clear`).
A request that matches a prefix takes its own refs via
`BlockAllocator.alloc_sequence_with_prefix`; eviction of a node whose
pages are still held by live sequences therefore only forgets the
cached entry — the pages return to the free list when the last sequence
drops them. Matching never mutates refcounts (read-only; the scheduler
immediately converts a match into a sequence on the same host thread).

Determinism: LRU ordering uses a monotonic use-counter, not wall-clock,
so scheduling stays replayable (golden-trace tested).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..utils import faults
from .kv_cache import BlockAllocator

__all__ = ["RadixCache", "RadixNode"]

# Fault-injection point (ISSUE 3): donation failure. The scheduler's
# finish/preempt paths must treat a failed insert as "nothing cached"
# — the donor still frees its sequence normally, pages reclaim fully.
FAULT_INSERT = faults.register_point("serving.radix.insert")


class RadixNode:
    """One edge+node of the tree: `key` is the token run along the edge
    into this node, `pages` the KV pages holding those tokens."""

    __slots__ = ("key", "pages", "children", "parent", "last_use")

    def __init__(self, key=(), pages=None, parent=None):
        self.key: Tuple[int, ...] = tuple(key)
        self.pages: List[int] = list(pages or [])
        self.children: Dict[Tuple[int, ...], "RadixNode"] = {}
        self.parent: Optional["RadixNode"] = parent

    def __repr__(self):
        return (f"RadixNode(tokens={len(self.key)}, pages={self.pages}, "
                f"children={len(self.children)})")


def _lcp(a, b):
    n = 0
    for x, y in zip(a, b):
        if x != y:
            break
        n += 1
    return n


class RadixCache:
    """Prefix cache: token sequences -> KV pages, page-granular."""

    def __init__(self, allocator: BlockAllocator):
        self.allocator = allocator
        self.page_size = allocator.page_size
        self.root = RadixNode()
        self.root.last_use = 0
        self._tick = 0
        # counters the metrics provider reads
        self.num_evicted_pages = 0
        self.num_inserted_pages = 0
        # incremental size counters: the engine reads these as gauges
        # every step, so they must not cost a tree walk
        self._cached_pages = 0
        self._nodes = 0

    def _bump(self, node):
        self._tick += 1
        node.last_use = self._tick

    def _edge_key(self, tokens):
        return tuple(tokens[:self.page_size])

    # ---- lookup ----------------------------------------------------------
    def _walk_prefix(self, tokens):
        """Shared edge-walk under both lookups: yield (child,
        full_pages_matched_on_edge) down the longest cached prefix of
        `tokens`, stopping at a missing child or a mid-edge divergence.
        Pure traversal — bumping (or not) is the caller's policy, which
        is the whole difference between `match` and `match_len`."""
        tokens = tuple(tokens)
        node = self.root
        while len(tokens) >= self.page_size:
            child = node.children.get(self._edge_key(tokens))
            if child is None:
                return
            n = _lcp(child.key, tokens)
            yield child, n // self.page_size
            if n < len(child.key):
                return                     # diverged (or ran out) mid-edge
            node = child
            tokens = tokens[n:]

    def match(self, tokens) -> Tuple[List[int], int]:
        """Longest cached block-aligned prefix of `tokens`.

        Returns (pages, num_matched_tokens) with num_matched ==
        len(pages) * page_size. Read-only except for the LRU bump on
        every node touched; the caller must convert the match into
        sequence refs (alloc_sequence_with_prefix) before anything else
        can evict — matched pages are also the freshest LRU entries, and
        `evict(protect=...)` exists for the admission retry path.
        """
        pages: List[int] = []
        for child, full in self._walk_prefix(tokens):
            pages.extend(child.pages[:full])
            self._bump(child)
        return pages, len(pages) * self.page_size

    def match_len(self, tokens) -> int:
        """READ-ONLY longest-prefix probe: the token count `match()`
        would report (same walk by construction), with NO LRU bump
        (eviction order untouched) and no refcount change. The fleet
        router scores every replica's cache with this on every
        submission — a probe that bumped LRU entries would let routing
        traffic (including for requests that land elsewhere) distort
        each replica's eviction order."""
        return sum(full for _, full in self._walk_prefix(tokens)) \
            * self.page_size

    # ---- insertion (donation) -------------------------------------------
    def insert(self, tokens, pages) -> int:
        """Donate `pages` holding the KV of `tokens` (len(tokens) ==
        len(pages) * page_size; the caller truncates to full pages).

        The tree takes its own allocator ref on every page it ADOPTS;
        spans already cached keep the existing pages (the donor's
        duplicates are simply not adopted). The caller retains its refs
        and frees its sequence normally afterwards. Returns the number
        of newly adopted pages."""
        faults.fire(FAULT_INSERT)
        tokens = tuple(tokens)
        if len(tokens) != len(pages) * self.page_size:
            raise ValueError(
                f"insert needs page-aligned tokens: {len(tokens)} tokens "
                f"vs {len(pages)} pages of {self.page_size}")
        node = self.root
        adopted = 0
        while tokens:
            child = node.children.get(self._edge_key(tokens))
            if child is None:
                new = RadixNode(tokens, pages, parent=node)
                for pid in new.pages:
                    self.allocator._incref(pid)
                adopted += len(new.pages)
                node.children[self._edge_key(tokens)] = new
                self._nodes += 1
                self._cached_pages += len(new.pages)
                self._bump(new)
                break
            n = _lcp(child.key, tokens)
            aligned = (n // self.page_size) * self.page_size
            # the dict hit guarantees the first page matched in full
            assert aligned >= self.page_size
            self._bump(child)
            if n == len(child.key):
                node = child
                tokens = tokens[n:]
                pages = pages[n // self.page_size:]
                continue
            # diverged (or ran out of tokens) inside the edge: split at
            # the last shared page boundary and continue under the upper
            # half (aligned <= n < len(child.key), so the split is real)
            self._split(child, aligned)
            node = child
            tokens = tokens[aligned:]
            pages = pages[aligned // self.page_size:]
        self.num_inserted_pages += adopted
        return adopted

    def _split(self, child, at):
        """Split `child`'s edge at token offset `at` (a page multiple):
        child becomes the upper node; a new node takes the tail."""
        assert at % self.page_size == 0 and 0 < at < len(child.key)
        tail = RadixNode(child.key[at:], child.pages[at // self.page_size:],
                         parent=child)
        tail.children = child.children
        for c in tail.children.values():
            c.parent = tail
        tail.last_use = child.last_use
        child.key = child.key[:at]
        child.pages = child.pages[:at // self.page_size]
        child.children = {self._edge_key(tail.key): tail}
        self._nodes += 1               # pages just moved between nodes

    # ---- eviction --------------------------------------------------------
    def _iter_nodes(self):
        stack = list(self.root.children.values())
        while stack:
            n = stack.pop()
            yield n
            stack.extend(n.children.values())

    def evictable_pages(self) -> int:
        """Pages eviction could actually return to the free list right
        now (tree-held pages no live sequence shares)."""
        return sum(1 for n in self._iter_nodes() for p in n.pages
                   if self.allocator._refs.get(p) == 1)

    def evict(self, need_pages: int, protect=()) -> int:
        """LRU-evict leaf nodes until >= `need_pages` pages actually hit
        the free list (or nothing evictable remains). Leaves whose pages
        are ALL still shared with live sequences are skipped — evicting
        them frees nothing and throws away a reusable prefix. `protect`
        pages (e.g. a match the scheduler is about to take refs on) are
        never evicted. Returns pages freed."""
        protect = set(protect)
        freed = 0
        while freed < need_pages:
            best = None
            for n in self._iter_nodes():
                if n.children or (protect & set(n.pages)):
                    continue
                if not any(self.allocator._refs.get(p) == 1
                           for p in n.pages):
                    continue               # all shared: frees nothing
                if best is None or n.last_use < best.last_use:
                    best = n
            if best is None:
                break
            freed += self._drop_node(best)
        return freed

    def _drop_node(self, node) -> int:
        before = self.allocator.num_free
        for pid in node.pages:
            self.allocator._decref(pid)
        del node.parent.children[self._edge_key(node.key)]
        self._nodes -= 1
        self._cached_pages -= len(node.pages)
        freed = self.allocator.num_free - before
        self.num_evicted_pages += freed
        return freed

    def clear(self) -> int:
        """Drop every cached node (releases the tree's refs); returns
        pages returned to the free list."""
        before = self.allocator.num_free
        for node in list(self._iter_nodes()):
            for pid in node.pages:
                self.allocator._decref(pid)
        self.root = RadixNode()
        self.root.last_use = self._tick
        self._cached_pages = 0
        self._nodes = 0
        return self.allocator.num_free - before

    # ---- introspection ---------------------------------------------------
    @property
    def num_cached_pages(self) -> int:
        return self._cached_pages

    @property
    def num_nodes(self) -> int:
        return self._nodes

    def check_invariants(self):
        """Test hook: page-aligned edges, child keys match edge heads,
        every stored page holds a live allocator ref, size counters
        agree with a full recount."""
        assert self._cached_pages == \
            sum(len(n.pages) for n in self._iter_nodes())
        assert self._nodes == sum(1 for _ in self._iter_nodes())
        for node in self._iter_nodes():
            assert len(node.key) == len(node.pages) * self.page_size
            assert node.key, "empty edge"
            assert node.parent.children[self._edge_key(node.key)] is node
            for k, c in node.children.items():
                assert k == self._edge_key(c.key)
            for pid in node.pages:
                assert self.allocator._refs.get(pid, 0) >= 1, \
                    f"tree page {pid} has no allocator ref"
