"""Radix-tree prefix cache over the paged KV allocator.

Design follows SGLang's RadixAttention (Zheng et al., 2023): completed
(or evicted) sequences donate their KV pages into a radix tree keyed by
token content; a new request walks the tree at intake, reuses the
longest cached block-aligned prefix through the allocator's ref-counted
sharing, and only prefills the remainder. Zero-active-ref cached nodes
are LRU-evicted when the allocator runs dry — BEFORE any running
request is preempted (see SERVING.md "Eviction ordering").

Granularity is the allocator's page: every edge in the tree covers a
whole number of pages (len(node.key) == len(node.pages) * page_size),
children are keyed by their edge's FIRST PAGE of tokens (a tuple of
page_size ints), and node splits only happen at page boundaries — a
page's KV covers exactly page_size token positions, so sub-page sharing
is impossible by construction.

Reference-count contract: the tree holds exactly ONE allocator ref for
every page it stores (taken at `insert`, released at eviction/`clear`).
A request that matches a prefix takes its own refs via
`BlockAllocator.alloc_sequence_with_prefix`; eviction of a node whose
pages are still held by live sequences therefore only forgets the
cached entry — the pages return to the free list when the last sequence
drops them. Matching never mutates refcounts (read-only; the scheduler
immediately converts a match into a sequence on the same host thread).

Determinism: LRU ordering uses a monotonic use-counter, not wall-clock,
so scheduling stays replayable (golden-trace tested).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..utils import faults
from .kv_cache import (BlockAllocator, HostPageCorrupt, HostPageLost,
                       HostPageSlow)

__all__ = ["RadixCache", "RadixNode"]

# Fault-injection point (ISSUE 3): donation failure. The scheduler's
# finish/preempt paths must treat a failed insert as "nothing cached"
# — the donor still frees its sequence normally, pages reclaim fully.
FAULT_INSERT = faults.register_point("serving.radix.insert")


class RadixNode:
    """One edge+node of the tree: `key` is the token run along the edge
    into this node, `pages` the KV pages holding those tokens.

    Residency (ISSUE 17): a non-root node is either DEVICE-resident
    (`pages` holds device ids, each carrying one tree ref on the
    BlockAllocator) or HOST-resident (`host_pages` holds HostPageStore
    ids, each carrying one tree ref there; `pages` is empty) — never
    both. The in-flight window of a promotion is device-side only: the
    host bookkeeping flips host->device atomically when the async copy
    is enqueued, and the device stream orders the copy before any
    kernel that reads the page."""

    __slots__ = ("key", "pages", "host_pages", "children", "parent",
                 "last_use")

    def __init__(self, key=(), pages=None, parent=None):
        self.key: Tuple[int, ...] = tuple(key)
        self.pages: List[int] = list(pages or [])
        self.host_pages: List[int] = []
        self.children: Dict[Tuple[int, ...], "RadixNode"] = {}
        self.parent: Optional["RadixNode"] = parent

    def __repr__(self):
        where = f"host_pages={self.host_pages}" if self.host_pages \
            else f"pages={self.pages}"
        return (f"RadixNode(tokens={len(self.key)}, {where}, "
                f"children={len(self.children)})")


def _lcp(a, b):
    n = 0
    for x, y in zip(a, b):
        if x != y:
            break
        n += 1
    return n


class RadixCache:
    """Prefix cache: token sequences -> KV pages, page-granular."""

    def __init__(self, allocator: BlockAllocator):
        self.allocator = allocator
        self.page_size = allocator.page_size
        self.root = RadixNode()
        self.root.last_use = 0
        self._tick = 0
        # host spill tier (ISSUE 17): None = HBM-only (the pre-spill
        # behaviour, bit for bit). The bridge is engine-owned (the tree
        # has no device access) and provides:
        #   host_free() -> int                free host pages
        #   demote(pids) -> hids | None       device gather -> host store
        #   promote(hids) -> pids | None      host -> device async scatter
        #                                     (raises HostPageError kinds)
        #   release(hids)                     drop the tree's host refs
        #   holds(hid) -> bool                store still holds the id
        self.spill = None
        # counters the metrics provider reads
        self.num_evicted_pages = 0
        self.num_inserted_pages = 0
        # eviction rungs (ISSUE 17 satellite): which rung each eviction
        # took — demote-to-host vs drop — so spill hit-rate claims are
        # auditable from counters alone
        self.num_evict_demoted = 0
        self.num_evict_dropped = 0
        # spill traffic counters
        self.num_demoted_pages = 0
        self.num_promoted_pages = 0
        self.num_host_hits = 0
        self.num_host_dropped_pages = 0
        # incremental size counters: the engine reads these as gauges
        # every step, so they must not cost a tree walk
        self._cached_pages = 0
        self._nodes = 0
        self._host_pages = 0

    def set_spill(self, bridge):
        """Attach the engine's host-spill bridge (see __init__)."""
        self.spill = bridge

    def _bump(self, node):
        self._tick += 1
        node.last_use = self._tick

    def _edge_key(self, tokens):
        return tuple(tokens[:self.page_size])

    # ---- lookup ----------------------------------------------------------
    def _walk_prefix(self, tokens):
        """Shared edge-walk under both lookups: yield (child,
        full_pages_matched_on_edge) down the longest cached prefix of
        `tokens`, stopping at a missing child or a mid-edge divergence.
        Pure traversal — bumping (or not) is the caller's policy, which
        is the whole difference between `match` and `match_len`."""
        tokens = tuple(tokens)
        node = self.root
        while len(tokens) >= self.page_size:
            child = node.children.get(self._edge_key(tokens))
            if child is None:
                return
            n = _lcp(child.key, tokens)
            yield child, n // self.page_size
            if n < len(child.key):
                return                     # diverged (or ran out) mid-edge
            node = child
            tokens = tokens[n:]

    def match(self, tokens, promote_budget=None) -> Tuple[List[int], int]:
        """Longest cached block-aligned prefix of `tokens`.

        Returns (pages, num_matched_tokens) with num_matched ==
        len(pages) * page_size. Read-only except for the LRU bump on
        every node touched; the caller must convert the match into
        sequence refs (alloc_sequence_with_prefix) before anything else
        can evict — matched pages are also the freshest LRU entries, and
        `evict(protect=...)` exists for the admission retry path.

        Host-resident nodes on the walk are PROMOTED back to device
        pages (async host->device copy, enqueued here and overlapped
        with the prefill launch the scheduler is about to build).
        `promote_budget` is the scheduler's remaining chunked-prefill
        token budget: a promotion moves the same bytes a prefill of
        those tokens would write, so it is charged against the same
        budget (whole nodes only — a node that does not fit waits for a
        later step). Any promotion failure — budget, device pages dry,
        or a host_spill fault — STOPS the match at the last device-
        resident token: the remainder recomputes through normal chunked
        prefill, which preserves bit-identity by construction.
        """
        pages: List[int] = []
        for child, full in self._walk_prefix(tokens):
            if child.host_pages:
                if self.spill is None:
                    break
                need_tokens = len(child.host_pages) * self.page_size
                if promote_budget is not None \
                        and promote_budget < need_tokens:
                    break
                if not self._promote_node(child):
                    break
                if promote_budget is not None:
                    promote_budget -= need_tokens
                self.num_host_hits += 1
            pages.extend(child.pages[:full])
            self._bump(child)
        return pages, len(pages) * self.page_size

    def match_len(self, tokens) -> int:
        """READ-ONLY longest-prefix probe: the token count `match()`
        would report (same walk by construction), with NO LRU bump
        (eviction order untouched) and no refcount change. The fleet
        router scores every replica's cache with this on every
        submission — a probe that bumped LRU entries would let routing
        traffic (including for requests that land elsewhere) distort
        each replica's eviction order. Host-resident spans COUNT (they
        are servable without recompute — exactly what the router wants
        to know) but are NOT promoted."""
        return sum(full for _, full in self._walk_prefix(tokens)) \
            * self.page_size

    def _promote_node(self, node) -> bool:
        """Host -> device for one node. True iff the node is device-
        resident on return. Failure handling mirrors the fault points:
        slow keeps the node (the payload is intact — a later match
        retries), corrupt/lost drop the node AND its subtree (the
        prefix chain through it is broken, so descendants are
        unreachable by any match)."""
        try:
            pids = self.spill.promote(node.host_pages)
        except HostPageSlow:
            return False
        except (HostPageCorrupt, HostPageLost):
            self._drop_subtree(node)
            return False
        if pids is None:                   # device pool dry: recompute
            return False
        self.spill.release(node.host_pages)
        self._host_pages -= len(node.host_pages)
        self._cached_pages += len(pids)
        self.num_promoted_pages += len(pids)
        node.pages = list(pids)
        node.host_pages = []
        return True

    # ---- insertion (donation) -------------------------------------------
    def insert(self, tokens, pages) -> int:
        """Donate `pages` holding the KV of `tokens` (len(tokens) ==
        len(pages) * page_size; the caller truncates to full pages).

        The tree takes its own allocator ref on every page it ADOPTS;
        spans already cached keep the existing pages (the donor's
        duplicates are simply not adopted). The caller retains its refs
        and frees its sequence normally afterwards. Returns the number
        of newly adopted pages."""
        faults.fire(FAULT_INSERT)
        tokens = tuple(tokens)
        if len(tokens) != len(pages) * self.page_size:
            raise ValueError(
                f"insert needs page-aligned tokens: {len(tokens)} tokens "
                f"vs {len(pages)} pages of {self.page_size}")
        node = self.root
        adopted = 0
        while tokens:
            child = node.children.get(self._edge_key(tokens))
            if child is None:
                new = RadixNode(tokens, pages, parent=node)
                for pid in new.pages:
                    self.allocator._incref(pid)
                adopted += len(new.pages)
                node.children[self._edge_key(tokens)] = new
                self._nodes += 1
                self._cached_pages += len(new.pages)
                self._bump(new)
                break
            n = _lcp(child.key, tokens)
            aligned = (n // self.page_size) * self.page_size
            # the dict hit guarantees the first page matched in full
            assert aligned >= self.page_size
            self._bump(child)
            if n == len(child.key):
                adopted += self._readopt(child, pages)
                node = child
                tokens = tokens[n:]
                pages = pages[n // self.page_size:]
                continue
            # diverged (or ran out of tokens) inside the edge: split at
            # the last shared page boundary and continue under the upper
            # half (aligned <= n < len(child.key), so the split is real)
            self._split(child, aligned)
            adopted += self._readopt(child, pages)
            node = child
            tokens = tokens[aligned:]
            pages = pages[aligned // self.page_size:]
        self.num_inserted_pages += adopted
        return adopted

    def _readopt(self, node, donor_pages) -> int:
        """Insert walked onto a HOST-resident span the donor holds
        device pages for: adopt the donor's pages (residency repair for
        free — no host->device copy) and release the host copies. The
        node's span is fully covered by the donor here (callers only
        reach this after matching the whole — possibly just-split —
        edge). No-op for device-resident nodes."""
        if not node.host_pages:
            return 0
        k = len(node.key) // self.page_size
        fresh = list(donor_pages[:k])
        assert len(fresh) == k
        for pid in fresh:
            self.allocator._incref(pid)
        if self.spill is not None:
            self.spill.release(node.host_pages)
        self._host_pages -= len(node.host_pages)
        self._cached_pages += k
        node.pages = fresh
        node.host_pages = []
        return k

    def _split(self, child, at):
        """Split `child`'s edge at token offset `at` (a page multiple):
        child becomes the upper node; a new node takes the tail."""
        assert at % self.page_size == 0 and 0 < at < len(child.key)
        cut = at // self.page_size
        tail = RadixNode(child.key[at:], child.pages[cut:], parent=child)
        tail.host_pages = child.host_pages[cut:]
        tail.children = child.children
        for c in tail.children.values():
            c.parent = tail
        tail.last_use = child.last_use
        child.key = child.key[:at]
        child.pages = child.pages[:cut]
        child.host_pages = child.host_pages[:cut]
        child.children = {self._edge_key(tail.key): tail}
        self._nodes += 1               # pages just moved between nodes

    # ---- eviction --------------------------------------------------------
    def _iter_nodes(self):
        stack = list(self.root.children.values())
        while stack:
            n = stack.pop()
            yield n
            stack.extend(n.children.values())

    def evictable_pages(self) -> int:
        """Pages eviction could actually return to the free list right
        now (tree-held pages no live sequence shares)."""
        return sum(1 for n in self._iter_nodes() for p in n.pages
                   if self.allocator._refs.get(p) == 1)

    def evict(self, need_pages: int, protect=()) -> int:
        """LRU-evict device-resident leaf-rung nodes until >=
        `need_pages` pages actually hit the free list (or nothing
        evictable remains). Leaves whose pages are ALL still shared with
        live sequences are skipped — evicting them frees nothing and
        throws away a reusable prefix. `protect` pages (e.g. a match the
        scheduler is about to take refs on) are never evicted. Returns
        pages freed.

        Eviction rungs (ISSUE 17): with a spill bridge attached each
        victim is DEMOTED to host RAM first (KV bytes survive; the
        device pages free) and only DROPPED when the host pool cannot
        take it even after LRU-dropping host leaves. The rung taken is
        counted (num_evict_demoted / num_evict_dropped) so hit-rate
        claims audit from counters alone. Host-resident children do not
        shield a node from the rung (they hold no device pages), but a
        drop beneath them severs their prefix, so the drop rung drops
        that subtree too."""
        protect = set(protect)
        freed = 0
        while freed < need_pages:
            best = None
            for n in self._iter_nodes():
                if not n.pages or (protect & set(n.pages)):
                    continue               # host-resident or protected
                if any(c.pages for c in n.children.values()):
                    continue               # a device child: not the rung
                if not any(self.allocator._refs.get(p) == 1
                           for p in n.pages):
                    continue               # all shared: frees nothing
                if best is None or n.last_use < best.last_use:
                    best = n
            if best is None:
                break
            got = self._demote_node(best) if self.spill is not None \
                else None
            if got is None:
                for c in list(best.children.values()):
                    self._drop_subtree(c)  # orphaned host descendants
                freed += self._drop_node(best)
                self.num_evict_dropped += 1
            else:
                freed += got
                self.num_evict_demoted += 1
        return freed

    def _demote_node(self, node):
        """Device -> host for one node: gather the pages' bytes into the
        host store (making room by LRU-dropping host leaves if needed),
        then release the tree's device refs. Returns pages actually
        freed to the device free list, or None when the host tier cannot
        take the node (caller falls through to the drop rung)."""
        need = len(node.pages)
        if self.spill.host_free() < need:
            self._evict_host(need - self.spill.host_free(), keep=node)
        if self.spill.host_free() < need:
            return None
        hids = self.spill.demote(node.pages)
        if hids is None:
            return None
        before = self.allocator.num_free
        for pid in node.pages:
            self.allocator._decref(pid)
        freed = self.allocator.num_free - before
        self.num_evicted_pages += freed
        self.num_demoted_pages += len(hids)
        self._cached_pages -= len(node.pages)
        self._host_pages += len(hids)
        node.host_pages = list(hids)
        node.pages = []
        return freed

    def _evict_host(self, need: int, keep=None) -> int:
        """LRU-drop childless host-resident nodes until `need` host
        pages are free (or none remain). `keep` shields the node a
        demotion is making room for."""
        freed = 0
        while freed < need:
            best = None
            for n in self._iter_nodes():
                if n is keep or not n.host_pages or n.children:
                    continue
                if best is None or n.last_use < best.last_use:
                    best = n
            if best is None:
                break
            freed += len(best.host_pages)
            self._drop_host_node(best)
        return freed

    def _drop_host_node(self, node):
        """Remove a host-resident node, releasing its host refs. (After
        a host_spill.lost fault the store has already forgotten the lost
        id; the bridge's release tolerates exactly that.)"""
        if self.spill is not None:
            self.spill.release(node.host_pages)
        self.num_host_dropped_pages += len(node.host_pages)
        del node.parent.children[self._edge_key(node.key)]
        self._nodes -= 1
        self._host_pages -= len(node.host_pages)

    def _drop_subtree(self, node):
        """Drop `node` and every descendant, whatever their residency."""
        for c in list(node.children.values()):
            self._drop_subtree(c)
        if node.host_pages:
            self._drop_host_node(node)
        else:
            self._drop_node(node)

    def _drop_node(self, node) -> int:
        before = self.allocator.num_free
        for pid in node.pages:
            self.allocator._decref(pid)
        del node.parent.children[self._edge_key(node.key)]
        self._nodes -= 1
        self._cached_pages -= len(node.pages)
        freed = self.allocator.num_free - before
        self.num_evicted_pages += freed
        return freed

    def clear(self) -> int:
        """Drop every cached node (releases the tree's refs on BOTH
        tiers); returns device pages returned to the free list."""
        before = self.allocator.num_free
        for node in list(self._iter_nodes()):
            for pid in node.pages:
                self.allocator._decref(pid)
            if node.host_pages and self.spill is not None:
                self.spill.release(node.host_pages)
            self.num_host_dropped_pages += len(node.host_pages)
        self.root = RadixNode()
        self.root.last_use = self._tick
        self._cached_pages = 0
        self._nodes = 0
        self._host_pages = 0
        return self.allocator.num_free - before

    # ---- introspection ---------------------------------------------------
    @property
    def num_cached_pages(self) -> int:
        return self._cached_pages

    @property
    def num_host_pages(self) -> int:
        return self._host_pages

    @property
    def num_nodes(self) -> int:
        return self._nodes

    def check_invariants(self):
        """Test hook: page-aligned edges, child keys match edge heads,
        every stored page holds a live ref on its tier, exactly one
        residency per node, size counters agree with a full recount."""
        assert self._cached_pages == \
            sum(len(n.pages) for n in self._iter_nodes())
        assert self._host_pages == \
            sum(len(n.host_pages) for n in self._iter_nodes())
        assert self._nodes == sum(1 for _ in self._iter_nodes())
        for node in self._iter_nodes():
            assert not (node.pages and node.host_pages), \
                "node on both residency tiers"
            held = node.host_pages or node.pages
            assert len(node.key) == len(held) * self.page_size
            assert node.key, "empty edge"
            assert node.parent.children[self._edge_key(node.key)] is node
            for k, c in node.children.items():
                assert k == self._edge_key(c.key)
            for pid in node.pages:
                assert self.allocator._refs.get(pid, 0) >= 1, \
                    f"tree page {pid} has no allocator ref"
            if node.host_pages:
                assert self.spill is not None, \
                    "host-resident node with no spill bridge"
                for hid in node.host_pages:
                    assert self.spill.holds(hid), \
                        f"tree host page {hid} has no store ref"
