"""Draft-model proposer: a smaller causal LM drafts K tokens per step.

The classic two-model speculative setup (Leviathan et al., 2023;
vLLM's draft-model speculator): a cheap `LlamaForCausalLM`-protocol
model autoregressively proposes K continuation tokens which the target
then scores in one verify launch. TPU-shaped like the engine itself:
the draft model owns its OWN `BlockAllocator` + paged K/V caches in
the same (num_pages, KVH, page, D) block-table layout the kernels
expect, and all its device work runs through a small bucketed program
grid — a per-sequence catch-up CHUNK program (reusing
`forward_paged_prefill`) plus a BATCHED greedy decode program (reusing
`forward_paged_decode`) — so drafting never triggers unbounded
recompilation either.

Drafting is greedy by design: a deterministic proposal is verified
with the one-hot rejection rule (accept draft d with probability
p_target(d); on rejection sample the renormalized remainder), which is
unbiased for ANY deterministic proposer — so the same verify program
serves both this and `NgramProposer`, and greedy-target acceptance is
exact longest-prefix matching.

Resilience contract (`Proposer` docstring): drafting is advisory, so
every failure here degrades to "no drafts this step" rather than
propagating into the engine step — a draft OOM truncates that
request's draft KV and skips it; a failure that consumed the donated
draft caches (the TPU hazard `ServingEngine._caches_alive` guards)
disables the proposer for the engine's lifetime, other errors retry
next round and disable only after 3 consecutive failures. A disable
is never silent: `disabled_reason` records why and a RuntimeWarning
fires (a missing speedup must be diagnosable).
"""
from __future__ import annotations

from typing import Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from ...core.autograd import no_grad
from ...core.tensor import Tensor
from ...jit.api import functional_call
from ..kv_cache import BlockAllocator, BlocksExhausted, PAD_PAGE
from .proposer import Proposer

__all__ = ["DraftModelProposer"]


class _DraftSeq:
    """Per-request draft cache state: the tokens whose K/V currently
    live in the draft pool, and the pages holding them."""

    __slots__ = ("seq", "tokens")

    def __init__(self, seq):
        self.seq = seq
        self.tokens: List[int] = []


class DraftModelProposer(Proposer):
    def __init__(self, draft_model, *, num_pages: int = 128,
                 page_size: int = 16,
                 prefill_buckets=None, batch_buckets=None,
                 pages_buckets=None):
        from ..engine import _bucket_for, _pow2_buckets  # no cycle: the
        # engine never imports serving.spec (proposers are passed in)
        self._bucket_for = _bucket_for
        cfg = draft_model.cfg
        self.model = draft_model
        self.num_layers = cfg.num_hidden_layers
        self.num_kv = cfg.num_key_value_heads
        self.head_dim = cfg.hidden_size // cfg.num_attention_heads
        self.page_size = int(page_size)
        self.num_pages = int(num_pages)
        self._weights = {k: t._data
                         for k, t in draft_model.state_dict().items()}
        from ...kernels.paged_attention import check_supported_paged
        dtype = next(iter(self._weights.values())).dtype
        check_supported_paged(
            (1, cfg.num_attention_heads, self.head_dim),
            (self.num_pages, self.num_kv, self.page_size, self.head_dim),
            dtype)
        self.max_seq_len = min(int(cfg.max_position_embeddings),
                               (self.num_pages - 1) * self.page_size)
        max_pages_per_seq = -(-self.max_seq_len // self.page_size)
        self.prefill_buckets = sorted(
            prefill_buckets or _pow2_buckets(
                min(16, self.max_seq_len), self.max_seq_len))
        self.batch_buckets = sorted(batch_buckets or _pow2_buckets(1, 8))
        self.pages_buckets = sorted(
            pages_buckets or _pow2_buckets(
                min(2, max_pages_per_seq), max_pages_per_seq))
        self.max_seq_len = min(self.max_seq_len,
                               self.pages_buckets[-1] * self.page_size)

        self.allocator = BlockAllocator(self.num_pages, self.page_size)
        shape = (self.num_pages, self.num_kv, self.page_size, self.head_dim)
        self._k_caches = [jnp.zeros(shape, dtype)
                          for _ in range(self.num_layers)]
        self._v_caches = [jnp.zeros(shape, dtype)
                          for _ in range(self.num_layers)]
        # unified ProgramCache (ISSUE 8): the draft model's catch-up
        # chunk + decode programs are their own families, bounded by
        # the draft bucket grid exactly like the engine's
        from ..program_cache import ProgramCache
        self.programs = ProgramCache()
        self.programs.register_family(
            "draft_chunk", lambda: (len(self.prefill_buckets)
                                    * len(self.pages_buckets)))
        self.programs.register_family(
            "draft_decode", lambda: (len(self.batch_buckets)
                                     * len(self.pages_buckets)))
        self._donate = (1, 2) if jax.default_backend() == "tpu" else ()
        # draft-model structure rides every draft program key (B1):
        # the builders close over num_layers as a Python constant, so
        # two proposers of different depth must never share a program
        self._dkey = (("layers", self.num_layers),)
        self._states: Dict[int, _DraftSeq] = {}
        # drafting turned itself off (see propose()): the engine keeps
        # decoding plainly. `disabled_reason` records why — a silently
        # missing speedup must be diagnosable from the proposer state.
        self.disabled = False
        self.disabled_reason: str = ""
        self.num_draft_launches = 0
        self.num_propose_failures = 0
        self._consecutive_failures = 0

    # ------------------------------------------------------------ programs
    @property
    def num_compiled_programs(self) -> int:
        return self.programs.num_programs

    def program_counts(self):
        return self.programs.counts()

    def max_program_count(self, family=None) -> int:
        return self.programs.max_count(family)

    def _get_program(self, key, builder):
        return self.programs.get(key, builder)

    def _build_chunk(self, S, P):
        """Catch-up chunk: write one span of ONE sequence's history into
        the draft cache and return the greedy next token (the first
        draft, when the span reaches the history end)."""
        L = self.num_layers
        # tpu-lint: cache-key-ok (per-proposer cache, no disk tier)
        model = self.model

        def program(state, kcs, vcs, ids, cache_len, live, bt):
            st = {k: Tensor(v) for k, v in state.items()}
            paged = [(Tensor(kcs[l]), Tensor(vcs[l])) for l in range(L)]
            logits, caches = functional_call(
                model, st, Tensor(ids), paged, Tensor(bt),
                Tensor(cache_len), Tensor(live),
                method="forward_paged_prefill")
            tok = jnp.argmax(logits._data[0, 0]).astype(jnp.int32)
            return (tok, [c[0]._data for c in caches],
                    [c[1]._data for c in caches])

        # tpu-lint: cache-key-ok (donation is backend-constant per process)
        return jax.jit(program, donate_argnums=self._donate)

    def _build_decode(self, B, P):
        """One batched greedy draft step over the draft paged caches."""
        L = self.num_layers
        # tpu-lint: cache-key-ok (per-proposer cache, no disk tier)
        model = self.model

        def program(state, kcs, vcs, ids, bt, sl):
            st = {k: Tensor(v) for k, v in state.items()}
            paged = [(Tensor(kcs[l]), Tensor(vcs[l])) for l in range(L)]
            logits, caches = functional_call(
                model, st, Tensor(ids), paged, Tensor(bt), Tensor(sl),
                method="forward_paged_decode")
            toks = jnp.argmax(logits._data[:, 0, :], axis=-1).astype(
                jnp.int32)
            return (toks, [c[0]._data for c in caches],
                    [c[1]._data for c in caches])

        # tpu-lint: cache-key-ok (donation is backend-constant per process)
        return jax.jit(program, donate_argnums=self._donate)

    # ------------------------------------------------------------- helpers
    def _state_of(self, req) -> _DraftSeq:
        st = self._states.get(req.request_id)
        if st is None:
            seq = self.allocator.alloc_sequence(0)
            st = _DraftSeq(seq)
            self._states[req.request_id] = st
        return st

    def _extend(self, st: _DraftSeq, n: int) -> bool:
        """Grow the draft sequence by n token slots; all-or-nothing (a
        mid-loop pool exhaustion rolls back to the entry length)."""
        base = st.seq.num_tokens
        try:
            for _ in range(n):
                # no forks in the draft pool -> never returns CoW copies
                self.allocator.append_token(st.seq)
        except BlocksExhausted:
            self.allocator.truncate_sequence(st.seq, base)
            return False
        return True

    def _sync(self, st: _DraftSeq, hist: List[int]):
        """Roll the draft cache back to its longest still-valid prefix
        of `hist` (stale tokens = rejected drafts or divergence), capped
        at len(hist)-1 so the catch-up chunk always has at least the
        newest token to process (its logits seed the first draft)."""
        lcp = 0
        for a, b in zip(st.tokens, hist):
            if a != b:
                break
            lcp += 1
        lcp = min(lcp, len(hist) - 1)
        if lcp < st.seq.num_tokens:
            self.allocator.truncate_sequence(st.seq, lcp)
        del st.tokens[lcp:]

    def _launch(self, prog, *args):
        self.num_draft_launches += 1
        with no_grad():
            return prog(self._weights, self._k_caches, self._v_caches,
                        *args)

    # ------------------------------------------------------------- propose
    def propose(self, reqs, k: int) -> List[List[int]]:
        drafts: List[List[int]] = [[] for _ in reqs]
        if self.disabled or k <= 0:
            return drafts
        try:
            out = self._propose(reqs, k, drafts)
            self._consecutive_failures = 0
            return out
        except Exception as exc:                         # noqa: BLE001
            # advisory contract: NO draft-side failure may take the
            # engine step down. Two bins: (a) the failed dispatch may
            # have consumed the donated draft caches (TPU) — nothing
            # valid to re-pass, same hazard as engine._caches_alive, so
            # drafting is off for this engine's life; (b) the caches
            # are alive (host-side error, pre-dispatch failure) — skip
            # this round and only give up after repeated failures.
            # Either way the shutdown is RECORDED, never silent.
            self.num_propose_failures += 1
            self._consecutive_failures += 1
            caches_dead = any(
                getattr(a, "is_deleted", lambda: False)()
                for a in (self._k_caches[0], self._v_caches[0]))
            if caches_dead:
                self._disable(f"draft launch consumed donated caches: "
                              f"{exc!r}")
            elif self._consecutive_failures >= 3:
                self._disable(f"3 consecutive propose failures, "
                              f"last: {exc!r}")
            return [[] for _ in reqs]

    def _disable(self, reason: str):
        import warnings
        self.disabled = True
        self.disabled_reason = reason
        warnings.warn(f"DraftModelProposer disabled ({reason}); the "
                      "engine continues with plain decode",
                      RuntimeWarning, stacklevel=3)

    def _propose(self, reqs, k, drafts):
        # --- per-request catch-up: prefill the history gap ---------------
        active = []                      # (row index, draft-state) pairs
        for i, req in enumerate(reqs):
            hist = [int(t) for t in req.resume_ids]
            if len(hist) + k - 1 > self.max_seq_len:
                continue                 # request outgrew the draft pool
            st = self._state_of(req)
            self._sync(st, hist)
            need = hist[len(st.tokens):]
            if not self._extend(st, len(need)):
                continue                 # draft pool dry: skip this one
            pos = len(st.tokens)
            tok = None
            while need:
                span = need[:self.prefill_buckets[-1]]
                S = self._bucket_for(len(span), self.prefill_buckets)
                P = self._bucket_for(
                    self.allocator.pages_needed(pos + len(span)),
                    self.pages_buckets)
                prog = self._get_program(
                    ("draft_chunk", S, P) + self._dkey,
                    lambda: self._build_chunk(S, P))
                bt = np.full((P,), PAD_PAGE, np.int32)
                npages = min(len(st.seq.pages), P)
                bt[:npages] = st.seq.pages[:npages]
                padded = np.zeros((1, S), np.int32)
                padded[0, :len(span)] = span
                tok, self._k_caches, self._v_caches = self._launch(
                    prog, jnp.asarray(padded), jnp.int32(pos),
                    jnp.int32(len(span)), jnp.asarray(bt))
                st.tokens.extend(span)
                pos += len(span)
                need = need[len(span):]
            drafts[i] = [int(tok)]
            active.append((i, st))

        # --- batched greedy decode for drafts 2..k -----------------------
        for _ in range(1, k):
            step = [(i, st) for i, st in active
                    if self._extend(st, 1)]
            if not step:
                break
            B = self._bucket_for(len(step), self.batch_buckets)
            maxp = max(len(st.seq.pages) for _, st in step)
            P = self._bucket_for(maxp, self.pages_buckets)
            prog = self._get_program(
                ("draft_decode", B, P) + self._dkey,
                lambda: self._build_decode(B, P))
            ids = np.zeros((B, 1), np.int32)
            sl = np.zeros((B,), np.int32)
            bt = np.full((B, P), PAD_PAGE, np.int32)
            for row, (i, st) in enumerate(step):
                ids[row, 0] = drafts[i][-1]
                sl[row] = st.seq.num_tokens
                bt[row, :len(st.seq.pages)] = st.seq.pages
            toks, self._k_caches, self._v_caches = self._launch(
                prog, jnp.asarray(ids), jnp.asarray(bt), jnp.asarray(sl))
            toks = np.asarray(toks)
            for row, (i, st) in enumerate(step):
                st.tokens.append(int(ids[row, 0]))   # its K/V just wrote
                drafts[i].append(int(toks[row]))
            active = step
        return drafts

    # ------------------------------------------------------------ cleanup
    def on_finished(self, req):
        st = self._states.pop(req.request_id, None)
        if st is not None:
            self.allocator.free_sequence(st.seq)

    def reset(self):
        for st in self._states.values():
            self.allocator.free_sequence(st.seq)
        self._states.clear()
