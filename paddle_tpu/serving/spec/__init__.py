"""paddle_tpu.serving.spec — speculative decoding for the serving engine.

Decode is memory-bandwidth-bound (the paged kernel runs near the HBM
roofline — BENCH_OPS/RELAY_STATUS), so per-sequence tokens/step is the
remaining throughput lever. The reference serves this need through its
fused multi-token attention paths (`block_multi_head_attention` /
`masked_multihead_attention`, SURVEY A.2); the TPU-native analog built
here is speculative decoding: a cheap PROPOSER drafts K candidate
tokens per sequence, ONE bucketed `("verify", B, K, P)` launch scores
all of them against the paged cache, and the engine keeps the longest
verified prefix plus one correction/bonus token — amortizing a single
paged-attention pass over up to K+1 emitted tokens. Rejected drafts
roll back via `BlockAllocator.truncate_sequence` with refcount/CoW/
radix invariants intact. See SERVING.md "Speculative decoding".
"""
from .draft_model import DraftModelProposer
from .proposer import NgramProposer, Proposer

__all__ = ["Proposer", "NgramProposer", "DraftModelProposer"]
