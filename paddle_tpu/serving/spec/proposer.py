"""Draft-token proposers for speculative decoding.

A Proposer suggests up to K continuation tokens per decoding request
each engine step; the engine's verify program scores them all in one
launch and keeps the longest accepted prefix. Proposals are PURELY
ADVISORY: a proposer can return fewer than K tokens (or none — the
step then degrades to a plain one-token verify, which emits exactly
what a decode step would), and nothing a proposer returns can change
the emitted token stream under greedy acceptance — only how many
launches it takes to produce it. That contract is what makes the
draft-mismatch chaos storm in `tools/soak_serving.py` a safe no-op on
outputs and lets `DraftModelProposer` draft greedily even when the
target samples.

Proposers see the engine's host-side request state only (token
histories); KV-owning proposers (the draft model) manage their own
pool and are told about terminal requests via `on_finished` so their
pages reclaim.
"""
from __future__ import annotations

from typing import List

__all__ = ["Proposer", "NgramProposer"]


class Proposer:
    """Interface: `propose(reqs, k)` returns one draft list (<= k
    tokens, possibly empty) per request, aligned with `reqs`."""

    def propose(self, reqs, k: int) -> List[List[int]]:
        raise NotImplementedError

    def on_finished(self, req):
        """Request reached a terminal state (finished, aborted,
        expired, quarantined): release any per-request state."""

    def reset(self):
        """Drop all per-request state (engine drain/teardown)."""


class NgramProposer(Proposer):
    """Prompt-lookup drafting (Saxena's prompt-lookup decoding / vLLM's
    ngram speculator): the draft for a request is read out of the
    request's OWN token history — find the most recent earlier
    occurrence of the current suffix n-gram and propose the tokens that
    followed it. Zero extra weights, pure host logic, fully
    CPU-testable; it shines exactly where decode throughput hurts most
    (summarization, code editing, RAG — outputs that re-walk their
    inputs).

    `max_ngram`/`min_ngram` bound the suffix lengths tried (longest
    first — a longer match is a stronger predictor). Among matches of
    the chosen n-gram the scan runs most-recent-first and stops at the
    first one whose continuation fills all k draft slots; when every
    continuation is cut short by the history end (a suffix-overlapping
    cycle like "a b a b a▸"), the longest one wins — recency breaks
    ties. The scan is O(history) per request per step, noise beside a
    compiled model launch.
    """

    def __init__(self, max_ngram: int = 3, min_ngram: int = 1):
        if not 1 <= int(min_ngram) <= int(max_ngram):
            raise ValueError(
                f"need 1 <= min_ngram <= max_ngram, got "
                f"{min_ngram}/{max_ngram}")
        self.max_ngram = int(max_ngram)
        self.min_ngram = int(min_ngram)

    def propose_for(self, tokens, k: int) -> List[int]:
        """Draft up to `k` tokens continuing `tokens` by suffix lookup.
        Returns [] when no suffix n-gram recurs earlier in the history."""
        tokens = list(tokens)
        n_hist = len(tokens)
        if k <= 0 or n_hist < self.min_ngram + 1:
            return []
        for n in range(min(self.max_ngram, n_hist - 1),
                       self.min_ngram - 1, -1):
            suffix = tokens[n_hist - n:]
            best: List[int] = []
            # scan right-to-left: most recent prior occurrence first.
            # cont begins AFTER the matched n-gram and may extend into
            # the suffix region itself — exactly the self-repetition
            # case ngram drafting exploits ("a b a b a b ..." cycles)
            for start in range(n_hist - n - 1, -1, -1):
                if tokens[start:start + n] == suffix:
                    cont = tokens[start + n:start + n + k]
                    if len(cont) == k:
                        return [int(t) for t in cont]
                    if len(cont) > len(best):
                        best = cont
            if best:
                return [int(t) for t in best]
        return []

    def propose(self, reqs, k: int) -> List[List[int]]:
        return [self.propose_for(r.resume_ids, k) for r in reqs]
