"""Typed failure surface of the serving engine.

Every way the engine can refuse or lose work has a distinct type, so
clients and the soak harness can branch on *what* failed instead of
string-matching messages:

* `EngineOverloaded` — admission control shed the request (bounded
  queue); retry-after semantics belong to the caller.
* `TransientDeviceError` — a device/transport error the supervisor
  believes is retryable (UNAVAILABLE, relay loss). Raised internally
  and by fault injection; callers normally never see it because the
  supervisor retries it away.
* `PoisonedComputation` — a deterministic numeric failure (NaN/Inf)
  attributed to specific request(s); subclasses FloatingPointError so
  the existing `utils.nan_inf` contract (dispatch NaN hooks raise
  FloatingPointError) and the supervisor's classifier agree.
* `EngineFailure` — the engine hit an unrecoverable error and drained
  to `snapshot` (see SERVING.md "Failure semantics"); a fresh engine
  resumes from it via `ServingEngine.from_snapshot`.
* `SnapshotVersionError` — a snapshot's schema `version` stamp does not
  match what this engine build writes. Resume and fleet migration must
  fail LOUD on it: silently reinterpreting an old schema would resume
  garbage (wrong deadlines, dropped tokens) instead of crashing.

* `UnsupportedFeature` — a feature COMBINATION this build refuses by
  policy (see `FEATURE_CONFLICTS`, the central capability table).
  Subclasses ValueError so pre-existing callers catching the untyped
  constructor refusals keep working.

Fleet-level errors (replica supervision, routing, tenant fairness) live
in `serving.fleet.errors` — they are failures of the layer ABOVE the
engine.
"""
from __future__ import annotations

from typing import Optional

__all__ = ["EngineOverloaded", "TransientDeviceError",
           "PoisonedComputation", "EngineFailure",
           "SnapshotVersionError", "UnsupportedFeature",
           "FEATURE_CONFLICTS", "check_feature_conflicts"]


class EngineOverloaded(RuntimeError):
    """Admission refused: the bounded waiting queue is full."""

    def __init__(self, msg: str, queue_depth: int = 0,
                 max_queue_len: int = 0):
        super().__init__(msg)
        self.queue_depth = queue_depth
        self.max_queue_len = max_queue_len


class TransientDeviceError(RuntimeError):
    """A retryable device/transport failure (UNAVAILABLE-class)."""


class PoisonedComputation(FloatingPointError):
    """NaN/Inf attributed to a specific computation; `request_ids`
    carries the quarantine targets when the engine can attribute it."""

    def __init__(self, msg: str, request_ids=()):
        super().__init__(msg)
        self.request_ids = tuple(request_ids)


class SnapshotVersionError(ValueError):
    """Snapshot schema mismatch: refuse to resume/migrate it. Subclasses
    ValueError so pre-existing callers that caught the untyped rejection
    keep working; `found` / `expected` carry the version stamps."""

    def __init__(self, msg: str, found=None, expected=None):
        super().__init__(msg)
        self.found = found
        self.expected = expected


class UnsupportedFeature(ValueError):
    """A feature combination this build refuses (capability table hit).
    `features` carries the conflicting pair so callers/routers can
    branch on WHAT conflicted instead of string-matching the reason."""

    def __init__(self, msg: str, features=()):
        super().__init__(msg)
        self.features = tuple(sorted(features))


# The central capability table (ROADMAP item 4): every pairwise feature
# conflict the engine refuses, in ONE place, as
# {frozenset({feature_a, feature_b}): reason}. Feature names are the
# vocabulary `ServingEngine.__init__` derives from its kwargs:
#
#   proposer          speculative decoding (serving.spec)
#   multi_step_decode decode_steps > 1 (ISSUE 13)
#   lora              multi-LoRA adapter serving (ISSUE 15)
#   tensor_parallel   mesh with model-axis degree > 1 (ISSUE 8)
#   host_spill        host_spill_pages > 0 (ISSUE 17)
#   no_prefix_cache   enable_prefix_cache=False
#   prefill_role      role="prefill" (ISSUE 18 disaggregation)
#
# Adding a conflict = adding a row; the engine's single
# `check_feature_conflicts(active)` call enforces all of them. Reasons
# keep the historical phrasing ("mutually exclusive", "not supported
# yet") — callers match on those strings.
FEATURE_CONFLICTS = {
    frozenset({"multi_step_decode", "proposer"}):
        "decode_steps > 1 and a proposer are mutually exclusive: "
        "speculative verify and plain multi-step decode both multiply "
        "tokens per launch — pick one per engine",
    frozenset({"lora", "proposer"}):
        "lora and a proposer are mutually exclusive: the verify "
        "program has no adapter path (pick one per engine)",
    frozenset({"lora", "tensor_parallel"}):
        "lora under tensor parallelism is not supported yet: the "
        "adapter pools/stacks carry no sharding specs (run lora "
        "engines at tp=1)",
    frozenset({"host_spill", "tensor_parallel"}):
        "host spill under tensor parallelism is not supported yet: "
        "page gathers would fetch every shard through the host (run "
        "spill engines at tp=1)",
    frozenset({"host_spill", "no_prefix_cache"}):
        "host_spill_pages needs the radix cache: the spill tier lives "
        "UNDER it (enable_prefix_cache=True)",
    frozenset({"prefill_role", "proposer"}):
        "a prefill-role engine and a proposer are mutually exclusive: "
        "speculative decoding only pays on the decode side, which a "
        "prefill-role engine hands off before reaching",
    frozenset({"prefill_role", "multi_step_decode"}):
        "a prefill-role engine and decode_steps > 1 are mutually "
        "exclusive: multi-step decode only pays on the decode side, "
        "which a prefill-role engine hands off before reaching",
    frozenset({"prefill_role", "no_prefix_cache"}):
        "a prefill-role engine needs the radix cache: handoff ships "
        "the prefilled KV out of the donated radix prefix "
        "(enable_prefix_cache=True)",
}


def check_feature_conflicts(active) -> None:
    """Raise the typed `UnsupportedFeature` for the first capability-
    table row fully contained in `active` (a set of feature names).
    Rows are checked in a deterministic order so the same kwargs always
    produce the same refusal."""
    active = frozenset(active)
    for pair in sorted(FEATURE_CONFLICTS, key=sorted):
        if pair <= active:
            raise UnsupportedFeature(FEATURE_CONFLICTS[pair],
                                     features=pair)


class EngineFailure(RuntimeError):
    """Unrecoverable engine error. `snapshot` is the serializable
    drain state (queued + preempted + in-flight requests)."""

    def __init__(self, msg: str, snapshot: Optional[dict] = None,
                 cause: Optional[BaseException] = None):
        super().__init__(msg)
        self.snapshot = snapshot
        self.cause = cause
