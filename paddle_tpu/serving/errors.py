"""Typed failure surface of the serving engine.

Every way the engine can refuse or lose work has a distinct type, so
clients and the soak harness can branch on *what* failed instead of
string-matching messages:

* `EngineOverloaded` — admission control shed the request (bounded
  queue); retry-after semantics belong to the caller.
* `TransientDeviceError` — a device/transport error the supervisor
  believes is retryable (UNAVAILABLE, relay loss). Raised internally
  and by fault injection; callers normally never see it because the
  supervisor retries it away.
* `PoisonedComputation` — a deterministic numeric failure (NaN/Inf)
  attributed to specific request(s); subclasses FloatingPointError so
  the existing `utils.nan_inf` contract (dispatch NaN hooks raise
  FloatingPointError) and the supervisor's classifier agree.
* `EngineFailure` — the engine hit an unrecoverable error and drained
  to `snapshot` (see SERVING.md "Failure semantics"); a fresh engine
  resumes from it via `ServingEngine.from_snapshot`.
* `SnapshotVersionError` — a snapshot's schema `version` stamp does not
  match what this engine build writes. Resume and fleet migration must
  fail LOUD on it: silently reinterpreting an old schema would resume
  garbage (wrong deadlines, dropped tokens) instead of crashing.

Fleet-level errors (replica supervision, routing, tenant fairness) live
in `serving.fleet.errors` — they are failures of the layer ABOVE the
engine.
"""
from __future__ import annotations

from typing import Optional

__all__ = ["EngineOverloaded", "TransientDeviceError",
           "PoisonedComputation", "EngineFailure",
           "SnapshotVersionError"]


class EngineOverloaded(RuntimeError):
    """Admission refused: the bounded waiting queue is full."""

    def __init__(self, msg: str, queue_depth: int = 0,
                 max_queue_len: int = 0):
        super().__init__(msg)
        self.queue_depth = queue_depth
        self.max_queue_len = max_queue_len


class TransientDeviceError(RuntimeError):
    """A retryable device/transport failure (UNAVAILABLE-class)."""


class PoisonedComputation(FloatingPointError):
    """NaN/Inf attributed to a specific computation; `request_ids`
    carries the quarantine targets when the engine can attribute it."""

    def __init__(self, msg: str, request_ids=()):
        super().__init__(msg)
        self.request_ids = tuple(request_ids)


class SnapshotVersionError(ValueError):
    """Snapshot schema mismatch: refuse to resume/migrate it. Subclasses
    ValueError so pre-existing callers that caught the untyped rejection
    keep working; `found` / `expected` carry the version stamps."""

    def __init__(self, msg: str, found=None, expected=None):
        super().__init__(msg)
        self.found = found
        self.expected = expected


class EngineFailure(RuntimeError):
    """Unrecoverable engine error. `snapshot` is the serializable
    drain state (queued + preempted + in-flight requests)."""

    def __init__(self, msg: str, snapshot: Optional[dict] = None,
                 cause: Optional[BaseException] = None):
        super().__init__(msg)
        self.snapshot = snapshot
        self.cause = cause
