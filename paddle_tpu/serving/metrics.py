"""Serving observability: counters + gauges wired into the profiler.

Two integration points with the existing profiler subsystem:
  * the engine wraps prefill/decode program launches in
    `profiler.RecordEvent` spans, so they land on the host timeline and
    in `Profiler.summary()` like any other op;
  * a ServingMetrics registers itself as a profiler counter provider
    (`profiler.register_counter_provider`), so `Profiler.summary()`
    appends the live serving counters to its table.
"""
from __future__ import annotations

import time
from typing import Dict, Optional

__all__ = ["ServingMetrics"]


class ServingMetrics:
    """Counters/gauges for one ServingEngine."""

    def __init__(self, name: str = "serving"):
        self.name = name
        self.counters: Dict[str, int] = {
            "requests_added": 0,
            "requests_finished": 0,
            "requests_preempted": 0,
            "prefill_tokens": 0,
            "decode_tokens": 0,
            "engine_steps": 0,
            "recompiles": 0,
        }
        self._registered = False
        self._t_start = time.perf_counter()
        self._arrive_t: Dict[int, float] = {}   # in-flight only (popped
        # on finish) — the TTFT record is a running aggregate so a
        # long-lived server doesn't keep a per-request entry forever
        self._ttft_sum = 0.0
        self._ttft_count = 0
        # gauges updated by the engine each step
        self.queue_depth = 0
        self.running = 0
        self.kv_used_pages = 0
        self.kv_occupancy = 0.0

    # ---- event hooks -----------------------------------------------------
    def on_add(self, request_id: int):
        self.counters["requests_added"] += 1
        self._arrive_t[request_id] = time.perf_counter()

    def on_first_token(self, request_id: int):
        # called once per request (the engine guards on num_generated==0)
        t0 = self._arrive_t.get(request_id)
        if t0 is not None:
            self._ttft_sum += time.perf_counter() - t0
            self._ttft_count += 1

    def on_prefill(self, num_tokens: int):
        self.counters["prefill_tokens"] += num_tokens

    def on_decode(self, num_tokens: int):
        self.counters["decode_tokens"] += num_tokens

    def on_finish(self, request_id: int):
        self.counters["requests_finished"] += 1
        self._arrive_t.pop(request_id, None)

    def on_preempt(self):
        self.counters["requests_preempted"] += 1

    def on_step(self):
        self.counters["engine_steps"] += 1

    def on_recompile(self):
        self.counters["recompiles"] += 1

    def update_gauges(self, *, queue_depth, running, kv_used_pages,
                      kv_occupancy):
        self.queue_depth = queue_depth
        self.running = running
        self.kv_used_pages = kv_used_pages
        self.kv_occupancy = kv_occupancy

    # ---- derived ---------------------------------------------------------
    def tokens_per_second(self) -> float:
        dt = time.perf_counter() - self._t_start
        total = self.counters["prefill_tokens"] + self.counters["decode_tokens"]
        return total / dt if dt > 0 else 0.0

    def mean_ttft(self) -> Optional[float]:
        if not self._ttft_count:
            return None
        return self._ttft_sum / self._ttft_count

    def snapshot(self) -> dict:
        snap = dict(self.counters)
        snap.update({
            "queue_depth": self.queue_depth,
            "running": self.running,
            "kv_used_pages": self.kv_used_pages,
            "kv_occupancy": round(self.kv_occupancy, 4),
            "tokens_per_second": round(self.tokens_per_second(), 2),
        })
        ttft = self.mean_ttft()
        if ttft is not None:
            snap["mean_ttft_ms"] = round(ttft * 1e3, 3)
        return snap

    # ---- profiler integration -------------------------------------------
    def register(self):
        """Expose this engine's counters through Profiler.summary()."""
        from .. import profiler
        profiler.register_counter_provider(self.name, self.snapshot)
        self._registered = True
        return self

    def unregister(self):
        if self._registered:
            from .. import profiler
            profiler.unregister_counter_provider(self.name)
            self._registered = False
