"""Serving observability: counters + gauges wired into the profiler.

Two integration points with the existing profiler subsystem:
  * the engine wraps prefill/decode program launches in
    `profiler.RecordEvent` spans, so they land on the host timeline and
    in `Profiler.summary()` like any other op;
  * a ServingMetrics registers itself as a profiler counter provider
    (`profiler.register_counter_provider`), so `Profiler.summary()`
    appends the live serving counters to its table.

Prefix-cache / chunked-prefill observability (ISSUE 2): prefix hit
rate, cached-tokens-served, prefill-tokens-skipped, radix evictions,
prefill chunks, and per-request queue-wait / TTFT percentiles (bounded
reservoirs — a long-lived server keeps the last `PERCENTILE_WINDOW`
samples, not one entry per request ever served).
"""
from __future__ import annotations

import time
from collections import deque
from typing import Dict, Optional

__all__ = ["ServingMetrics"]

PERCENTILE_WINDOW = 1024


# the shared nearest-rank percentile rule (profiler/exposition.py) —
# serving reservoirs and the TrainingMonitor latency ring must agree
from ..profiler.exposition import percentile as _percentile  # noqa: E402


class ServingMetrics:
    """Counters/gauges for one ServingEngine."""

    def __init__(self, name: str = "serving"):
        self.name = name
        self.counters: Dict[str, int] = {
            "requests_added": 0,
            # snapshot-restored intake (fleet migration / from_snapshot)
            # — kept separate from requests_added so fleet-merged
            # counters (dead replicas included) don't double-count a
            # migrated request as two arrivals
            "requests_adopted": 0,
            "requests_finished": 0,
            "requests_preempted": 0,
            "prefill_tokens": 0,
            "decode_tokens": 0,
            "engine_steps": 0,
            "recompiles": 0,
            # --- prefix cache / chunked prefill (ISSUE 2) ---
            "prefill_chunks": 0,           # chunk launches (incl. final)
            "admissions": 0,               # first-chunk admissions
            "prefix_hits": 0,              # admissions with a cache match
            "cached_tokens_served": 0,     # matched tokens reused from cache
            "prefill_tokens_skipped": 0,   # prefill work those tokens saved
            "radix_evicted_pages": 0,
            # --- failure modes (ISSUE 3) ---
            "requests_aborted": 0,         # client abort() honored
            "deadline_expired": 0,         # TTL/deadline cancellations
            "requests_shed": 0,            # EngineOverloaded rejections
            "step_retries": 0,             # transient-failure re-launches
            "requests_quarantined": 0,     # poisoned (NaN) requests failed
            "engine_failures": 0,          # unrecoverable -> snapshot
            # --- quantized KV / weights (ISSUE 6) ---
            # device bytes the KV writes landed / the attention reads
            # streamed (host-computed from token counts x bytes-per-
            # token, scales included) — the capacity-per-chip evidence:
            # at kv_dtype=int8 both drop ~2x for the same token traffic
            "kv_bytes_written": 0,
            "kv_bytes_read": 0,
            # --- speculative decoding (ISSUE 5) ---
            "spec_steps": 0,               # verify launches
            "spec_verified_rows": 0,       # sequence-steps verified
            "spec_drafted_tokens": 0,      # draft tokens scored
            "spec_accepted_tokens": 0,     # drafts that survived verify
            "spec_emitted_tokens": 0,      # tokens emitted by verify steps
            "spec_rollback_tokens": 0,     # rejected-draft KV truncated
            "spec_draft_oom_drops": 0,     # drafts dropped: pool pressure
            # --- multi-step decode (ISSUE 13) ---
            "decode_launches": 0,          # decode-side program launches
            "decode_launch_steps": 0,      # K summed over those launches
            "decode_launch_rows": 0,       # live rows summed over them
            "multi_decode_slot_shortfall": 0,  # K-1 slots the pool denied
            # --- multi-LoRA serving (ISSUE 15) ---
            # registry lifecycle (AdapterRegistry.bind_counters homes
            # them here): loads, explicit unloads, LRU evictions of
            # idle adapters, typed load failures (incl. the injected
            # serving.lora.load_fail fault), evict-race guard refusals
            # (a busy adapter picked for eviction and refused), and
            # requests rejected at the door for naming an unloaded
            # adapter
            "adapters_loaded": 0,
            "adapters_unloaded": 0,
            "adapters_evicted": 0,
            "adapter_load_failures": 0,
            "lora_evict_refusals": 0,
            "adapter_rejects": 0,
            # --- tiered KV: host-RAM spill (ISSUE 17) ---
            # demotion/promotion traffic (radix-synced at the gauge
            # sites, like radix_evicted_pages), the eviction rung taken
            # (demote-to-host vs drop — the spill tier's auditability
            # counters), host-tier hits/drops, fleet prefix pulls, and
            # the three host_spill fault outcomes (bridge-incremented
            # where the degradation happens)
            "kv_pages_demoted": 0,         # device pages spilled to host
            "kv_pages_promoted": 0,        # host pages copied back
            "host_prefix_hits": 0,         # matches that promoted a span
            "host_pages_dropped": 0,       # host-tier LRU/cascade drops
            "radix_evict_demoted": 0,      # eviction rung: demoted
            "radix_evict_dropped": 0,      # eviction rung: dropped
            "kv_pages_exported": 0,        # fleet pull, donor side
            "kv_pages_adopted": 0,         # fleet pull, receiver side
            # --- disaggregated prefill/decode (ISSUE 18) ---
            # prefill-role engines: requests finished "handoff" (pages
            # donated for the fleet's kv_pull) and pages released
            # (demoted-to-coldest or dropped) after the decode side
            # confirmed adoption
            "prefill_handoffs": 0,
            "kv_pages_released": 0,
            "host_spill_corrupt": 0,       # CRC reject -> recompute
            "host_spill_slow": 0,          # deadline miss -> retry later
            "host_spill_lost": 0,          # buffer gone -> recompute
            # --- persistent compile cache (ISSUE 14) ---
            # mirrors of the engine's CompileCache counters (zero with
            # the cache off): hits skipped a trace+compile entirely;
            # rejects are corrupt/stale/mismatched entries that
            # degraded to recompile (counted, never crashing)
            "compile_cache_hits": 0,
            "compile_cache_misses": 0,
            "compile_cache_rejects": 0,
        }
        self._registered = False
        self._t_start = time.perf_counter()
        self._arrive_t: Dict[int, float] = {}   # in-flight only (popped
        # on finish) — aggregates + bounded reservoirs, so a long-lived
        # server doesn't keep a per-request entry forever
        self._ttft_sum = 0.0
        self._ttft_count = 0
        # Named bounded reservoirs, AUTO-exposed by snapshot()/summary()
        # as {name}_p50/_p90/_p99{suffix} — registering one here is all
        # it takes to surface its percentiles, the same no-hand-
        # maintained-key-list contract the counters dict gives new
        # counters (a PR-3 lesson: drift between the metric store and
        # the reporting path is a silent observability bug).
        self._reservoirs: Dict[str, deque] = {}
        self._reservoir_fmt: Dict[str, tuple] = {}   # name -> (scale,
        #                                         suffix, round digits)
        self._ttft_samples = self.add_reservoir("ttft", scale=1e3,
                                                suffix="_ms")
        self._queue_wait_samples = self.add_reservoir("queue_wait",
                                                      scale=1e3,
                                                      suffix="_ms")
        # accepted tokens per verify step (the spec-decode win, per
        # step): mean > 1 is the "speculation pays" signal
        self._accepted_samples = self.add_reservoir("spec_accepted")
        # TPOT: launch wall seconds / tokens emitted by the launch, so
        # the per-token percentiles stay comparable whether a launch
        # emits 1 token (K=1) or K (multi-step decode, ISSUE 13) —
        # coarser launches must not silently inflate the p99s
        self._tpot_samples = self.add_reservoir("tpot", scale=1e3,
                                                suffix="_ms")
        # distinct adapters per decode-side launch (ISSUE 15): the
        # per-launch adapter-mix histogram — p50 > 1 means launches
        # really are heterogeneous (the segment kernel's whole point)
        self._adapter_mix_samples = self.add_reservoir("adapter_mix",
                                                       digits=2)
        # gauges updated by the engine each step
        self.queue_depth = 0
        self.running = 0
        self.kv_used_pages = 0
        self.kv_occupancy = 0.0
        self.cached_pages = 0
        self.radix_nodes = 0
        # static KV-geometry gauges (set once at engine construction)
        self.kv_dtype = None
        self.kv_page_bytes = 0
        self.kv_pool_bytes = 0
        self.kv_bytes_per_token = 0
        # per-shard geometry (ISSUE 8): with a TP mesh the page
        # contents are head-sharded, so one chip pays page_bytes/tp
        # per page; at tp=1 shard == global
        self.kv_tp_degree = 0
        self.kv_page_bytes_shard = 0
        self.kv_pool_bytes_shard = 0
        # host spill tier (ISSUE 17): pool geometry set once at engine
        # construction (set_host_info), occupancy updated per step.
        # host_pool_pages == 0 means no spill tier — the snapshot block
        # is gated on it, so spill-off engines expose nothing new.
        self.host_pool_pages = 0
        self.host_page_bytes = 0
        self.host_pool_bytes = 0
        self.host_pages_used = 0
        self.host_occupancy = 0.0

    # ---- reservoir registry ---------------------------------------------
    def add_reservoir(self, name: str, scale: float = 1.0,
                      suffix: str = "", digits: int = 3) -> deque:
        """Register a bounded percentile reservoir. Returns the deque to
        append raw samples to; snapshot() exposes
        `{name}_p50{suffix}` / p90 / p99 (sample * scale) automatically."""
        d = self._reservoirs.setdefault(
            name, deque(maxlen=PERCENTILE_WINDOW))
        self._reservoir_fmt[name] = (float(scale), suffix, int(digits))
        return d

    def reservoir_percentiles(self, name):
        """{p50, p90, p99} raw-valued over one registered reservoir."""
        return {f"p{q}": _percentile(self._reservoirs.get(name, ()), q)
                for q in (50, 90, 99)}

    # ---- event hooks -----------------------------------------------------
    def on_add(self, request_id: int):
        self.counters["requests_added"] += 1
        self._arrive_t[request_id] = time.perf_counter()

    def on_adopt(self, request_id: int):
        """Snapshot-restored request entering this engine: counted as
        adopted, not added, and with NO arrival stamp — its queue-wait/
        TTFT windows belong to its original admission, not the
        migration."""
        self.counters["requests_adopted"] += 1

    def on_admission(self, request_id: int, cached_tokens: int,
                     resumed: bool = False):
        """First chunk of an admission scheduled. `admissions` and the
        hit accounting count RE-admissions after preemption too (a
        donated prefix turning a resume into a hit is the point);
        the queue-wait sample is taken only for the ORIGINAL admission —
        on a resume the arrival-to-now span includes time already spent
        running, which is not queue wait."""
        self.counters["admissions"] += 1
        if cached_tokens > 0:
            self.counters["prefix_hits"] += 1
            self.counters["cached_tokens_served"] += cached_tokens
            self.counters["prefill_tokens_skipped"] += cached_tokens
        if not resumed:
            t0 = self._arrive_t.get(request_id)
            if t0 is not None:
                self._queue_wait_samples.append(time.perf_counter() - t0)

    def on_first_token(self, request_id: int):
        # called once per request (the engine guards on num_generated==0)
        t0 = self._arrive_t.get(request_id)
        if t0 is not None:
            dt = time.perf_counter() - t0
            self._ttft_sum += dt
            self._ttft_count += 1
            self._ttft_samples.append(dt)

    def on_prefill(self, num_tokens: int):
        self.counters["prefill_tokens"] += num_tokens
        self.counters["prefill_chunks"] += 1

    def on_decode(self, num_tokens: int):
        self.counters["decode_tokens"] += num_tokens

    def on_decode_launch(self, k: int, rows: int, tokens: int,
                         seconds: Optional[float] = None):
        """One decode-side program launch (plain K=1 or multi-step K)
        over `rows` live rows: `tokens` tokens were emitted in
        `seconds` of launch wall time. The TPOT sample divides the
        launch latency by the tokens it emitted — the per-token number
        that stays comparable across K."""
        self.counters["decode_launches"] += 1
        self.counters["decode_launch_steps"] += int(k)
        self.counters["decode_launch_rows"] += int(rows)
        if seconds is not None and seconds > 0 and tokens > 0:
            self._tpot_samples.append(seconds / tokens)

    def on_adapter_mix(self, distinct: int):
        """Distinct adapters (null/base excluded) in one decode-side
        launch — the mixed-batch heterogeneity histogram (ISSUE 15)."""
        self._adapter_mix_samples.append(int(distinct))

    def tokens_per_launch(self) -> Optional[float]:
        """Mean decode tokens emitted per ROW per decode-side launch
        (None before any launch) — 1.0 for plain decode, approaching K
        for multi-step decode at full batch (the >= 0.9 K acceptance
        number; the tail of a draining workload pulls it down when
        rows run out of remaining tokens mid-grid)."""
        if not self.counters["decode_launch_rows"]:
            return None
        return (self.counters["decode_tokens"]
                / self.counters["decode_launch_rows"])

    # ---- quantized KV / weights (ISSUE 6) --------------------------------
    def set_kv_info(self, *, kv_dtype, page_bytes, pool_bytes,
                    bytes_per_token, tp_degree=1, page_bytes_shard=None,
                    pool_bytes_shard=None):
        """Static KV-pool geometry: dtype, bytes/page (scales included),
        total pool bytes, and one token's all-layer K+V footprint —
        page capacity at fixed HBM is pool_bytes / page_bytes, the
        number kv_dtype=int8 roughly doubles. page/pool bytes are
        GLOBAL (summed over TP shards); the per-shard gauges (ISSUE 8)
        record what ONE chip pays — pool_bytes_shard is the per-chip
        `kv_pool_bytes` budget's echo, the number head-sharding holds
        fixed while page capacity scales ~x tp."""
        self.kv_dtype = str(kv_dtype)
        self.kv_page_bytes = int(page_bytes)
        self.kv_pool_bytes = int(pool_bytes)
        self.kv_bytes_per_token = int(bytes_per_token)
        self.kv_tp_degree = int(tp_degree)
        self.kv_page_bytes_shard = int(
            page_bytes if page_bytes_shard is None else page_bytes_shard)
        self.kv_pool_bytes_shard = int(
            pool_bytes if pool_bytes_shard is None else pool_bytes_shard)

    def set_host_info(self, *, pool_pages, page_bytes):
        """Static host-spill-pool geometry (ISSUE 17): slot count and
        the bytes ONE host page carries — a radix page's K+V across
        every layer, scale rows included (num_layers x kv_page_bytes),
        because the demote unit is the whole per-layer stack for one
        device page. pool_pages > 0 is also the snapshot gate for the
        host block, the same role kv_pool_bytes plays for the KV
        geometry block."""
        self.host_pool_pages = int(pool_pages)
        self.host_page_bytes = int(page_bytes)
        self.host_pool_bytes = int(pool_pages) * int(page_bytes)

    def on_kv_bytes(self, written: int = 0, read: int = 0):
        self.counters["kv_bytes_written"] += int(written)
        self.counters["kv_bytes_read"] += int(read)

    def on_finish(self, request_id: int):
        self.counters["requests_finished"] += 1
        self._arrive_t.pop(request_id, None)

    def on_preempt(self):
        self.counters["requests_preempted"] += 1

    # ---- failure-mode hooks (ISSUE 3) -----------------------------------
    def on_abort(self, request_id: int):
        self.counters["requests_aborted"] += 1
        self._arrive_t.pop(request_id, None)

    def on_expire(self, request_id: int):
        self.counters["deadline_expired"] += 1
        self._arrive_t.pop(request_id, None)

    def on_shed(self):
        self.counters["requests_shed"] += 1

    def on_step_retry(self):
        self.counters["step_retries"] += 1

    def on_quarantine(self, request_id: int):
        self.counters["requests_quarantined"] += 1
        self._arrive_t.pop(request_id, None)

    def on_engine_failure(self):
        self.counters["engine_failures"] += 1

    # ---- speculative decoding hooks (ISSUE 5) ---------------------------
    def on_spec_step(self, drafted: int, accepted: int, emitted: int,
                     rolled_back: int, rows: int):
        """One verify launch: `drafted` draft tokens scored, `accepted`
        of them kept, `emitted` tokens emitted in total (accepted + the
        correction/bonus tokens), `rolled_back` rejected-draft tokens
        truncated out of the paged cache, over `rows` verified
        sequences (emitting rows — quarantined rows excluded), so the
        tokens-per-step multiplier normalizes per SEQUENCE, not per
        launch (a full batch would otherwise look like speculation)."""
        self.counters["spec_steps"] += 1
        self.counters["spec_verified_rows"] += rows
        self.counters["spec_drafted_tokens"] += drafted
        self.counters["spec_accepted_tokens"] += accepted
        self.counters["spec_emitted_tokens"] += emitted
        self.counters["spec_rollback_tokens"] += rolled_back
        self._accepted_samples.append(accepted)

    def on_spec_draft_oom(self, dropped: int):
        self.counters["spec_draft_oom_drops"] += dropped

    def on_step(self):
        self.counters["engine_steps"] += 1

    def on_recompile(self):
        self.counters["recompiles"] += 1

    def update_gauges(self, *, queue_depth, running, kv_used_pages,
                      kv_occupancy, cached_pages=0, radix_nodes=0,
                      radix_evicted_pages=None,
                      host_pages_used=None, host_occupancy=None,
                      radix_evict_demoted=None, radix_evict_dropped=None,
                      kv_pages_demoted=None, kv_pages_promoted=None,
                      host_prefix_hits=None, host_pages_dropped=None):
        """None for an optional field means "leave it untouched" — the
        engine passes its radix/spill sync kwargs only when the
        corresponding subsystem exists, so a cache-off or spill-off
        engine can never zero a counter it does not own."""
        self.queue_depth = queue_depth
        self.running = running
        self.kv_used_pages = kv_used_pages
        self.kv_occupancy = kv_occupancy
        self.cached_pages = cached_pages
        self.radix_nodes = radix_nodes
        if radix_evicted_pages is not None:
            self.counters["radix_evicted_pages"] = radix_evicted_pages
        if host_pages_used is not None:
            self.host_pages_used = host_pages_used
        if host_occupancy is not None:
            self.host_occupancy = host_occupancy
        # radix-owned counters synced by assignment (idempotent), the
        # radix_evicted_pages pattern
        for key, val in (("radix_evict_demoted", radix_evict_demoted),
                         ("radix_evict_dropped", radix_evict_dropped),
                         ("kv_pages_demoted", kv_pages_demoted),
                         ("kv_pages_promoted", kv_pages_promoted),
                         ("host_prefix_hits", host_prefix_hits),
                         ("host_pages_dropped", host_pages_dropped)):
            if val is not None:
                self.counters[key] = val

    # ---- derived ---------------------------------------------------------
    def tokens_per_second(self) -> float:
        dt = time.perf_counter() - self._t_start
        total = self.counters["prefill_tokens"] + self.counters["decode_tokens"]
        return total / dt if dt > 0 else 0.0

    def mean_ttft(self) -> Optional[float]:
        if not self._ttft_count:
            return None
        return self._ttft_sum / self._ttft_count

    def prefix_hit_rate(self) -> Optional[float]:
        if not self.counters["admissions"]:
            return None
        return self.counters["prefix_hits"] / self.counters["admissions"]

    def spec_acceptance_rate(self) -> Optional[float]:
        """accepted / drafted over the engine's life (None before any
        draft was scored)."""
        if not self.counters["spec_drafted_tokens"]:
            return None
        return (self.counters["spec_accepted_tokens"]
                / self.counters["spec_drafted_tokens"])

    def spec_tokens_per_step(self) -> Optional[float]:
        """Mean tokens emitted per SEQUENCE per verify launch — the
        spec-decode throughput multiplier (1.0 = speculation never
        paid; the paged-attention launch amortizes over this many
        tokens per sequence)."""
        if not self.counters["spec_verified_rows"]:
            return None
        return (self.counters["spec_emitted_tokens"]
                / self.counters["spec_verified_rows"])

    def ttft_percentiles(self):
        """{p50, p90, p99} seconds over the bounded TTFT window —
        a view over the registered reservoir, so this method and
        snapshot() can never disagree."""
        return self.reservoir_percentiles("ttft")

    def queue_wait_percentiles(self):
        return self.reservoir_percentiles("queue_wait")

    def snapshot(self) -> dict:
        snap = dict(self.counters)
        snap.update({
            "queue_depth": self.queue_depth,
            "running": self.running,
            "kv_used_pages": self.kv_used_pages,
            "kv_occupancy": round(self.kv_occupancy, 4),
            "cached_pages": self.cached_pages,
            "radix_nodes": self.radix_nodes,
            "tokens_per_second": round(self.tokens_per_second(), 2),
        })
        # pool bytes gate the block (not page bytes): a heterogeneous
        # fleet merge zeroes the per-page gauges as sentinels while the
        # pooled bytes stay exact — they must still surface
        if self.kv_page_bytes or self.kv_pool_bytes:
            snap.update({
                "kv_dtype": self.kv_dtype,
                "kv_page_bytes": self.kv_page_bytes,
                "kv_pool_bytes": self.kv_pool_bytes,
                "kv_bytes_per_token": self.kv_bytes_per_token,
                "kv_tp_degree": self.kv_tp_degree,
                "kv_page_bytes_shard": self.kv_page_bytes_shard,
                "kv_pool_bytes_shard": self.kv_pool_bytes_shard,
            })
        # host spill tier (ISSUE 17): gated on a configured pool —
        # merged summaries keep the block when ANY replica spills
        # (pool pages sum; page bytes may sentinel to 0 when mixed)
        if self.host_pool_pages:
            snap.update({
                "host_pool_pages": self.host_pool_pages,
                "host_page_bytes": self.host_page_bytes,
                "host_pool_bytes": self.host_pool_bytes,
                "host_pages_used": self.host_pages_used,
                "host_occupancy": round(self.host_occupancy, 4),
            })
        hr = self.prefix_hit_rate()
        if hr is not None:
            snap["prefix_hit_rate"] = round(hr, 4)
        ar = self.spec_acceptance_rate()
        if ar is not None:
            snap["spec_acceptance_rate"] = round(ar, 4)
        tps = self.spec_tokens_per_step()
        if tps is not None:
            snap["spec_tokens_per_step"] = round(tps, 4)
        tpl = self.tokens_per_launch()
        if tpl is not None:
            snap["decode_tokens_per_launch"] = round(tpl, 4)
        ttft = self.mean_ttft()
        if ttft is not None:
            snap["mean_ttft_ms"] = round(ttft * 1e3, 3)
        # every registered reservoir surfaces its percentiles here — no
        # hand-maintained key list to drift from the registry
        for name, (scale, suffix, digits) in self._reservoir_fmt.items():
            for q, v in self.reservoir_percentiles(name).items():
                if v is not None:
                    snap[f"{name}_{q}{suffix}"] = round(v * scale, digits)
        return snap

    # the reference's Metric objects expose `summary()`; ours is the
    # same auto-exposing view (counters dict + registered reservoirs)
    summary = snapshot

    # ---- Prometheus exposition (ISSUE 10) --------------------------------
    def prometheus_text(self, *, prefix: str = "paddle_serving",
                        labels: Optional[dict] = None,
                        emit_type: bool = True) -> str:
        """This metrics object as Prometheus exposition text — DERIVED
        from `snapshot()` (the renderer walks the live snapshot dict),
        so the scrape can never disagree with it: every counter, gauge
        and registered-reservoir percentile surfaces with no
        hand-maintained name list. Keys in the counters dict are typed
        `counter`, everything else `gauge`."""
        from .exposition import prometheus_lines
        lines = prometheus_lines(self.snapshot(),
                                 counter_keys=set(self.counters),
                                 prefix=prefix, labels=labels,
                                 emit_type=emit_type)
        return "\n".join(lines) + "\n" if lines else ""

    # ---- cross-replica aggregation (fleet, ISSUE 7) ----------------------
    @classmethod
    def merge(cls, *metrics: "ServingMetrics",
              name: str = "fleet") -> "ServingMetrics":
        """Combine per-replica metrics into ONE summary: counters and
        TTFT aggregates sum, every registered percentile reservoir
        merges via a balanced NEWEST-first draw across replicas (still
        bounded by the window — an overflowing union keeps each
        replica's freshest samples instead of letting the last-merged
        replica's window win), count-like gauges sum, and
        kv_occupancy becomes the pooled used/total ratio. The result is
        a live view's worth of state in a fresh UNREGISTERED instance
        (register() it only if it should shadow a real engine in
        Profiler.summary(), which a fleet summary should not).
        tokens_per_second spans the earliest source's start time, so
        the merged rate is fleet throughput, not a division by the
        merge call's age."""
        out = cls(name=name)
        total_pages_used = 0
        total_pages = 0.0
        for m in metrics:
            for k, v in m.counters.items():
                out.counters[k] = out.counters.get(k, 0) + v
            out._ttft_sum += m._ttft_sum
            out._ttft_count += m._ttft_count
            out._t_start = min(out._t_start, m._t_start)
            out.queue_depth += m.queue_depth
            out.running += m.running
            out.kv_used_pages += m.kv_used_pages
            out.cached_pages += m.cached_pages
            out.radix_nodes += m.radix_nodes
            out.kv_pool_bytes += m.kv_pool_bytes
            # pool-weighted occupancy: per-replica page counts recovered
            # from the byte geometry (pool / page bytes)
            if m.kv_page_bytes:
                pages = m.kv_pool_bytes / m.kv_page_bytes
                total_pages += pages
                total_pages_used += m.kv_used_pages
        if total_pages:
            out.kv_occupancy = total_pages_used / total_pages
        # per-page geometry gauges are only meaningful when every
        # source agrees — a heterogeneous fleet gets explicit sentinels
        # instead of whichever replica happened to merge last (pooled
        # kv_pool_bytes / occupancy above stay exact either way)
        pbs = {m.kv_page_bytes for m in metrics if m.kv_page_bytes}
        dts = {m.kv_dtype for m in metrics if m.kv_page_bytes}
        bpts = {m.kv_bytes_per_token for m in metrics if m.kv_page_bytes}
        out.kv_page_bytes = pbs.pop() if len(pbs) == 1 else 0
        out.kv_dtype = dts.pop() if len(dts) == 1 \
            else ("mixed" if dts else None)
        out.kv_bytes_per_token = bpts.pop() if len(bpts) == 1 else 0
        # per-shard geometry (ISSUE 8): same singleton-or-sentinel rule
        # — a fleet mixing TP degrees zeroes the per-shard gauges (and
        # tp_degree) instead of letting the last-merged replica win,
        # while the pooled kv_pool_bytes / occupancy above stay EXACT
        # (both are computed from each replica's own GLOBAL geometry
        # before the sentinel collapse, so mixed-TP pools sum true)
        tps = {m.kv_tp_degree for m in metrics if m.kv_page_bytes}
        pbss = {m.kv_page_bytes_shard for m in metrics if m.kv_page_bytes}
        plss = {m.kv_pool_bytes_shard for m in metrics if m.kv_page_bytes}
        out.kv_tp_degree = tps.pop() if len(tps) == 1 else 0
        out.kv_page_bytes_shard = pbss.pop() if len(pbss) == 1 else 0
        out.kv_pool_bytes_shard = plss.pop() if len(plss) == 1 else 0
        # host spill tier (ISSUE 17): pooled slots/bytes/usage sum EXACT
        # across the replicas that spill (spill-off replicas contribute
        # zeros); occupancy is the pooled used/total ratio; per-page
        # bytes follow the singleton-or-sentinel rule — a heterogeneous
        # fleet (mixed layer counts or kv dtypes) zeroes the gauge
        # instead of letting the last-merged replica win
        out.host_pool_pages = sum(m.host_pool_pages for m in metrics)
        out.host_pool_bytes = sum(m.host_pool_bytes for m in metrics)
        out.host_pages_used = sum(m.host_pages_used for m in metrics)
        if out.host_pool_pages:
            out.host_occupancy = (out.host_pages_used
                                  / out.host_pool_pages)
        hpbs = {m.host_page_bytes for m in metrics if m.host_pool_pages}
        out.host_page_bytes = hpbs.pop() if len(hpbs) == 1 else 0
        # reservoirs: per-name balanced newest-first draw — walk every
        # source from its freshest sample backwards, round-robin, until
        # the window fills; reversed so the merged deque stays
        # oldest->newest like any live reservoir
        fmts = {}
        for m in metrics:
            for rname in m._reservoirs:
                fmts.setdefault(rname, m._reservoir_fmt[rname])
        for rname, (scale, suffix, digits) in fmts.items():
            srcs = [list(m._reservoirs[rname]) for m in metrics
                    if rname in m._reservoirs]
            picked = []
            depth = 1
            while len(picked) < PERCENTILE_WINDOW and \
                    any(depth <= len(s) for s in srcs):
                for s in srcs:
                    if depth <= len(s) and len(picked) < PERCENTILE_WINDOW:
                        picked.append(s[-depth])
                depth += 1
            out.add_reservoir(rname, scale=scale, suffix=suffix,
                              digits=digits).extend(reversed(picked))
        return out

    # ---- profiler integration -------------------------------------------
    def register(self):
        """Expose this engine's counters through Profiler.summary()."""
        from .. import profiler
        profiler.register_counter_provider(self.name, self.snapshot)
        self._registered = True
        return self

    def unregister(self):
        if self._registered:
            from .. import profiler
            profiler.unregister_counter_provider(self.name)
            self._registered = False
