"""Replica worker process (ISSUE 14): one ServingEngine behind the
framed mailbox channel.

`python -m paddle_tpu.serving.fleet.worker --spec <spec.json>` hosts a
single engine and speaks the transport protocol with the supervising
`ProcessFleet` (procfleet.py). The worker is the unit of failure the
cross-process fleet shrinks the blast radius to: a segfault, OOM-kill
or wedged device loop takes down ONE worker process, and the
supervisor adopts its in-flight requests from the last shipped
incremental snapshot with exactly-once token delivery.

Protocol (all messages framed/versioned by transport.py; host->worker
then worker->host):

    adopt {recs}        -> adopted {rids} | reject {rids, error}
    abort {rid}         -> (honored at the next engine boundary)
    ping {}             -> pong {}
    stats {reset_prefix_cache?} -> stats {kv_used_pages, *_ok, ...}
    drain {} / SIGTERM  -> snapshot {final=true}, bye {}; exit 0
    shutdown {}         -> bye {}; exit 0 (no snapshot: discard work)
    kv_pull {pull_id, tokens}      -> kv_prefix {pull_id, tokens,
                                       num_pages, num_chunks} then one
                                       kv_page {pull_id, idx, part,
                                       parts, data} per chunk (ISSUE
                                       17: cached-prefix payloads
                                       chunked under FRAME_CAP)
    kv_prefix/kv_page (incoming)   -> kv_adopted {pull_id,
                                       adopted_pages[, error]} once the
                                       stream completes (same types the
                                       donor emits — the supervisor
                                       relays frames verbatim)
    kv_abort {pull_id}             -> (drop the intake buffer: the
                                       supervisor gave up on this pull;
                                       host-side buffers only, no pages
                                       were allocated yet)
    kv_release {tokens, drop?}     -> (post-handoff hygiene on the
                                       DONOR: demote — or with drop,
                                       free — the shipped radix prefix;
                                       fire-and-forget, no reply)

    ready {pid, geometry}        once, after the engine is built
    events {ev: [[rid,idx,tok]]} after every engine step that emitted
    finish {rid, reason, output_ids}
    prefill_done {rid, output_ids, prefix_len}
                                 a prefill-role engine finished a
                                 request with reason "handoff" (ISSUE
                                 18): first token(s) + the donated
                                 radix prefix length ride up for the
                                 supervisor to drive the kv_pull
    heartbeat {t, steps, load, counters, fired, snapshot}
    failed {snapshot}            EngineFailure; exit 3

Intake is `adopt_requests` (not `add_request`): the SUPERVISOR owns
request ids (they must be unique fleet-wide and survive migration), so
a fresh submit is just the adoption of a record with no output yet.
Token events carry the request-stream INDEX, so the supervisor's
exactly-once funnel can discard duplicated deliveries and re-order
around dropped ones; after a crash-adoption the successor re-emits the
overlap deterministically (greedy + same bucket grid) and the funnel
drops it by index.

Heartbeats ride an incremental snapshot (every non-finished request's
prompt + tokens so far) — that snapshot is what survives a kill -9.
The interval is spec-configurable (`heartbeat_interval_s`), and the
loop clock is injectable for in-process tests (`WorkerLoop(clock=...)`).

On SIGTERM the worker drains to a JSON snapshot on disk
(`snapshot_path`), ships it as the final snapshot message, persists
the compile cache (so its successor skips the bucket-grid compile
storm), and exits 0.

Fault point `worker.kill9` (registered here, fired once per loop
iteration): an armed payload SIGKILLs the worker's own process — the
un-graceful death the chaos soak proves zero-loss against. Module
import stays jax-free; jax/engine imports happen inside `run_worker`.
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time
from collections import deque
from typing import Dict, List, Optional

from ...utils import faults
from .transport import (Channel, chunk_payloads, connect_store,
                        join_payloads)

# The B2 protocol rule cross-checks every message type sent here
# against the supervisor's dispatch (and vice versa):
# tpu-lint-hint: protocol-peer=procfleet.py

__all__ = ["run_worker", "WorkerLoop", "build_model", "build_engine",
           "build_lora_registry", "FAULT_KILL9",
           "FAULT_HANDOFF_PARTIAL", "FAULT_DECODE_REJECT"]

# Fires at the TOP of every worker loop iteration (an engine-boundary,
# so the last shipped heartbeat snapshot is consistent): any payload ->
# os.kill(getpid(), SIGKILL). The process cannot report the firing; the
# supervisor proves it by the -SIGKILL returncode.
FAULT_KILL9 = faults.register_point("worker.kill9")

# ISSUE 18 handoff chaos. `fleet.handoff_partial` fires on the DONOR
# after each kv_page frame sent: any payload -> SIGKILL mid-stream, so
# armed with after=k the prefill worker dies with exactly k of n page
# frames shipped (the receiver's intake never completes; the supervisor
# phase-timeout re-prefills). `fleet.decode_reject` fires at the top of
# the adopt handler: any payload -> refuse the whole batch with a
# typed reject, which the supervisor answers by excluding this worker
# for those rids and re-routing.
FAULT_HANDOFF_PARTIAL = faults.register_point("fleet.handoff_partial")
FAULT_DECODE_REJECT = faults.register_point("fleet.decode_reject")


def build_model(model_spec: dict):
    """Model from a JSON-safe spec: {"kind": "llama", "config": {...},
    "seed": 0} via the registry, or {"factory": "pkg.mod:fn",
    "kwargs": {...}} for anything else. Every worker (and the
    supervisor's in-process baseline) building from the SAME spec gets
    bit-identical weights — `paddle.seed` before construction — which
    is what makes cross-process migration greedy-bit-identical."""
    import paddle_tpu as paddle
    seed = int(model_spec.get("seed", 0))
    paddle.seed(seed)
    if "factory" in model_spec:
        import importlib
        mod, _, fn = model_spec["factory"].partition(":")
        factory = getattr(importlib.import_module(mod), fn)
        return factory(**model_spec.get("kwargs", {}))
    kind = model_spec.get("kind", "llama")
    if kind == "llama":
        from ...models.llama import LlamaConfig, LlamaForCausalLM
        return LlamaForCausalLM(LlamaConfig(**model_spec["config"]))
    if kind == "qwen2_moe":
        from ...models.qwen2_moe import (Qwen2MoeConfig,
                                         Qwen2MoeForCausalLM)
        return Qwen2MoeForCausalLM(Qwen2MoeConfig(**model_spec["config"]))
    raise ValueError(f"unknown model kind {kind!r}")


def _arm_faults(specs: List[dict]):
    """Arm fault points inside THIS worker process from JSON specs
    ({"point", "payload"/"exc_transient", "times", "after", "prob",
    "seed"}) — the registry is per-process, so chaos that must land in
    a worker (kill9, a wedged transport) is armed here, not in the
    supervisor."""
    for fs in specs or []:
        kw = {k: fs[k] for k in ("times", "after", "prob", "seed")
              if k in fs}
        if fs.get("exc_transient"):
            from ...serving.errors import TransientDeviceError
            kw["exc"] = TransientDeviceError(str(fs["exc_transient"]))
        else:
            kw["payload"] = fs.get("payload", True)
        faults.inject(fs["point"], **kw)


class WorkerLoop:
    """The worker's engine-driving loop, factored for in-process tests
    (`run_worker` wires a real store/process around it). One iteration:
    fire kill9, drain channel messages, step the engine when it has
    work, ship emissions/finishes, heartbeat on the (injectable)
    clock."""

    def __init__(self, engine, channel: Channel, *,
                 heartbeat_interval_s: float = 0.05, clock=None,
                 snapshot_path: Optional[str] = None):
        self.engine = engine
        self.chan = channel
        self.hb_interval = float(heartbeat_interval_s)
        self.clock = clock if clock is not None else time.monotonic
        self.snapshot_path = snapshot_path
        self.live: set = set()               # rids being generated
        self.sent_counts: Dict[int, int] = {}   # rid -> next event index
        # last finished requests, re-shipped with every heartbeat: a
        # finish frame lost on the wire (transport.drop/stall) would
        # otherwise strand its handle live forever on the supervisor —
        # re-delivery is idempotent there (finalize checks finished)
        self.recent_finished: deque = deque(maxlen=64)
        # handoff completions (ISSUE 18) ride a SEPARATE deque: a
        # prefill_done lost on the wire must be healed by heartbeat
        # re-delivery like a finish, but it must NOT enter
        # recent_finished — the supervisor finalizes those handles,
        # while a handoff's handle stays live until the decode side
        # finishes it. The supervisor dedups re-deliveries by rid.
        self.recent_handoffs: deque = deque(maxlen=64)
        # in-flight cross-worker prefix pulls, RECEIVER side (ISSUE 17):
        # pull_id -> {tokens, num_chunks, chunks} until the stream
        # completes and the pages adopt
        self._kv_intake: Dict = {}
        self.steps = 0
        self.heartbeats = 0
        self.draining = False
        self.shutdown = False
        self._last_beat = -1e9

    # ---- message handling ------------------------------------------------
    def handle(self, msg: dict):
        mtype = msg.get("type")
        payload = msg.get("payload", {})
        if mtype == "adopt":
            if faults.fire(FAULT_DECODE_REJECT) is not None:
                rids = [int(rec["request_id"])
                        for rec in payload.get("recs", [])]
                if rids:
                    self.chan.send("reject", rids=rids,
                                   error="decode_reject fault armed")
                return
            # one rec at a time: a batch adopt that failed mid-way
            # would leave the already-restored records running in this
            # engine while the supervisor re-lands them elsewhere —
            # the same request generating on two workers at once.
            # Per-rec adoption gives exact partial-success semantics:
            # only the records that actually failed are rejected.
            adopted, failed, last_err = [], [], ""
            for rec in payload.get("recs", []):
                rid = int(rec["request_id"])
                try:
                    self.engine.adopt_requests([rec])
                except Exception as e:                    # noqa: BLE001
                    failed.append(rid)
                    last_err = f"{type(e).__name__}: {e}"[:300]
                    continue
                self.live.add(rid)
                # the supervisor already holds rec's tokens: events
                # index from there, so re-emitted overlap after a
                # crash-adoption dedups by index at the funnel
                self.sent_counts[rid] = len(rec.get("output_ids", []))
                adopted.append(rid)
            if adopted:
                self.chan.send("adopted", rids=adopted)
            if failed:
                self.chan.send("reject", rids=failed, error=last_err)
        elif mtype == "abort":
            self.engine.abort(int(payload["rid"]))
        elif mtype == "ping":
            self.chan.send("pong")
        elif mtype == "stats":
            # reclamation probe (the soak's full-reclamation check):
            # optionally drop the prefix cache, then report pool state
            # + invariant results
            eng = self.engine
            out = {}
            if eng.radix is not None:
                try:
                    eng.radix.check_invariants()
                    out["radix_ok"] = True
                except Exception as e:                    # noqa: BLE001
                    out["radix_ok"] = False
                    out["radix_err"] = str(e)[:200]
            if payload.get("reset_prefix_cache"):
                eng.reset_prefix_cache()
            try:
                eng.allocator.check_invariants()
                out["allocator_ok"] = True
            except Exception as e:                        # noqa: BLE001
                out["allocator_ok"] = False
                out["allocator_err"] = str(e)[:200]
            out["kv_used_pages"] = int(eng.allocator.num_used)
            out["queue_depth"] = int(eng.scheduler.queue_depth)
            out["num_compiled_programs"] = eng.num_compiled_programs
            self.chan.send("stats", **out)
        elif mtype == "kv_pull":
            # cross-worker prefix pull, DONOR side (ISSUE 17): the
            # longest device-resident cached prefix of `tokens` as the
            # spill codec's CRC'd page payloads, chunked under the
            # frame cap. The response (kv_prefix header + kv_page
            # stream) uses the SAME message types the receiver side
            # adopts from, so a supervisor routes pulls by relaying
            # frames verbatim between its worker channels.
            tokens = [int(t) for t in payload.get("tokens", [])]
            pull_id = payload.get("pull_id", 0)
            n, payloads = self.engine.export_prefix(tokens)
            chunks = chunk_payloads(payloads)
            self.chan.send("kv_prefix", pull_id=pull_id,
                           tokens=tokens[:n], num_pages=len(payloads),
                           num_chunks=len(chunks))
            for ch in chunks:
                self.chan.send("kv_page", pull_id=pull_id, **ch)
                if faults.fire(FAULT_HANDOFF_PARTIAL) is not None:
                    # die -9 with only part of the stream shipped: the
                    # chaos case the handoff state machine must survive
                    os.kill(os.getpid(), signal.SIGKILL)
        elif mtype == "kv_abort":
            # supervisor gave up on this pull (timeout/death): drop the
            # intake buffer. Host-side dicts only — no KV pages were
            # allocated before adoption, so nothing can leak.
            self._kv_intake.pop(payload.get("pull_id", 0), None)
        elif mtype == "kv_release":
            # DONOR-side release after the decode worker confirmed
            # adoption (handoff phase 4): demote (default) or drop the
            # shipped prefix so it becomes the coldest eviction victim
            # instead of squatting on the pool
            try:
                self.engine.release_prefix(
                    [int(t) for t in payload.get("tokens", [])],
                    drop=bool(payload.get("drop", False)))
            except Exception:                             # noqa: BLE001
                pass    # hygiene only — never kill the worker over it
        elif mtype == "kv_prefix":
            # RECEIVER side: open the intake buffer (an empty pull —
            # the donor held nothing — completes immediately)
            pull_id = payload.get("pull_id", 0)
            self._kv_intake[pull_id] = {
                "tokens": [int(t) for t in payload.get("tokens", [])],
                "num_chunks": int(payload.get("num_chunks", 0)),
                "chunks": []}
            self._maybe_adopt_pull(pull_id)
        elif mtype == "kv_page":
            buf = self._kv_intake.get(payload.get("pull_id", 0))
            if buf is not None:
                buf["chunks"].append(
                    {k: payload[k]
                     for k in ("idx", "part", "parts", "data")})
                self._maybe_adopt_pull(payload.get("pull_id", 0))
        elif mtype == "drain":
            self.draining = True
        elif mtype == "shutdown":
            self.shutdown = True

    def _maybe_adopt_pull(self, pull_id):
        """Adopt a completed kv pull stream into the local engine. A
        bad pull (reassembly gap, corrupt payload, dry pool) reports
        adopted_pages=0 — the prefix just recomputes locally, the
        spill tier's usual fallback; it must never kill the worker."""
        buf = self._kv_intake.get(pull_id)
        if buf is None or len(buf["chunks"]) < buf["num_chunks"]:
            return
        del self._kv_intake[pull_id]
        err = None
        adopted = 0
        try:
            payloads = join_payloads(buf["chunks"])
            adopted = self.engine.adopt_prefix(buf["tokens"], payloads)
        except Exception as e:                            # noqa: BLE001
            err = f"{type(e).__name__}: {e}"[:200]
        out = {"pull_id": pull_id, "adopted_pages": int(adopted)}
        if err:
            out["error"] = err
        self.chan.send("kv_adopted", **out)

    # ---- emission shipping -----------------------------------------------
    def _ship(self, emitted):
        from ..scheduler import RequestState
        if emitted:
            ev = []
            for rid, tok in emitted:
                idx = self.sent_counts.get(rid, 0)
                self.sent_counts[rid] = idx + 1
                ev.append([int(rid), int(idx), int(tok)])
            self.chan.send("events", ev=ev)
        for rid in sorted(self.live):
            req = self.engine.requests.get(rid)
            if req is None or req.state is RequestState.FINISHED:
                self.live.discard(rid)
                self.sent_counts.pop(rid, None)
                if req is not None and req.finish_reason == "handoff":
                    # prefill-role completion (ISSUE 18): the request
                    # is NOT finished fleet-wide — ship the prefill
                    # result up for the supervisor to drive the
                    # kv_pull + decode-side adoption
                    ho = {"rid": int(rid),
                          "output_ids": [int(t)
                                         for t in req.output_ids],
                          "prefix_len": int(req.handoff_prefix_len)}
                    self.recent_handoffs.append(ho)
                    self.chan.send("prefill_done", **ho)
                    continue
                fin = {"rid": int(rid),
                       "reason": (req.finish_reason if req is not None
                                  else "lost"),
                       "output_ids": ([int(t) for t in req.output_ids]
                                      if req is not None else [])}
                self.recent_finished.append(fin)
                self.chan.send("finish", **fin)

    def heartbeat(self, force: bool = False):
        now = self.clock()
        if not force and now - self._last_beat < self.hb_interval:
            return False
        self._last_beat = now
        self.heartbeats += 1
        s = self.engine.scheduler
        self.chan.send(
            "heartbeat", t=float(now), steps=self.steps,
            load=int(s.num_in_flight + s.queue_depth),
            counters=self.engine.metrics.snapshot(),
            fired=faults.fired_counts(),
            # no flight recorder on the 20 Hz path: the supervisor only
            # reads the request records; postmortem context rides the
            # drain/failure snapshots
            snapshot=self.engine.snapshot(reason="heartbeat",
                                          include_recorder=False),
            recent_finished=list(self.recent_finished),
            recent_handoffs=list(self.recent_handoffs))
        return True

    # ---- lifecycle -------------------------------------------------------
    def drain_to_snapshot(self) -> dict:
        """Graceful exit: snapshot everything non-finished, write it to
        disk (the SIGTERM contract), persist the compile cache, ship
        the final snapshot + bye."""
        snap = self.engine.snapshot(reason="drain")
        if self.snapshot_path:
            try:
                os.makedirs(os.path.dirname(self.snapshot_path)
                            or ".", exist_ok=True)
                with open(self.snapshot_path, "w") as f:
                    json.dump(snap, f)
            except OSError:
                pass        # disk trouble must not block the handoff
        # ship the handoff FIRST: save_compile_cache re-lowers AOT per
        # new entry (seconds each on a cold cache) and a worker cannot
        # heartbeat mid-save — the supervisor must already hold the
        # final snapshot if its hard-stall ladder loses patience
        self.chan.send("snapshot", final=True, snapshot=snap)
        saved = 0
        try:
            saved = self.engine.save_compile_cache()
        except Exception:                                 # noqa: BLE001
            pass            # cache persistence is best-effort
        self.chan.send("bye", fired=faults.fired_counts(),
                       cache_saved=saved)
        return snap

    def step_once(self) -> bool:
        """One loop iteration; returns True while the loop should
        continue."""
        if faults.fire(FAULT_KILL9) is not None:
            os.kill(os.getpid(), signal.SIGKILL)
        for msg in self.chan.recv_all():
            self.handle(msg)
        if self.shutdown:
            self.chan.send("bye", fired=faults.fired_counts())
            return False
        if self.draining:
            self.drain_to_snapshot()
            return False
        if self.engine.has_work():
            emitted = self.engine.step()
            self.steps += 1
            self._ship(emitted)
        else:
            time.sleep(2e-3)
        self.heartbeat()
        return True


def build_lora_registry(model, lora_spec: dict):
    """AdapterRegistry from a JSON-safe spec (ISSUE 15):
    {"rank_buckets": [8], "slots": 8, "adapters": [{"name", "rank",
    "seed", "quant"}]} loads seed-deterministic adapters — every worker
    building from the SAME spec holds bit-identical adapter weights
    (the `build_model` discipline), which is what makes cross-process
    migration of adapter'd requests greedy-bit-identical — or
    {"factory": "pkg.mod:fn", "kwargs": {...}} for real checkpoints."""
    if "factory" in lora_spec:
        import importlib
        mod, _, fn = lora_spec["factory"].partition(":")
        return getattr(importlib.import_module(mod), fn)(
            model, **lora_spec.get("kwargs", {}))
    from ..lora import AdapterRegistry, LoRAAdapter
    from ..lora.store import llama_lora_dims
    dims = llama_lora_dims(model.cfg)
    reg = AdapterRegistry(
        dims,
        rank_buckets=tuple(lora_spec.get("rank_buckets", (8,))),
        slots=int(lora_spec.get("slots", 8)))
    for ad in lora_spec.get("adapters", ()):
        reg.load(LoRAAdapter.random(ad["name"],
                                    int(ad.get("rank", 8)), dims,
                                    seed=int(ad.get("seed", 0))),
                 quant=ad.get("quant"))
    return reg


def build_engine(spec: dict):
    """(model, engine) from a worker spec — factored from `run_worker`
    so the spec plumbing (incl. the ISSUE-15 `lora` block) is testable
    in-process."""
    from ..engine import ServingEngine
    model = build_model(spec["model"])
    engine_kw = dict(spec.get("engine", {}))
    if spec.get("compile_cache_dir"):
        engine_kw["compile_cache"] = spec["compile_cache_dir"]
    if spec.get("lora"):
        engine_kw["lora"] = build_lora_registry(model, spec["lora"])
    return model, ServingEngine(model, **engine_kw)


def run_worker(spec: dict) -> int:
    """Worker process entry: build engine + channel from `spec`, then
    loop until drained/shut down. Returns the exit code."""
    import jax
    jax.config.update("jax_platforms", spec.get("platform", "cpu"))
    from ..errors import EngineFailure

    model, engine = build_engine(spec)

    store = connect_store(spec["endpoint"],
                          timeout_ms=int(spec.get("connect_timeout_ms",
                                                  60000)))
    chan = Channel(store, me=spec["name"], peer="host",
                   session=spec.get("session", "s0"))
    _arm_faults(spec.get("faults"))
    loop = WorkerLoop(
        engine, chan,
        heartbeat_interval_s=float(spec.get("heartbeat_interval_s",
                                            0.05)),
        snapshot_path=spec.get("snapshot_path"))

    # SIGTERM = deliberate eviction (rolling restart / scale-down):
    # flip to draining so the NEXT boundary snapshots and exits — the
    # handler itself must not touch the engine mid-step
    signal.signal(signal.SIGTERM, lambda *_: setattr(loop, "draining",
                                                     True))

    chan.send("ready", pid=os.getpid(),
              geometry={"max_seq_len": engine.max_seq_len,
                        "num_pages": engine.num_pages,
                        "compile_cache": bool(engine.compile_cache)})
    loop.heartbeat(force=True)
    try:
        while loop.step_once():
            pass
    except EngineFailure as exc:
        chan.send("failed",
                  snapshot=(exc.snapshot
                            if exc.snapshot is not None
                            else engine.last_snapshot))
        return 3
    except Exception as exc:                              # noqa: BLE001
        # anything else is a worker bug: ship what we know and die loud
        try:
            chan.send("failed",
                      snapshot=engine.snapshot(
                          reason=f"worker crash: {exc!r}"[:200]))
        except Exception:                                 # noqa: BLE001
            pass
        return 4
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="paddle_tpu serving fleet worker process")
    ap.add_argument("--spec", required=True,
                    help="path to the worker spec JSON")
    args = ap.parse_args(argv)
    with open(args.spec) as f:
        spec = json.load(f)
    return run_worker(spec)


if __name__ == "__main__":
    sys.exit(main())
