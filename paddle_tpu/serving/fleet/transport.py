"""Framed, versioned mailbox transport for the cross-process fleet
(ISSUE 14).

The wire is the native TCPStore that already carries `dist.send/recv`
p2p (round 4, `PADDLE_P2P_STORE`): one process binds the store, every
peer connects as a client, and a **Channel** between two peers is a
pair of sequence-numbered key streams inside it —

    ptw/<session>/<src)>(dst>/head   monotonically allocated via add()
    ptw/<session>/<src)>(dst>/<seq>  one framed message per key

Ordered, at-most-once-per-seq delivery falls out of the store: send()
allocates the next seq with an atomic add and writes the frame; recv()
polls its next expected seq (capped exponential backoff between polls,
per-call timeout), deletes the key it consumed, and advances. Nothing
here blocks without a deadline.

**Framing.** Every message is one frame:

    MAGIC "PTW1" | u8 version | u32 body_len | u32 crc32(body) | body

with a JSON body (the envelope: type/src/dst/seq/payload). A frame that
fails the magic, version, length, or checksum raises a typed
`TransportError` — version/framing mismatches are FATAL (a rolling
restart mixing incompatible builds must fail loud), connect/timeout
losses are TRANSIENT. The error carries `failure_class`, which the
engine supervisor's `classify_failure` (PR 3) consults first, so
transport failures route through the same transient/poison/fatal
machinery as device launches.

**Fault points** (armed by the soak; table in SERVING.md):

* `transport.drop`      — recv reads a frame and DISCARDS it, as if the
  network ate the message (recovery = the heartbeat snapshot path);
* `transport.duplicate` — recv delivers the same message twice (the
  exactly-once token funnel must dedup — asserted over the wire);
* `transport.stall`     — the channel wedges for this call: recv reads
  nothing even when messages are pending, send silently writes nothing
  (returns -1). Armed with times=-1 it models a permanently wedged
  endpoint — from outside, indistinguishable from a hung process: no
  heartbeats out, no commands in, until the supervisor's hard-stall
  ladder kills and adopts. Finite specs consume firings at BOTH sites.

This module is importable without jax: the store object is injected
(ducked-typed set/get/add/delete_key), and `bind_store`/`connect_store`
import the native extension lazily.
"""
from __future__ import annotations

import base64
import json
import struct
import time
import zlib
from typing import Any, Dict, List, Optional

from ...utils import faults

__all__ = ["TransportError", "Channel", "encode_frame", "decode_frame",
           "bind_store", "connect_store", "free_port",
           "TRANSPORT_VERSION", "FRAME_CAP", "chunk_payloads",
           "join_payloads", "FAULT_DROP", "FAULT_DUPLICATE",
           "FAULT_STALL"]

MAGIC = b"PTW1"
TRANSPORT_VERSION = 1
_HEADER = struct.Struct(">4sBII")          # magic, version, len, crc32

# Largest JSON body one frame may carry. Store values ride a single
# set(); a KV page payload (num_layers x kv_page_bytes, megabytes at
# real geometry) must be split across frames BELOW this, not shipped as
# one giant value that stalls every other mailbox key behind it.
FRAME_CAP = 256 * 1024

# sentinel: a seq was consumed without yielding a message
_CONSUMED = object()

# Registered here (the module every transport endpoint imports), fired
# at the RECV site so drop/duplicate/stall model the network without
# corrupting the seq stream: a dropped frame is consumed-and-discarded,
# a duplicate is delivered twice, a stall reads nothing this call.
FAULT_DROP = faults.register_point("transport.drop")
FAULT_DUPLICATE = faults.register_point("transport.duplicate")
FAULT_STALL = faults.register_point("transport.stall")


class TransportError(RuntimeError):
    """A transport failure with an explicit supervisor classification:
    `failure_class` is "transient" (connect/timeout/store loss —
    retry/backoff is sane) or "fatal" (framing/version mismatch —
    retrying re-reads the same garbage). `classify_failure` consults
    the attribute before any of its own heuristics."""

    def __init__(self, msg: str, failure_class: str = "transient"):
        super().__init__(msg)
        self.failure_class = failure_class


def encode_frame(msg: dict) -> bytes:
    body = json.dumps(msg, separators=(",", ":")).encode("utf-8")
    return _HEADER.pack(MAGIC, TRANSPORT_VERSION, len(body),
                        zlib.crc32(body)) + body


def decode_frame(data: bytes) -> dict:
    """Decode one frame; every rejection is typed and names what broke
    (the compile-cache loader shares this fail-loud-but-classified
    discipline). Truncated/corrupt frames are TRANSIENT (a half-written
    store value may be re-sent); a version mismatch is FATAL."""
    if len(data) < _HEADER.size:
        raise TransportError(
            f"short frame: {len(data)} < header {_HEADER.size}")
    magic, version, body_len, crc = _HEADER.unpack_from(data)
    if magic != MAGIC:
        raise TransportError(f"bad frame magic {magic!r}",
                             failure_class="fatal")
    if version != TRANSPORT_VERSION:
        raise TransportError(
            f"transport version {version} != {TRANSPORT_VERSION} "
            f"(mixed incompatible builds in one fleet)",
            failure_class="fatal")
    body = data[_HEADER.size:]
    if len(body) != body_len:
        raise TransportError(
            f"frame length {len(body)} != declared {body_len}")
    if zlib.crc32(body) != crc:
        raise TransportError("frame checksum mismatch")
    try:
        return json.loads(body.decode("utf-8"))
    except Exception as e:                                # noqa: BLE001
        raise TransportError(f"frame body undecodable: {e}") from e


def chunk_payloads(payloads: List[bytes],
                   cap: int = FRAME_CAP) -> List[dict]:
    """Binary KV page payloads (the spill tier's CRC'd codec, ISSUE 17)
    -> JSON-safe chunk dicts, each frame body under `cap`. A chunk is
    {"idx": page, "part": j, "parts": n, "data": base64} — idx/part/
    parts let `join_payloads` reassemble each page independently and
    detect gaps, so a relayed stream may interleave pulls freely."""
    # base64 grows 3 -> 4; leave slack for the JSON envelope around it
    raw_cap = max(1, (int(cap) * 3) // 4 - 512)
    chunks = []
    for idx, blob in enumerate(payloads):
        blob = bytes(blob)
        parts = max(1, -(-len(blob) // raw_cap))
        for part in range(parts):
            piece = blob[part * raw_cap:(part + 1) * raw_cap]
            chunks.append({
                "idx": idx, "part": part, "parts": parts,
                "data": base64.b64encode(piece).decode("ascii")})
    return chunks


def join_payloads(chunks: List[dict]) -> List[bytes]:
    """Reassemble `chunk_payloads` output (any order). Missing pages or
    parts, duplicate parts, or inconsistent part counts raise a
    TRANSIENT TransportError — a re-pull heals; byte-level corruption
    is the payload codec's CRC to catch, not ours."""
    pages: Dict[int, Dict[int, bytes]] = {}
    declared: Dict[int, int] = {}
    for ch in chunks:
        try:
            idx, part = int(ch["idx"]), int(ch["part"])
            parts = int(ch["parts"])
            data = base64.b64decode(ch["data"], validate=True)
        except Exception as e:                            # noqa: BLE001
            raise TransportError(f"undecodable kv chunk: {e}") from e
        if declared.setdefault(idx, parts) != parts:
            raise TransportError(
                f"kv page {idx}: inconsistent part counts "
                f"{declared[idx]} != {parts}")
        if part in pages.setdefault(idx, {}):
            raise TransportError(f"kv page {idx}: duplicate part {part}")
        pages[idx][part] = data
    if set(pages) != set(range(len(pages))):
        raise TransportError(
            f"kv pull missing pages: have {sorted(pages)}")
    out = []
    for idx in range(len(pages)):
        if set(pages[idx]) != set(range(declared[idx])):
            raise TransportError(
                f"kv page {idx}: missing parts "
                f"{sorted(set(range(declared[idx])) - set(pages[idx]))}")
        out.append(b"".join(pages[idx][p]
                            for p in range(declared[idx])))
    return out


def free_port() -> int:
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def bind_store(endpoint: str):
    """Create/bind the master TCPStore at `endpoint` (host side).
    Lazy native import — this module stays importable without jax."""
    from ...distributed.env import create_store
    return create_store(endpoint, rank=0)


def connect_store(endpoint: str, timeout_ms: int = 120000):
    """Connect to an existing store as a client (worker side)."""
    from ...distributed.env import create_store
    return create_store(endpoint, rank=1, timeout_ms=timeout_ms)


class Channel:
    """One directed pair of mailbox streams between `me` and `peer`.

    send(type, **payload) frames and writes one message on the
    me->peer stream. recv(timeout_s) returns the next message from the
    peer->me stream (None on timeout); recv_all() drains everything
    currently available without sleeping. Store losses surface as
    transient `TransportError`s after `max_attempts` capped-backoff
    retries of the failing store call."""

    def __init__(self, store, me: str, peer: str, *,
                 session: str = "s0", poll_s: float = 5e-4,
                 poll_cap_s: float = 0.02, max_attempts: int = 5,
                 backoff_s: float = 0.01, sleep=None):
        self.store = store
        self.me = str(me)
        self.peer = str(peer)
        self.session = str(session)
        self.poll_s = float(poll_s)
        self.poll_cap_s = float(poll_cap_s)
        self.max_attempts = int(max_attempts)
        self.backoff_s = float(backoff_s)
        self._sleep = sleep if sleep is not None else time.sleep
        self._next_recv = 1                 # next expected peer seq
        self._pending: List[dict] = []      # duplicate-fault replays
        # seq-hole repair: a sender that died (or exhausted its set()
        # retries) between allocating a seq and writing its frame
        # leaves a PERMANENT hole — the reader would poll it forever
        # while later messages pile up behind. When the peer's head
        # counter is past our cursor but the key stays absent for
        # `hole_timeout_s`, the seq is skipped and counted (equivalent
        # to a dropped frame; the snapshot/recent-finished machinery
        # heals the content).
        self.hole_timeout_s = 2.0
        self._hole_first_miss: Optional[float] = None
        self.counters: Dict[str, int] = {
            "sent": 0, "received": 0, "dropped": 0, "duplicated": 0,
            "stalls": 0, "undecodable": 0, "store_retries": 0,
            "holes_skipped": 0}

    # ---- key naming ------------------------------------------------------
    def _key(self, src: str, dst: str, seq: int) -> str:
        return f"ptw/{self.session}/{src}>{dst}/{seq}"

    def _head(self, src: str, dst: str) -> str:
        return f"ptw/{self.session}/{src}>{dst}/head"

    # ---- guarded store IO ------------------------------------------------
    def _store_call(self, what: str, fn, *args):
        """One store operation with capped exponential backoff over
        connection-class failures; exhaustion raises the TRANSIENT
        TransportError the supervisor machinery retries/classifies."""
        last = None
        for attempt in range(self.max_attempts):
            try:
                return fn(*args)
            except Exception as e:                        # noqa: BLE001
                last = e
                self.counters["store_retries"] += 1
                self._sleep(min(1.0, self.backoff_s * (2 ** attempt)))
        raise TransportError(
            f"store {what} failed after {self.max_attempts} attempts: "
            f"{last}") from last

    # ---- send/recv -------------------------------------------------------
    def send(self, type: str, **payload) -> int:
        """Frame and write one message; returns its sequence number
        (-1 when an armed `transport.stall` wedged the write — the
        message is silently lost, exactly like a hung sender)."""
        if faults.fire(FAULT_STALL) is not None:
            self.counters["stalls"] += 1
            return -1
        seq = int(self._store_call(
            "add", self.store.add, self._head(self.me, self.peer), 1))
        msg = {"type": str(type), "src": self.me, "dst": self.peer,
               "seq": seq, "payload": payload}
        self._store_call("set", self.store.set,
                         self._key(self.me, self.peer, seq),
                         encode_frame(msg))
        self.counters["sent"] += 1
        return seq

    def relay(self, msg: dict) -> int:
        """Re-send a RECEIVED message (same type + payload) down this
        channel verbatim — the supervisor forwarding a donor's
        `kv_prefix`/`kv_page` stream to the adopting decode worker
        (ISSUE 18). A fresh seq on this stream is allocated; src/dst
        are rewritten to this channel's endpoints."""
        return self.send(msg["type"], **msg.get("payload", {}))

    def _read_next(self):
        """Non-blocking: the next pending message, None when the
        stream is empty (or stalled), or `_CONSUMED` when a seq was
        consumed without yielding a message (dropped by fault, or a
        corrupt frame skipped) — readers keep draining past those."""
        if self._pending:
            return self._pending.pop(0)
        if faults.fire(FAULT_STALL) is not None:
            self.counters["stalls"] += 1
            return None
        key = self._key(self.peer, self.me, self._next_recv)
        data = self._store_call("get", self.store.get, key, False)
        if data is None:
            head = int(self._store_call(
                "head", self.store.add,
                self._head(self.peer, self.me), 0))
            if head < self._next_recv:
                self._hole_first_miss = None    # truly nothing sent yet
                return None
            # the peer allocated this seq but its frame is missing: a
            # hole until proven otherwise (the write may simply be in
            # flight — give it hole_timeout_s)
            now = time.monotonic()
            if self._hole_first_miss is None:
                self._hole_first_miss = now
                return None
            if now - self._hole_first_miss < self.hole_timeout_s:
                return None
            self.counters["holes_skipped"] += 1
            self._hole_first_miss = None
            self._next_recv += 1
            return _CONSUMED
        self._hole_first_miss = None
        self._next_recv += 1
        try:
            self._store_call("delete", self.store.delete_key, key)
        except TransportError:
            pass   # losing the delete only leaves a stale key behind
        try:
            msg = decode_frame(bytes(data))
        except TransportError as e:
            if e.failure_class == "fatal":
                raise
            self.counters["undecodable"] += 1
            return _CONSUMED     # corrupt frame: count and skip it
        if faults.fire(FAULT_DROP) is not None:
            self.counters["dropped"] += 1
            return _CONSUMED
        if faults.fire(FAULT_DUPLICATE) is not None:
            self.counters["duplicated"] += 1
            self._pending.append(dict(msg))
        self.counters["received"] += 1
        return msg

    def recv(self, timeout_s: float = 0.0) -> Optional[dict]:
        """Next message, waiting up to `timeout_s` (0 = one poll).
        Returns None on timeout — callers own their liveness policy."""
        deadline = time.monotonic() + float(timeout_s)
        delay = self.poll_s
        while True:
            msg = self._read_next()
            if msg is _CONSUMED:
                continue            # a seq was eaten; look again now
            if msg is not None:
                return msg
            if time.monotonic() >= deadline:
                return None
            self._sleep(delay)
            delay = min(self.poll_cap_s, delay * 2)

    def recv_all(self, limit: int = 1024) -> List[dict]:
        """Drain every currently-available message (bounded)."""
        out = []
        n = 0
        while n < limit:
            msg = self._read_next()
            if msg is None:
                break
            n += 1
            if msg is not _CONSUMED:
                out.append(msg)
        return out

    def purge(self):
        """Best-effort deletion of every outstanding frame + both head
        keys of this channel (shutdown hygiene: frames a dead peer
        never consumed would otherwise sit in the store for its
        lifetime). Never raises — the store may already be gone."""
        for src, dst, start in ((self.me, self.peer, 1),
                                (self.peer, self.me, self._next_recv)):
            try:
                head = int(self.store.add(self._head(src, dst), 0))
                for seq in range(start, head + 1):
                    try:
                        self.store.delete_key(self._key(src, dst, seq))
                    except Exception:                     # noqa: BLE001
                        pass
                self.store.delete_key(self._head(src, dst))
            except Exception:                             # noqa: BLE001
                pass
