"""One supervised serving replica: an in-process ServingEngine plus the
health state the fleet's supervision loop reads.

A replica is the unit of failure: the fleet steps each replica on its
own loop, and every step updates a HEARTBEAT (`last_progress`, on the
fleet's injectable clock). Supervision derives three unhealth signals
from it (fleet.py acts on them):

* **crash** — `ReplicaCrashed` (the `fleet.replica_crash` fault point)
  or `EngineFailure` escaping `step()`: the replica is dead on the
  spot, its snapshot is the live-migration payload;
* **stall** — the replica has work but its heartbeat has not advanced
  within `stall_timeout_s` (the `fleet.stream_stall` fault point models
  this: an armed stall makes `step()` return without stepping the
  engine OR touching the heartbeat);
* **consecutive failures** — `max_consecutive_failures` step exceptions
  of any other kind in a row (one success resets the count).

Everything here is host-side bookkeeping around the engine — replicas
stay in-process, so N replicas on CPU respect the one-TPU-process rule
and the whole fleet is deterministically testable.
"""
from __future__ import annotations

import enum
import time

from ...utils import faults
from .errors import ReplicaCrashed

__all__ = ["Replica", "ReplicaState", "FAULT_CRASH", "FAULT_STALL"]

# Fleet fault-injection points (ISSUE 7; utils/faults.py, table in
# SERVING.md). replica_crash fires at the TOP of Replica.step — an
# iteration boundary, so the engine's host state is consistent and the
# snapshot the fleet takes is exact. A payload of True crashes whichever
# replica hits the spec; a payload naming a replica crashes exactly that
# one (other replicas consume the firing and ignore it — arm with
# times=-1 for a targeted kill). An exc spec raises as-is and lands in
# the consecutive-failure supervision path instead. stream_stall makes
# the matching replica skip the engine step WITHOUT advancing its
# heartbeat — the stall detector's trigger; arm times=-1 + a name for a
# permanent targeted wedge. NOTE: a NAMED payload with finite `times`
# does NOT give a k-step targeted hiccup — non-target replicas consume
# firings they then ignore, so the target sees only ~k/R of them; use
# payload=True (whoever steps stalls) or a single-replica fleet for
# bounded hiccups.
FAULT_CRASH = faults.register_point("fleet.replica_crash")
FAULT_STALL = faults.register_point("fleet.stream_stall")


class ReplicaState(enum.Enum):
    HEALTHY = "healthy"        # in rotation: routed to and stepped
    DRAINED = "drained"        # deliberately emptied; out of rotation
    UNHEALTHY = "unhealthy"    # stall/failure threshold; evacuated
    DEAD = "dead"              # crashed; evacuated


class Replica:
    """One engine + its supervision-visible health state."""

    def __init__(self, name: str, engine, clock=None):
        self.name = str(name)
        self.engine = engine
        self.state = ReplicaState.HEALTHY
        self._clock = clock if clock is not None else time.monotonic
        self.steps_done = 0
        self.stalled_steps = 0
        self.consecutive_failures = 0
        self.last_progress = self._clock()

    # ---- router inputs ---------------------------------------------------
    @property
    def load(self) -> int:
        """In-flight + queued requests — the router's tiebreak."""
        s = self.engine.scheduler
        return s.num_in_flight + s.queue_depth

    def match_len(self, tokens, adapter=None) -> int:
        """Read-only longest-cached-prefix probe of THIS replica's radix
        tree (0 with the prefix cache off) — the router's primary
        score. Must never perturb the cache: `RadixCache.match_len`
        skips the LRU bump by contract. `adapter` namespaces the probe
        key exactly like the scheduler's match (ISSUE 15), so the score
        reflects what admission would actually reuse."""
        radix = self.engine.radix
        if radix is None:
            return 0
        key = adapter
        if adapter is not None:
            lora = getattr(self.engine, "lora", None)
            if lora is None or not lora.has(adapter):
                return 0           # nothing cached under an unheld adapter
            # the engine namespaces by (name, load-generation) — probe
            # with the same key admission would match with
            key = lora.namespace_of(adapter)
        from ..scheduler import adapter_prefix_key
        return radix.match_len(adapter_prefix_key(list(tokens), key))

    def has_adapter(self, adapter) -> bool:
        """True when this replica's registry currently holds `adapter`
        (trivially True for base-model traffic) — the adapter-affinity
        router's primary score (ISSUE 15)."""
        if adapter is None:
            return True
        lora = getattr(self.engine, "lora", None)
        return lora is not None and lora.has(adapter)

    # ---- the stepping loop body -----------------------------------------
    def _targets_me(self, payload) -> bool:
        return payload is True or payload == self.name

    def step(self):
        """One supervised engine iteration. Returns the engine's
        emitted [(request_id, token)]; raises whatever the engine (or
        an injected crash) raises — supervision policy lives in the
        fleet, not here."""
        crash = faults.fire(FAULT_CRASH)
        if crash is not None and self._targets_me(crash):
            raise ReplicaCrashed(f"injected crash of {self.name}")
        stall = faults.fire(FAULT_STALL)
        if stall is not None and self._targets_me(stall):
            # no engine step, no heartbeat: indistinguishable from a
            # wedged device loop to the stall detector — by design
            self.stalled_steps += 1
            return []
        emitted = self.engine.step()
        self.steps_done += 1
        self.consecutive_failures = 0
        self.last_progress = self._clock()
        return emitted

    def __repr__(self):
        return (f"Replica({self.name}, {self.state.value}, "
                f"load={self.load}, steps={self.steps_done})")
